"""cluster.*, lock/unlock, collection.* (reference `weed/shell/command_cluster_ps.go`,
`command_lock_unlock.go`, `command_collection_*.go`)."""

from __future__ import annotations

import json

from .env import CommandEnv, ShellError
from .registry import command, parse_flags


@command("lock", "acquire the exclusive admin lock on the master")
def cmd_lock(env: CommandEnv, args: list[str]) -> str:
    env.acquire_lock()
    return "lock acquired"


@command("unlock", "release the admin lock")
def cmd_unlock(env: CommandEnv, args: list[str]) -> str:
    env.release_lock()
    return "lock released"


@command("cluster.ps", "list cluster processes (masters, volume servers, filers)")
def cmd_cluster_ps(env: CommandEnv, args: list[str]) -> str:
    info = env.get(f"{env.master_url}/cluster/ps")
    lines = []
    for m in info.get("masters", []):
        lines.append(f"master {m['address']}" + (" leader" if m.get("isLeader") else ""))
    for v in info.get("volumeServers", []):
        lines.append(f"volumeServer {v['address']} dc={v['dataCenter']} rack={v['rack']}")
    for f in info.get("filers", []):
        lines.append(f"filer {f['address']}")
    for b in info.get("brokers", []):
        lines.append(f"broker {b['address']}")
    return "\n".join(lines)


def _scrape(url: str) -> list:
    """GET <url>/metrics -> parsed (name, labels, value) samples."""
    from seaweedfs_tpu.server.httpd import http_request
    from seaweedfs_tpu.stats import parse_exposition

    status, _, body = http_request("GET", f"{url}/metrics", timeout=10)
    if status != 200:
        raise IOError(f"GET {url}/metrics -> {status}")
    return parse_exposition(body.decode("utf-8", "replace"))


def _fmt_gb(n: float) -> str:
    return f"{n / 1024**3:.1f}GB"


def _fetch_cluster_telemetry(env: CommandEnv, timeout: float = 10):
    """The master's one-fetch cluster aggregate (stats/aggregate.py), or
    None when the aggregator isn't live (old master, no senders yet) —
    callers fall back to the N-endpoint fan-out."""
    try:
        out = env.get(f"{env.master_url}/debug/cluster/telemetry",
                      timeout=timeout)
    except Exception:
        return None
    if not isinstance(out, dict) or not out.get("senders"):
        return None
    return out


@command("cluster.check",
         "[-fail] [-capacityPct 90] [-include url,url] — health dashboard:"
         " replica/EC health, per-node disk + heartbeat freshness, volumes"
         " near the size cap, read-only volumes, fastlane"
         " native-vs-proxied hit rate, firing alerts (every discovered"
         " endpoint + -include'd gateways). -fail exits nonzero when any"
         " problem is found or any critical alert fires (scripting)")
def cmd_cluster_check(env: CommandEnv, args: list[str]) -> str:
    """Scrapes the PR-2 Prometheus series (`SeaweedFS_master_*` topology
    gauges off the master, `SeaweedFS_volume_fastlane_*` + disk gauges off
    every volume server) and renders one cluster-health dashboard — the
    in-situ view arXiv:1709.05365 argues storage tuning needs."""
    flags = parse_flags(args)
    fail_mode = "fail" in flags
    try:
        cap_pct = float(flags.get("capacityPct", 90))
    except ValueError:
        raise ShellError("usage: cluster.check [-fail] [-capacityPct n]")

    servers = env.servers()
    replicas = env.volume_replicas()
    problems: list[str] = []
    if not servers:
        problems.append("no volume servers registered")
    # replica counts straight from the topology snapshot (works even when
    # a node's /metrics is unreachable)
    underrep_seen: set[str] = set()
    for vid, holders in sorted(replicas.items()):
        rp_byte = holders[0].volumes[vid].get("replica_placement", 0)
        want = (rp_byte // 100) + (rp_byte // 10) % 10 + rp_byte % 10 + 1
        if len(holders) < want:
            underrep_seen.add(str(vid))
            problems.append(
                f"volume {vid}: {len(holders)}/{want} replicas "
                f"({', '.join(h.id for h in holders)})"
            )

    # firing alerts (PR-4): every node's /metrics carries the alert
    # engine's SeaweedFS_alerts_firing gauge; criticals are problems
    # (so -fail trips on an error storm or a stale heartbeat between
    # manual checks), warnings render informationally. Dedup by
    # (alert, severity): single-process clusters share one engine.
    firing_alerts: dict[str, str] = {}

    def note_alerts(samples: list) -> None:
        for name, labels, value in samples:
            if name == "SeaweedFS_alerts_firing" and value > 0:
                alert = labels.get("alert", "?")
                if firing_alerts.get(alert) != "critical":
                    firing_alerts[alert] = labels.get("severity", "warning")

    # --- master gauges: size limit, staleness, readonly, EC shard health ---
    size_limit = 30 * 1024**3
    stale_nodes: dict[str, float] = {}
    hb_age: dict[str, float] = {}
    free_slots: dict[str, float] = {}
    near_cap: list[str] = []
    readonly_volumes: list[str] = []
    try:
        msamples = _scrape(env.master_url)
    except Exception as e:
        msamples = []
        problems.append(f"master metrics unreachable: {e}")
    note_alerts(msamples)
    for name, labels, value in msamples:
        if name == "SeaweedFS_master_volume_size_limit_bytes":
            size_limit = value or size_limit
    for name, labels, value in msamples:
        node = labels.get("node", "")
        if name == "SeaweedFS_master_heartbeat_age_seconds":
            hb_age[node] = value
        elif name == "SeaweedFS_master_stale_heartbeats" and value > 0:
            stale_nodes[node] = hb_age.get(node, value)
        elif name == "SeaweedFS_master_free_slots":
            free_slots[node] = value
        elif name == "SeaweedFS_master_volume_size_bytes":
            if value >= size_limit * cap_pct / 100.0:
                near_cap.append(
                    f"volume {labels.get('volume')} on {node}: "
                    f"{_fmt_gb(value)} >= {cap_pct:g}% of "
                    f"{_fmt_gb(size_limit)} cap"
                )
        elif name == "SeaweedFS_master_volume_readonly" and value > 0:
            readonly_volumes.append(
                f"volume {labels.get('volume')} read-only on {node}"
            )
        elif name == "SeaweedFS_master_volumes_underreplicated" and value > 0:
            # skip vids the snapshot loop above already flagged — the gauge
            # catches what the snapshot can't (e.g. a layout whose last
            # holder vanished entirely), not the same fault twice
            if labels.get("volume") not in underrep_seen:
                problems.append(
                    f"volume {labels.get('volume')} under-replicated: "
                    f"{labels.get('have')}/{labels.get('want')} replicas"
                )
        elif name == "SeaweedFS_master_ec_missing_shards" and value > 0:
            problems.append(
                f"ec volume {labels.get('volume')}: {value:g} shard(s)"
                " without a live holder"
            )
    for node, age in sorted(stale_nodes.items()):
        problems.append(f"stale heartbeat from {node}: {age:.1f}s ago")
    problems.extend(near_cap)
    problems.extend(readonly_volumes)

    # --- per-node scrape: disk + fastlane hit rate -------------------------
    lines = [f"cluster.check @ {env.master_url}"]
    ec_count = sum(len(sv.ec_shards) for sv in servers)
    lines.append(
        f"topology: {len(servers)} volume servers, {len(replicas)} volumes,"
        f" {ec_count} ec volume holdings"
    )
    for sv in sorted(servers, key=lambda s: s.id):
        disk_used = disk_free = 0.0
        native = proxied = 0.0
        try:
            vsamples = _scrape(sv.http)
        except Exception as e:
            problems.append(f"{sv.id}: metrics unreachable ({e})")
            lines.append(f"node {sv.id} dc={sv.dc} rack={sv.rack}:"
                         " metrics unreachable")
            continue
        note_alerts(vsamples)
        for name, labels, value in vsamples:
            # the `server` label scopes series to this node when several
            # servers share one process registry (test clusters)
            if labels.get("server", sv.id) != sv.id:
                continue
            if name == "SeaweedFS_volume_disk_used_bytes":
                disk_used += value
            elif name == "SeaweedFS_volume_disk_free_bytes":
                disk_free += value
            elif name == "SeaweedFS_volume_fastlane_requests_total":
                native += value
            elif name == "SeaweedFS_volume_fastlane_proxied_total":
                proxied += value
        total = native + proxied
        rate = f"{100.0 * native / total:.1f}%" if total else "n/a"
        age = hb_age.get(sv.id)
        lines.append(
            f"node {sv.id} dc={sv.dc} rack={sv.rack}: "
            f"disk {_fmt_gb(disk_used)} used / {_fmt_gb(disk_free)} free, "
            f"free_slots={free_slots.get(sv.id, sv.free_slots()):g}, "
            f"heartbeat {f'{age:.1f}s ago' if age is not None else 'n/a'}, "
            f"fastlane native {rate}"
            f" ({native:g} native / {proxied:g} proxied)"
        )

    # alerts fire per PROCESS: in a multi-process cluster the filer/s3
    # engines are separate. When the master's telemetry aggregator is
    # live, ONE fetch covers them all — every sender's frame carries its
    # current alert edges, and the cluster-scope rules (merged SLO burn,
    # stale senders) only exist there. Fall back to fanning out
    # /debug/alerts across every discovered endpoint otherwise (the
    # filer's catch-all main port has no /metrics, but its debug routes
    # shadow file paths).
    tele = _fetch_cluster_telemetry(env)
    if tele is not None:
        senders = tele.get("senders") or {}
        stale = sorted(n for n, s in senders.items() if s.get("stale"))
        lines.append(
            f"telemetry: one-fetch master aggregate, {len(senders)}"
            f" sender(s)" + (f", {len(stale)} stale ({', '.join(stale)})"
                             if stale else ""))
        for name, info in (tele.get("alerts") or {}).items():
            if firing_alerts.get(name) != "critical":
                firing_alerts[name] = info.get("severity", "warning")
        for s in senders.values():
            for a in s.get("alerts") or ():
                name = a.get("alert", "?")
                if firing_alerts.get(name) != "critical":
                    firing_alerts[name] = a.get("severity", "warning")
    else:
        seen = {env.master_url} | {sv.http for sv in servers}
        for ep in sorted(_discover_endpoints(env, flags.get("include", ""),
                                             servers=servers) - seen):
            try:
                out = env.get(f"{ep}/debug/alerts", timeout=10)
            except Exception:
                continue  # an unreachable gateway must not sink the check
            for a in out.get("alerts", []):
                if a.get("firing"):
                    name = a.get("name", "?")
                    if firing_alerts.get(name) != "critical":
                        firing_alerts[name] = a.get("severity", "warning")

    for alert, sev in sorted(firing_alerts.items()):
        if sev == "critical":
            problems.append(
                f"alert {alert} firing [critical] (see /debug/alerts)"
            )
        else:
            lines.append(f"warning: alert {alert} firing (see /debug/alerts)")

    if problems:
        lines.append(f"{len(problems)} problem(s):")
        lines.extend("  " + p for p in problems)
        report = "\n".join(lines)
        if fail_mode:
            raise ShellError(report)
        return report
    lines.append("cluster is healthy")
    return "\n".join(lines)


@command("collection.list", "list collections")
def cmd_collection_list(env: CommandEnv, args: list[str]) -> str:
    info = env.get(f"{env.master_url}/col/list")
    return "\n".join(
        f"collection {c['name'] or '(default)'}: {c['volumeCount']} volumes"
        for c in info["collections"]
    )


@command("collection.delete", "-collection <name> — delete all its volumes",
         needs_lock=True)
def cmd_collection_delete(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    name = flags.get("collection", flags.get("", ""))
    out = env.post(f"{env.master_url}/col/delete?collection={name}")
    return f"deleted {out['deleted']} volumes of collection {name!r}"


@command("volume.list", "list volumes per server (ref command_volume_list.go)")
def cmd_volume_list(env: CommandEnv, args: list[str]) -> str:
    lines = []
    for sv in env.servers():
        lines.append(
            f"{sv.id} dc={sv.dc} rack={sv.rack} "
            f"volumes={len(sv.volumes)}/{sv.max_volume_count}"
        )
        for vid, v in sorted(sv.volumes.items()):
            rp = v.get("replica_placement", 0)
            lines.append(
                f"  volume {vid} collection={v.get('collection', '') or '(default)'} "
                f"size={v.get('size', 0)} files={v.get('file_count', 0)} "
                f"deleted={v.get('delete_count', 0)} rp={rp:03d} "
                f"{'readonly' if v.get('read_only') else 'writable'}"
            )
        for vid, shards in sorted(sv.ec_shards.items()):
            lines.append(f"  ec volume {vid} shards={shards}")
    return "\n".join(lines)


@command("volume.status", "-volumeId <n> — show one volume's replicas + stats")
def cmd_volume_status(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    vid = int(flags.get("volumeId", flags.get("", 0)))
    out = []
    for sv in env.servers():
        if vid in sv.volumes:
            out.append(json.dumps({"server": sv.id, **sv.volumes[vid]}))
    return "\n".join(out) if out else f"volume {vid} not found"


def _discover_endpoints(env: CommandEnv, include: str = "",
                        servers: list | None = None) -> set[str]:
    """Every /debug-capable node the shell can see: the master, each
    volume server in the topology, registered filers, plus -include'd
    urls (s3 gateways don't register with the master). Pass `servers` to
    reuse an already-fetched topology snapshot instead of re-fetching."""
    endpoints = {env.master_url}
    for extra in include.split(","):
        extra = extra.strip().rstrip("/")
        if extra:
            if not extra.startswith(("http://", "https://")):
                extra = "http://" + extra
            endpoints.add(extra)
    try:
        for sv in (env.servers() if servers is None else servers):
            endpoints.add(sv.http)
    except Exception:
        pass
    try:
        ps = env.get(f"{env.master_url}/cluster/ps")
        for f in ps.get("filers", []):
            endpoints.add(f["address"])
    except Exception:
        pass
    if env.filer_url:
        endpoints.add(env.filer_url)
    return endpoints


def _fetch_concurrently(endpoints, fetch) -> None:
    """Run fetch(ep) for every endpoint on daemon threads and join. The
    shared fan-out under cluster.profile / cluster.top: each fetch
    swallows its own failures (an unreachable node must not sink the
    cluster view) and the wall-clock window stays simultaneous."""
    import threading as _threading

    threads = [
        _threading.Thread(target=fetch, args=(ep,), daemon=True)
        for ep in sorted(endpoints)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


@command("cluster.trace",
         "[-limit n] [-minMs n] [-include url,url] — fetch /debug/traces"
         " from master + volume servers + filers (+ -include'd endpoints,"
         " e.g. s3 gateways) and render merged span trees")
def cmd_cluster_trace(env: CommandEnv, args: list[str]) -> str:
    """Cluster-wide trace view: every node keeps its own span ring; this
    merges them by trace id into one tree per request (the multi-process
    counterpart of the single-process ring in stats/trace.py). S3 gateways
    don't register with the master, so pass them via -include to get the
    [s3] root spans in a multi-process cluster."""
    import math

    flags = parse_flags(args)
    try:
        limit = int(flags.get("limit", 10))
        min_ms = float(flags.get("minMs", 0))
        if not math.isfinite(min_ms):
            raise ValueError(min_ms)
    except ValueError:
        raise ShellError(
            "usage: cluster.trace [-limit n] [-minMs n] [-include url,url]"
        )

    endpoints = _discover_endpoints(env, flags.get("include", ""))

    # trace_id -> span_id -> span; single-process clusters share one ring,
    # so keying by span id dedups identical copies from every endpoint
    merged: dict[str, dict[str, dict]] = {}
    reached = []
    # fetch deep with min_ms=0: node-side min_ms would drop a node's
    # fast child spans out of a slow cross-node trace, and a shallow
    # fetch would hide older slow traces behind recent fast ones — the
    # -minMs filter applies AFTER the merge, on whole-trace duration
    per_node = max(limit * 10, 100)
    for ep in sorted(endpoints):
        try:
            out = env.get(
                f"{ep}/debug/traces?limit={per_node}&min_ms=0",
                timeout=10,
            )
        except Exception:
            continue
        reached.append(ep)
        for tr in out.get("traces", []):
            slot = merged.setdefault(tr["trace_id"], {})
            for sp in tr["spans"]:
                slot[sp["span_id"]] = sp
    if not reached:
        raise ShellError("no /debug/traces endpoint reachable")

    def render_tree(spans: list[dict]) -> list[str]:
        ids = {s["span_id"] for s in spans}
        children: dict[str, list[dict]] = {}
        roots = []
        for s in sorted(spans, key=lambda s: s["start"]):
            if s["parent_id"] in ids:
                children.setdefault(s["parent_id"], []).append(s)
            else:
                roots.append(s)
        lines: list[str] = []

        def walk(s: dict, depth: int) -> None:
            lines.append(
                f"{'  ' * depth}[{s.get('role') or '-'}] {s['name']} "
                f"{s['duration_ms']}ms {s['status']}"
            )
            for c in children.get(s["span_id"], []):
                walk(c, depth + 1)

        for r in roots:
            walk(r, 1)
        return lines

    rows = []
    for trace_id, by_id in merged.items():
        spans = list(by_id.values())
        start = min(s["start"] for s in spans)
        end = max(s["start"] + s["duration_ms"] / 1000.0 for s in spans)
        rows.append((start, (end - start) * 1000.0, trace_id, spans))
    rows.sort(reverse=True)
    out_lines = [f"merged traces from {len(reached)} endpoint(s)"]
    shown = 0
    for start, dur_ms, trace_id, spans in rows:
        if dur_ms < min_ms:
            continue
        if shown >= limit:
            break
        shown += 1
        roles = sorted({s["role"] for s in spans if s.get("role")})
        out_lines.append(
            f"trace {trace_id} {dur_ms:.1f}ms roles={','.join(roles)}"
        )
        out_lines.extend(render_tree(spans))
    if shown == 0:
        out_lines.append("no traces recorded (min_ms too high?)")
    return "\n".join(out_lines)


@command("cluster.profile",
         "[-seconds n] [-hz n] [-include url,url] [-out path] — sample every"
         " node's Python stacks concurrently (/debug/pprof/profile) and"
         " merge them, role-prefixed, into one flamegraph-ready"
         " collapsed-stack output")
def cmd_cluster_profile(env: CommandEnv, args: list[str]) -> str:
    """Cluster-wide CPU attribution: every reachable node samples itself
    for the same window (the fetches run concurrently — the window is
    wall-clock, so serial fetches would profile different moments), and
    the collapsed stacks merge under a per-role root (`master;...`,
    `volume;...`) so one flamegraph splits by role first. Several roles
    sharing one interpreter dedup by process identity — their stacks merge
    once, under a combined `role+role;` root, instead of counting the same
    process once per role. Feed the -out file to flamegraph.pl or
    speedscope as-is."""
    import math

    flags = parse_flags(args)
    try:
        seconds = float(flags.get("seconds", 2))
        hz = int(flags.get("hz", 100))
        if not math.isfinite(seconds) or seconds <= 0:
            raise ValueError(seconds)
    except ValueError:
        raise ShellError(
            "usage: cluster.profile [-seconds n] [-hz n] [-include url,url]"
            " [-out path]"
        )

    endpoints = _discover_endpoints(env, flags.get("include", ""))
    results: dict[str, dict] = {}

    def fetch(ep: str) -> None:
        try:
            results[ep] = env.get(
                f"{ep}/debug/pprof/profile?seconds={seconds:g}&hz={hz}"
                "&format=json",
                timeout=seconds + 30,
            )
        except Exception:
            pass

    _fetch_concurrently(endpoints, fetch)
    if not results:
        raise ShellError("no /debug/pprof/profile endpoint reachable")

    from seaweedfs_tpu.stats import profiler as prof_mod

    # group endpoints by process identity: in a single-process cluster
    # every role's endpoint sampled the SAME interpreter, and merging each
    # copy would multiply sample counts and attribute every role's threads
    # to every role (cluster.trace's span-id dedup, process-level)
    by_proc: dict[str, list[str]] = {}
    for ep in sorted(results):
        by_proc.setdefault(results[ep].get("proc") or ep, []).append(ep)
    merged: dict[str, int] = {}
    total_samples = 0
    for token in sorted(by_proc):
        eps = by_proc[token]
        roles = sorted({results[ep].get("role") or "node" for ep in eps})
        best = max(eps, key=lambda ep: int(results[ep].get("samples", 0)))
        out = results[best]
        prof_mod.merge_collapsed(
            merged, out.get("stacks", {}), prefix="+".join(roles)
        )
        total_samples += int(out.get("samples", 0))
    body = prof_mod.render_collapsed(merged)
    header = (
        f"profiled {len(results)}/{len(endpoints)} endpoint(s)"
        f" ({len(by_proc)} process(es)) for"
        f" {seconds:g}s @ {hz}Hz: {total_samples} samples,"
        f" {len(merged)} distinct stacks"
    )
    if "out" in flags:
        with open(flags["out"], "w") as f:
            f.write(body + "\n")
        return header + f"\ncollapsed stacks written to {flags['out']}"
    return header + "\n" + body


def _fmt_bytes_rate(n: float | None) -> str:
    if not n:
        return "-"
    for unit, div in (("GB/s", 1e9), ("MB/s", 1e6), ("KB/s", 1e3)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n:.0f}B/s"


def _fmt_uptime(sec: float | None) -> str:
    if sec is None or sec < 0:
        return "-"
    sec = int(sec)
    if sec >= 86400:
        return f"{sec // 86400}d{(sec % 86400) // 3600}h"
    if sec >= 3600:
        return f"{sec // 3600}h{(sec % 3600) // 60}m"
    if sec >= 60:
        return f"{sec // 60}m{sec % 60}s"
    return f"{sec}s"


@command("cluster.top",
         "[-once] [-interval 2] [-window 60] [-count n] [-include url,url]"
         " [-spool dir] [-snapshot file] — live dashboard: per-role"
         " request rates, 5xx%, p99, bytes/s, front-door native ratio,"
         " uptime and firing alerts from every node's history ring. -once"
         " renders a single frame and returns; -spool appends a dead"
         " process's rate history from its telemetry spool; -snapshot"
         " dumps one frame's cluster state as JSON")
def cmd_cluster_top(env: CommandEnv, args: list[str]) -> str:
    """The rates-over-time view cluster.check can't give: every reachable
    node serves its self-scraped history ring (/debug/metrics/history)
    and alert state (/debug/alerts); this fetches all of them
    CONCURRENTLY, dedups endpoints sharing one process (single-process
    clusters expose every role's series at every port), aggregates
    per-role request/error/byte rates, interpolates p99 from windowed
    bucket rates, and renders one table plus the firing alerts. Without
    -once it redraws every -interval seconds until -count frames (or
    Ctrl-C)."""
    import math
    import time as _time

    from seaweedfs_tpu.stats.history import quantile_from_bucket_rates

    flags = parse_flags(args)
    try:
        interval = float(flags.get("interval", 2.0))
        window = float(flags.get("window", 60.0))
        count = int(flags.get("count", 0))
        if not math.isfinite(interval) or interval <= 0:
            raise ValueError(interval)
        if not math.isfinite(window) or window <= 0:
            raise ValueError(window)
    except ValueError:
        raise ShellError(
            "usage: cluster.top [-once] [-interval n] [-window n]"
            " [-count n] [-include url,url]"
        )
    # -snapshot implies -once: the JSON artifact is one frame's state
    once = "once" in flags or "snapshot" in flags
    spool_dir = flags.get("spool", "").strip()

    # endpoint discovery is cached ACROSS watch frames: re-walking
    # /dir/status + /cluster/ps every redraw turns a 30-node watch
    # session into a topology-hammering loop. The cache is invalidated
    # only when an endpoint fails to answer, so a node that moved (new
    # port, restart) heals on the next frame.
    cache: dict = {"endpoints": None}

    def frame() -> str:
        endpoints = cache["endpoints"]
        if not endpoints:
            endpoints = cache["endpoints"] = _discover_endpoints(
                env, flags.get("include", ""))
        hist_res: dict[str, dict] = {}
        alert_res: dict[str, dict] = {}

        def fetch(ep: str) -> None:
            try:  # samples=0: rates + last values only, no raw points
                hist_res[ep] = env.get(
                    f"{ep}/debug/metrics/history?window={window:g}&samples=0",
                    timeout=10,
                )
            except Exception:
                return  # an unreachable node must not sink the view
            try:
                alert_res[ep] = env.get(
                    f"{ep}/debug/alerts?window={window:g}", timeout=10
                )
            except Exception:
                pass

        _fetch_concurrently(endpoints, fetch)
        if len(hist_res) < len(endpoints):
            cache["endpoints"] = None  # refetch topology next frame
        if not hist_res and not spool_dir:
            raise ShellError("no /debug/metrics/history endpoint reachable")

        # cluster-rollup header: the master aggregate's merged view
        # (global rates, top tenants WITH error bars, burning cluster
        # SLOs) — one extra fetch, not one per node (skipped in
        # spool-only post-mortem mode: the cluster is dead)
        tele = _fetch_cluster_telemetry(env) if hist_res else None

        # one representative endpoint per process (cluster.profile's dedup)
        by_proc: dict[str, str] = {}
        for ep in sorted(hist_res):
            by_proc.setdefault(hist_res[ep].get("proc") or ep, ep)

        now = _time.time()
        roles: dict[str, dict] = {}
        # tenants/heat ride the SAME history fetch: the usage and heat
        # collectors export into each process's ring, so no extra RPCs
        tenants: dict[str, dict] = {}
        heat_vols: dict[tuple, float] = {}
        days_full: dict[tuple, float] = {}

        def row(role: str) -> dict:
            return roles.setdefault(role, {
                "req_s": 0.0, "err_s": 0.0, "bytes_s": 0.0,
                "fr_native": 0.0, "fr_fb": 0.0,
                "buckets": {}, "uptime": None, "version": None,
            })

        def tenant(coll: str) -> dict:
            return tenants.setdefault(coll, {
                "req_s": 0.0, "in_s": 0.0, "out_s": 0.0, "err_s": 0.0,
            })

        # qos admission plane (qos/admission.py): per-class admit/queue/
        # shed rates + the tenants being shed, off the same history fetch
        qos_cls: dict[str, dict] = {}
        qos_shed_colls: dict[str, float] = {}

        def qrow(cls: str) -> dict:
            return qos_cls.setdefault(cls, {
                "admit_s": 0.0, "queue_s": 0.0, "shed_s": 0.0,
            })

        for token in sorted(by_proc):
            series = hist_res[by_proc[token]].get("series", [])
            start_ts = None
            proc_roles: set[str] = set()
            version = None
            for s in series:
                fam = s.get("family", "")
                labels = s.get("labels", {})
                rate = s.get("rate")
                if fam == "SeaweedFS_http_request_total" and rate:
                    r = row(labels.get("role", "?"))
                    r["req_s"] += rate
                    if labels.get("code", "").startswith("5"):
                        r["err_s"] += rate
                elif fam == "SeaweedFS_http_request_seconds_bucket" and rate:
                    le = labels.get("le", "")
                    bound = float("inf") if le == "+Inf" else float(le)
                    b = row(labels.get("role", "?"))["buckets"]
                    b[bound] = b.get(bound, 0.0) + rate
                elif fam == "SeaweedFS_volume_fastlane_bytes_total" and rate:
                    row("volume")["bytes_s"] += rate
                elif fam in ("SeaweedFS_filer_fastlane_native_total",
                             "SeaweedFS_s3_fastlane_native_total") and rate:
                    role = "filer" if "filer" in fam else "s3"
                    row(role)["fr_native"] += rate
                elif fam in ("SeaweedFS_filer_fastlane_fallback_total",
                             "SeaweedFS_s3_fastlane_fallback_total") and rate:
                    role = "filer" if "filer" in fam else "s3"
                    row(role)["fr_fb"] += rate
                elif fam == "SeaweedFS_usage_requests_total" and rate:
                    tenant(labels.get("collection", "?"))["req_s"] += rate
                elif fam == "SeaweedFS_usage_bytes_in_total" and rate:
                    tenant(labels.get("collection", "?"))["in_s"] += rate
                elif fam == "SeaweedFS_usage_bytes_out_total" and rate:
                    tenant(labels.get("collection", "?"))["out_s"] += rate
                elif fam == "SeaweedFS_usage_errors_total" and rate:
                    tenant(labels.get("collection", "?"))["err_s"] += rate
                elif fam == "SeaweedFS_qos_admitted_total" and rate:
                    qrow(labels.get("class", "?"))["admit_s"] += rate
                elif fam == "SeaweedFS_qos_queued_total" and rate:
                    qrow(labels.get("class", "?"))["queue_s"] += rate
                elif fam == "SeaweedFS_qos_shed_total" and rate:
                    qrow(labels.get("class", "?"))["shed_s"] += rate
                    coll = labels.get("collection", "?")
                    qos_shed_colls[coll] = \
                        qos_shed_colls.get(coll, 0.0) + rate
                elif fam == "SeaweedFS_volume_heat_score":
                    key = (labels.get("server", "?"),
                           labels.get("volume", "?"))
                    heat_vols[key] = max(heat_vols.get(key, 0.0),
                                         s.get("last") or 0.0)
                elif fam == "SeaweedFS_node_days_to_full":
                    key = (labels.get("node", "?"), labels.get("dir", "?"))
                    v = s.get("last")
                    if v is not None:
                        days_full[key] = min(days_full.get(key, v), v)
                elif fam == "SeaweedFS_process_start_time_seconds":
                    start_ts = s.get("last")
                elif fam == "SeaweedFS_build_info":
                    proc_roles.add(labels.get("role", "?"))
                    version = labels.get("version")
            for role in proc_roles:
                r = row(role)
                if start_ts:
                    up = now - start_ts
                    r["uptime"] = max(r["uptime"] or 0.0, up)
                if version and not r["version"]:
                    r["version"] = version

        firing: dict[str, dict] = {}
        slo_rows: dict[str, dict] = {}
        seen_procs: set[str] = set()
        for ep in sorted(alert_res):
            token = alert_res[ep].get("proc") or ep
            if token in seen_procs:
                continue
            seen_procs.add(token)
            for a in alert_res[ep].get("alerts", []):
                if a.get("firing"):
                    firing.setdefault(a["name"], a)
            # per-slo burn: the worst process's reading wins (one slow
            # filer is the story, not the fleet average)
            for name, s in (alert_res[ep].get("slos") or {}).items():
                cur = slo_rows.setdefault(name, dict(s))
                for k in ("burn_fast", "burn_slow"):
                    v = s.get(k)
                    if v is not None and (cur.get(k) is None
                                          or v > cur[k]):
                        cur[k] = v

        # p99 exemplars (histogram bucket -> trace id): per role, the
        # slowest sample's trace INSIDE the window — the p99 row's "go
        # look" link. Exemplars never expire server-side (freshest per
        # bucket), so without the ts filter one old multi-second request
        # would pin the column to a long-evicted trace forever.
        exemplar: dict[str, dict] = {}
        cutoff = _time.time() - window
        for token in sorted(by_proc):
            ex = hist_res[by_proc[token]].get("exemplars") or {}
            for e in ex.get("SeaweedFS_http_request_seconds", []):
                if e.get("ts", 0) < cutoff:
                    continue
                role = e.get("labels", {}).get("role", "?")
                cur = exemplar.get(role)
                if cur is None or e.get("value", 0) > cur.get("value", 0):
                    exemplar[role] = e

        # -snapshot rides the render pass: the same numbers the table
        # shows, pre-formatting, so the JSON artifact and the terminal
        # frame can never disagree
        snap: dict = {
            "ts": now,
            "master": env.master_url,
            "window": window,
            "processes": len(by_proc),
            "endpoints": len(hist_res),
            "cluster_telemetry": tele,
            "roles": {},
            "tenants": tenants,
            "heat": [
                {"server": srv, "volume": vid, "score": score}
                for (srv, vid), score in sorted(heat_vols.items(),
                                                key=lambda kv: -kv[1])
            ],
            "days_to_full": [
                {"node": node, "dir": d, "days": days}
                for (node, d), days in sorted(days_full.items(),
                                              key=lambda kv: kv[1])
            ],
            "slos": slo_rows,
            "alerts_firing": firing,
            "qos": {
                "classes": qos_cls,
                "top_shed": [
                    {"collection": coll, "shed_s": r}
                    for coll, r in sorted(qos_shed_colls.items(),
                                          key=lambda kv: -kv[1])
                ],
            },
        }
        cache["snap"] = snap
        lines = [
            f"cluster.top @ {env.master_url}  window={window:g}s  "
            f"{len(by_proc)} process(es), {len(hist_res)} endpoint(s)",
        ]
        if tele is not None:
            rates = tele.get("rates") or {}
            total_req = sum(r.get("req_rate", 0.0) for r in rates.values())
            total_err = sum(r.get("err_rate", 0.0) for r in rates.values())
            err_pct = 100.0 * total_err / total_req if total_req else 0.0
            senders = tele.get("senders") or {}
            n_stale = sum(1 for s in senders.values() if s.get("stale"))
            bits = [
                f"cluster: {total_req:.1f} req/s  5xx {err_pct:.2f}%  "
                f"senders {len(senders)}"
                + (f" ({n_stale} stale)" if n_stale else "")
            ]
            top3 = (tele.get("usage") or {}).get("tenants") or []
            if top3:
                bits.append("top tenants: " + ", ".join(
                    f"{t['collection']}"
                    f" {t.get('requests', 0):.0f}"
                    f"±{t.get('requests_err', 0):.0f}"
                    for t in top3[:3]))
            burning = sorted(
                name for name in (tele.get("alerts") or {})
                if name.startswith("cluster_slo_burn"))
            bits.append("burning: " + (", ".join(burning) or "none"))
            lines.append("  ".join(bits))
        lines.append(
            f"{'role':<10} {'req/s':>9} {'5xx%':>7} {'p99 ms':>9}"
            f" {'bytes/s':>10} {'front%':>7} {'uptime':>8}  version"
            f"  p99-trace"
        )
        for role in sorted(roles):
            r = roles[role]
            qflags: dict = {}
            p99 = quantile_from_bucket_rates(r["buckets"], 0.99,
                                             flags=qflags)
            # inf_mass: the p99 fell in the +Inf bucket — the clamped
            # value is a lower bound, rendered ">x", never "=x"
            if p99 is None:
                p99_txt = "n/a"
            elif qflags.get("inf_mass"):
                p99_txt = f">{p99 * 1e3:.0f}"
            else:
                p99_txt = f"{p99 * 1e3:.2f}"
            err_pct = (
                f"{100.0 * r['err_s'] / r['req_s']:.1f}" if r["req_s"] else "-"
            )
            # front-door ratio: share of data-plane-shaped requests the
            # filer/S3 engine served without touching Python
            fr_total = r["fr_native"] + r["fr_fb"]
            front = (
                f"{100.0 * r['fr_native'] / fr_total:.1f}" if fr_total else "-"
            )
            ex = exemplar.get(role)
            snap["roles"][role] = {
                "req_s": r["req_s"], "err_s": r["err_s"],
                "bytes_s": r["bytes_s"],
                "p99_s": p99,
                "p99_lower_bound": bool(qflags.get("inf_mass")),
                "front_native": r["fr_native"], "front_fallback": r["fr_fb"],
                "uptime_s": r["uptime"], "version": r["version"],
                "p99_trace": ex["trace_id"] if ex else None,
            }
            lines.append(
                f"{role:<10} {r['req_s']:>9.1f} {err_pct:>7}"
                f" {p99_txt:>9}"
                f" {_fmt_bytes_rate(r['bytes_s']):>10}"
                f" {front:>7}"
                f" {_fmt_uptime(r['uptime']):>8}  {r['version'] or '-'}"
                f"  {ex['trace_id'] if ex else '-'}"
            )
        if not roles:
            lines.append("(no rates yet — the history ring needs two"
                         " scrapes inside the window)")
        if tenants:
            top5 = sorted(tenants.items(),
                          key=lambda kv: -kv[1]["req_s"])[:5]
            lines.append("tenants (top by req/s):")
            for coll, t in top5:
                lines.append(
                    f"  {coll:<20} {t['req_s']:>8.1f}/s"
                    f"  in={_fmt_bytes_rate(t['in_s'])}"
                    f"  out={_fmt_bytes_rate(t['out_s'])}"
                    + (f"  err={t['err_s']:.2f}/s" if t["err_s"] else "")
                )
        if qos_cls:
            from seaweedfs_tpu.qos import PRIORITY_CLASSES as _QOS_CLASSES

            lines.append("qos (admitted/queued/shed per class):")
            order = [c for c in _QOS_CLASSES if c in qos_cls] + sorted(
                c for c in qos_cls if c not in _QOS_CLASSES)
            for cls in order:
                q = qos_cls[cls]
                lines.append(
                    f"  {cls:<12} {q['admit_s']:>8.1f}/s"
                    f"  queued={q['queue_s']:.2f}/s"
                    f"  shed={q['shed_s']:.2f}/s")
            top_shed = sorted(qos_shed_colls.items(),
                              key=lambda kv: -kv[1])[:3]
            if top_shed:
                lines.append("  top shed tenants: " + ", ".join(
                    f"{coll} {r:.2f}/s" for coll, r in top_shed))
        if heat_vols or days_full:
            bits = []
            if heat_vols:
                hot = sorted(heat_vols.items(), key=lambda kv: -kv[1])[:3]
                bits.append("hottest " + ", ".join(
                    f"{srv} v{vid}={score:.1f}"
                    for (srv, vid), score in hot))
            if days_full:
                soon = sorted(days_full.items(), key=lambda kv: kv[1])[:3]
                bits.append("days-to-full " + ", ".join(
                    f"{node} {d}={days:.1f}d"
                    for (node, d), days in soon))
            lines.append("heat: " + "; ".join(bits))
        if slo_rows:
            lines.append("slo error-budget burn (x sustainable;"
                         " fast/slow window):")
            for name in sorted(slo_rows):
                s = slo_rows[name]
                fast, slow = s.get("burn_fast"), s.get("burn_slow")
                obj = s.get("objective", 0.0)
                lines.append(
                    f"  {name:<24} obj={obj:.3%}"
                    f"  fast={'-' if fast is None else f'{fast:.2f}x'}"
                    f"  slow={'-' if slow is None else f'{slow:.2f}x'}"
                )
        if firing:
            lines.append(f"{len(firing)} alert(s) firing:")
            for name in sorted(firing):
                a = firing[name]
                lines.append(
                    f"  [{a.get('severity', '?')}] {name}:"
                    f" {a.get('detail', '')}"
                )
        else:
            lines.append("no alerts firing")
        if spool_dir:
            # post-mortem: the dead process's rate history, straight off
            # its telemetry spool's segment files — no live endpoint
            from seaweedfs_tpu.stats import store as store_mod

            try:
                info = store_mod.spool_info(spool_dir)
                series = store_mod.read_series(
                    spool_dir, "SeaweedFS_http_request_total",
                    tiers=("raw", "1m"))
            except OSError as e:
                raise ShellError(f"spool {spool_dir}: {e}")
            total = sum(t.get("bytes", 0) for t in info.values())
            rates: dict[str, float] = {}
            t_lo = t_hi = None
            for (_fam, labels), pts in sorted(series.items()):
                if len(pts) < 2:
                    continue
                (ta, va), (tb, vb) = pts[0], pts[-1]
                t_lo = ta if t_lo is None else min(t_lo, ta)
                t_hi = tb if t_hi is None else max(t_hi, tb)
                if tb > ta and vb >= va:  # counter reset inside: skip
                    role = dict(labels).get("role", "?")
                    rates[role] = rates.get(role, 0.0) \
                        + (vb - va) / (tb - ta)
            lines.append(
                f"post-mortem spool {spool_dir}: " + "  ".join(
                    f"{t}={info[t]['bytes']}B/{info[t]['segments']}seg"
                    for t, _, _ in store_mod.TIERS)
                + f"  total={total}B")
            if t_lo is not None:
                lines.append(
                    f"  request counters cover {t_hi - t_lo:.0f}s;"
                    " req/s by role: "
                    + (", ".join(f"{role}={v:.2f}"
                                 for role, v in sorted(rates.items()))
                       or "n/a"))
            else:
                lines.append("  no request-counter history in spool")
            snap["spool"] = {
                "dir": spool_dir, "tiers": info, "total_bytes": total,
                "req_rates": rates,
                "covers_seconds": (t_hi - t_lo) if t_lo is not None
                else 0.0,
            }
        return "\n".join(lines)

    if once:
        body = frame()
        if "snapshot" in flags:
            import json as _json

            with open(flags["snapshot"], "w") as f:
                _json.dump(cache["snap"], f, indent=2, sort_keys=True,
                           default=str)
                f.write("\n")
            return body + f"\nsnapshot json written to {flags['snapshot']}"
        return body
    shown = 0
    try:
        while True:
            # clear + home, like top(1); endpoints come from the cached
            # discovery (refreshed only after a failed fetch). A transient
            # fetch failure (master restarting, network blip) renders as a
            # frame and the watch keeps going — only Ctrl-C (or -count)
            # ends it, like top(1).
            try:
                body = frame()
            except ShellError as e:
                body = f"cluster.top @ {env.master_url}: {e} (retrying)"
            print("\x1b[2J\x1b[H" + body, flush=True)
            shown += 1
            if count > 0 and shown >= count:
                break
            _time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return f"cluster.top stopped after {shown} frame(s)"


@command("cluster.heat",
         "[-n 10] [-include url,url] [-out path] — the cluster's thermal"
         " picture: top-K tenants from the bounded usage sketch (with its"
         " error bound), hottest/coldest volumes by heat score, collection"
         "/node rollups, per-node days-to-full forecasts")
def cmd_cluster_heat(env: CommandEnv, args: list[str]) -> str:
    """Who is using the cluster and where the heat is: every node serves
    its bounded-cardinality tenant sketch (/debug/usage) and heat/forecast
    view (/debug/heat); this fetches all of them concurrently, dedups
    endpoints sharing a process, sums tenant counts across processes
    (each process sketches its own traffic), and renders one report.
    Sketch counts are approximate above the exported error bound — the
    header says by how much."""
    flags = parse_flags(args)
    try:
        n = int(flags.get("n", 10))
        if n < 1:
            raise ValueError(n)
    except ValueError:
        raise ShellError(
            "usage: cluster.heat [-n k] [-include url,url] [-out path]")

    endpoints = _discover_endpoints(env, flags.get("include", ""))
    usage_res: dict[str, dict] = {}
    heat_res: dict[str, dict] = {}

    def fetch(ep: str) -> None:
        try:
            usage_res[ep] = env.get(f"{ep}/debug/usage", timeout=10)
        except Exception:
            return  # an unreachable node must not sink the view
        try:
            heat_res[ep] = env.get(f"{ep}/debug/heat", timeout=10)
        except Exception:
            pass

    _fetch_concurrently(endpoints, fetch)
    if not usage_res:
        raise ShellError("no /debug/usage endpoint reachable")

    dims = ("requests", "bytes_in", "bytes_out", "errors")
    tenants: dict[str, dict] = {}
    other = {d: 0.0 for d in dims}
    error_bound, k, evictions = 0.0, None, 0
    seen: set[str] = set()
    for ep in sorted(usage_res):
        out = usage_res[ep]
        token = out.get("proc") or ep
        if token in seen:
            continue
        seen.add(token)
        for row in out.get("tenants", []):
            t = tenants.setdefault(
                row.get("collection", "?"),
                {d: 0.0 for d in dims} | {d + "_err": 0.0 for d in dims})
            for d in dims:
                t[d] += float(row.get(d, 0) or 0)
                t[d + "_err"] += float(row.get(d + "_err", 0) or 0)
        for d, v in (out.get("other") or {}).items():
            if d in other:
                other[d] += float(v or 0)
        error_bound = max(error_bound, float(out.get("error_bound") or 0))
        evictions += int(out.get("evictions") or 0)
        k = out.get("k", k)

    vols: dict[tuple, dict] = {}
    forecast: dict[tuple, float] = {}
    coll_scores: dict[str, float] = {}
    node_scores: dict[str, float] = {}
    seen_heat: set[str] = set()
    for ep in sorted(heat_res):
        out = heat_res[ep]
        token = out.get("proc") or ep
        if token in seen_heat:
            continue
        seen_heat.add(token)
        for v in out.get("volumes", []):
            key = (v.get("server", "?"), str(v.get("volume", "?")))
            cur = vols.get(key)
            if cur is None or v.get("score", 0) > cur.get("score", 0):
                vols[key] = v
        for f in out.get("forecast", []):
            key = (f.get("node", "?"), f.get("dir", "?"))
            d = float(f.get("days_to_full", 0) or 0)
            forecast[key] = min(forecast.get(key, d), d)
        for c in out.get("collections", []):
            name = c.get("collection", "?")
            coll_scores[name] = max(coll_scores.get(name, 0.0),
                                    float(c.get("score", 0) or 0))
        for nd in out.get("nodes", []):
            name = nd.get("node", "?")
            node_scores[name] = max(node_scores.get(name, 0.0),
                                    float(nd.get("score", 0) or 0))

    lines = [
        f"cluster.heat @ {env.master_url}  {len(seen)} process(es),"
        f" {len(usage_res)} endpoint(s)"
        + (f"  sketch K={k}" if k is not None else "")
        + f"  error bound <= {error_bound:g}"
        + (f"  ({evictions} eviction(s) into _other)" if evictions else ""),
        f"tenants (top {n} by requests; counts approximate above the"
        f" error bound):",
        f"  {'collection':<20} {'requests':>12} {'bytes in':>12}"
        f" {'bytes out':>12} {'errors':>8}",
    ]
    top = sorted(tenants.items(), key=lambda kv: -kv[1]["requests"])[:n]
    for coll, t in top:
        err = t["requests_err"]
        req = f"{t['requests']:g}" + (f"±{err:g}" if err else "")
        lines.append(
            f"  {coll:<20} {req:>12} {t['bytes_in']:>12g}"
            f" {t['bytes_out']:>12g} {t['errors']:>8g}")
    if any(other.values()):
        lines.append(
            f"  {'_other':<20} {other['requests']:>12g}"
            f" {other['bytes_in']:>12g} {other['bytes_out']:>12g}"
            f" {other['errors']:>8g}")
    if not tenants:
        lines.append("  (no tenant traffic accounted yet)")

    if vols:
        ranked = sorted(vols.values(), key=lambda v: -v.get("score", 0))
        lines.append(f"hottest volumes (of {len(ranked)} scored):")
        for v in ranked[:n]:
            lines.append(
                f"  {v.get('server', '?')} v{v.get('volume', '?')}"
                f" score={v.get('score', 0):g}"
                + ("  HOT" if v.get("hot") else ""))
        coldest = [v for v in reversed(ranked)][:min(n, 3)]
        if len(ranked) > n:
            lines.append("coldest:")
            for v in coldest:
                lines.append(
                    f"  {v.get('server', '?')} v{v.get('volume', '?')}"
                    f" score={v.get('score', 0):g}")
    if coll_scores:
        lines.append("collection heat (master rollup, ops/s):")
        for name, score in sorted(coll_scores.items(),
                                  key=lambda kv: -kv[1])[:n]:
            lines.append(f"  {name:<20} {score:g}")
    if node_scores:
        lines.append("node heat (ops/s): " + "  ".join(
            f"{name}={score:g}" for name, score in sorted(
                node_scores.items(), key=lambda kv: -kv[1])[:n]))
    if forecast:
        lines.append("days-to-full (linear fit over the disk-usage ring):")
        for (node, d), days in sorted(forecast.items(),
                                      key=lambda kv: kv[1])[:n]:
            lines.append(f"  {node} {d}: {days:.1f}d")
    else:
        lines.append("days-to-full: no positive fill trend"
                     " (nothing filling up)")

    body = "\n".join(lines)
    if "out" in flags:
        with open(flags["out"], "w") as f:
            f.write(body + "\n")
        return lines[0] + f"\nreport written to {flags['out']}"
    return body


def _why_describe(ev: dict) -> str:
    """One flight-recorder event as a timeline row body."""
    parts = [ev["type"]]
    for k in ("task", "volume", "node"):
        if ev.get(k) is not None:
            parts.append(f"{k}={ev[k]}")
    for k, v in sorted((ev.get("attrs") or {}).items()):
        parts.append(f"{k}={v}")
    if ev.get("trace_id"):
        parts.append(f"trace={ev['trace_id']}")
    return " ".join(str(p) for p in parts)


@command("cluster.why",
         "<trace-id|volume-id|collection> [-window 600] [-limit 2048]"
         " [-include url,url] [-spool dir,dir] [-out file] — assemble one"
         " causally-ordered cross-node timeline from every node's flight"
         " recorder (/debug/events) + trace ring: request span, degraded"
         " read, injected fault, alert edges, repair task lifecycle, heal."
         " -spool folds in a dead process's on-disk journal; -out dumps"
         " the timeline as JSON for a bug report")
def cmd_cluster_why(env: CommandEnv, args: list[str]) -> str:
    """The question the disconnected counters never answered: WHY was
    this read degraded / WHAT healed this volume. Given a trace id, the
    verb pulls the trace's spans and trace-keyed events from every node,
    widens to the volumes those events name, and folds in each volume's
    fault/alert/task/heal events inside the window; given a volume id it
    renders that volume's whole incident timeline; anything else is a
    collection (tenant) name — events carrying that collection
    correlation key (degraded reads, scrub findings, repair lifecycle,
    usage-sketch overflow, qos_shed admission rejections) assemble into
    a per-tenant timeline, so "why is tenant X seeing 429s" reads as
    the shed events next to whatever else hit that tenant. Events
    are deduped by (process token, seq) — single-process test clusters
    expose one ring at every port.

    Post-mortem: `-spool <dir>` reads a telemetry spool's event journal
    straight off its segment files (stats/store.py), so the timeline of
    a process that is still DEAD — crashed, not restarted — assembles
    next to whatever the live nodes remember."""
    import math
    import re as _re

    flags = parse_flags(args)
    target = flags.get("", "").strip()
    if not target:
        raise ShellError(
            "usage: cluster.why <trace-id|volume-id|collection>"
            " [-window n] [-include url,url]")
    try:
        window = float(flags.get("window", 600.0))
        limit = int(flags.get("limit", 2048))
        if not math.isfinite(window) or window <= 0:
            raise ValueError(window)
    except ValueError:
        raise ShellError("bad -window/-limit")
    volume_id: int | None = None
    trace_id: str | None = None
    collection: str | None = None
    if target.isdigit():
        volume_id = int(target)
    elif _re.fullmatch(r"[0-9a-f]{1,32}", target):
        trace_id = target
    else:
        collection = target

    endpoints = _discover_endpoints(env, flags.get("include", ""))
    ev_res: dict[str, dict] = {}
    tr_res: dict[str, dict] = {}

    def fetch(ep: str) -> None:
        try:
            ev_res[ep] = env.get(
                f"{ep}/debug/events?limit={limit}", timeout=10)
        except Exception:
            return  # an unreachable node must not sink the timeline
        if trace_id is not None:
            try:
                tr_res[ep] = env.get(
                    f"{ep}/debug/traces?id={trace_id}", timeout=10)
            except Exception:
                pass

    spool_dirs = [d.strip() for d in flags.get("spool", "").split(",")
                  if d.strip()]
    _fetch_concurrently(endpoints, fetch)
    if not ev_res and not spool_dirs:
        raise ShellError("no /debug/events endpoint reachable")

    # dedup: one ring per process, exposed at every one of its ports
    events: list[dict] = []
    seen: set[tuple] = set()
    procs: set[str] = set()
    for ep in sorted(ev_res):
        out = ev_res[ep]
        token = out.get("proc") or ep
        for ev in out.get("events", []):
            key = (token, ev.get("seq"))
            if key in seen:
                continue
            seen.add(key)
            procs.add(token)
            events.append(ev)

    # post-mortem spools: the dead process has no /debug/events port, so
    # its journal comes straight off the segment files. A RESTARTED
    # process replays the same journal into its live ring — the
    # (ts, seq, type) key keeps those events from appearing twice (the
    # proc token changes across a restart, so the live dedup can't).
    if spool_dirs:
        from seaweedfs_tpu.stats import store as store_mod

        live_keys = {(round(ev.get("ts", 0.0), 6), ev.get("seq"),
                      ev.get("type")) for ev in events}
        for d in spool_dirs:
            try:
                replayed = store_mod.read_events(d, limit=limit)
            except OSError as e:
                raise ShellError(f"spool {d}: {e}")
            fresh = 0
            for ev in replayed:
                key = (round(ev.get("ts", 0.0), 6), ev.get("seq"),
                       ev.get("type"))
                if key in live_keys:
                    continue
                live_keys.add(key)
                events.append(ev)
                fresh += 1
            if fresh:
                procs.add(f"spool:{d}")
    if not events and not ev_res:
        raise ShellError(
            "no events: every endpoint unreachable and the spool(s)"
            f" {spool_dirs} hold no journal records")

    spans: dict[str, dict] = {}
    for ep in sorted(tr_res):
        for sp in tr_res[ep].get("spans", []):
            spans.setdefault(sp["span_id"], sp)

    if trace_id is not None:
        direct = [ev for ev in events if ev.get("trace_id") == trace_id]
        anchor_ts = [sp["start"] for sp in spans.values()] \
            + [ev["ts"] for ev in direct]
        if not anchor_ts and not direct:
            raise ShellError(
                f"trace {trace_id}: no spans or events found on"
                f" {len(ev_res)} endpoint(s) (evicted, or wrong id?)")
        t0 = min(anchor_ts)
        # widen to the volumes the trace touched: their fault/alert/task
        # events ARE the causal context (a repair task has no trace id —
        # it is correlated by volume + time)
        vols = {ev["volume"] for ev in direct if ev.get("volume") is not None}
        for sp in spans.values():
            v = (sp.get("attrs") or {}).get("volume")
            if v is not None:
                try:
                    vols.add(int(v))
                except (TypeError, ValueError):
                    pass
        related = [
            ev for ev in events
            if ev.get("trace_id") != trace_id
            and ev.get("volume") in vols
            and t0 - 1.0 <= ev["ts"] <= t0 + window
        ]
        picked = direct + related
        head = (f"cluster.why trace {trace_id}: {len(spans)} span(s),"
                f" {len(direct)} direct + {len(related)} related event(s)"
                f" from {len(procs)} process(es)"
                + (f", volumes {sorted(vols)}" if vols else ""))
    else:
        if collection is not None:
            # per-tenant timeline: the collection correlation key rides
            # in attrs (degraded_read, scrub_finding, task_*,
            # tenant_overflow, heat edges on the tenant's volumes)
            picked = [ev for ev in events
                      if (ev.get("attrs") or {}).get("collection")
                      == collection]
            what = f"collection {collection!r}"
        else:
            picked = [ev for ev in events
                      if ev.get("volume") == volume_id]
            what = f"volume {volume_id}"
        if picked:
            t1 = max(ev["ts"] for ev in picked)
            picked = [ev for ev in picked if ev["ts"] >= t1 - window]
        if not picked:
            raise ShellError(
                f"{what}: no events found on {len(ev_res)} endpoint(s)"
                + (f" + {len(spool_dirs)} spool(s)" if spool_dirs else ""))
        # pull the request traces the volume's events name (the span side
        # of the story: which reads were degraded, how slow they were) —
        # ONE fan-out with all lookups batched per endpoint, so a single
        # unreachable node costs one timeout, not one per trace id
        tids = sorted({ev["trace_id"] for ev in picked
                       if ev.get("trace_id")})[:8]
        found: dict[str, list] = {}
        found_lock = __import__("threading").Lock()

        def fetch_traces(ep: str) -> None:
            for tid in tids:
                try:
                    out = env.get(f"{ep}/debug/traces?id={tid}", timeout=10)
                except Exception:
                    return  # unreachable: skip its remaining lookups too
                with found_lock:
                    found.setdefault(ep, []).extend(out.get("spans", []))

        if tids:
            _fetch_concurrently(ev_res, fetch_traces)
        for sps in found.values():
            for sp in sps:
                spans.setdefault(sp["span_id"], sp)
        head = (f"cluster.why {what}: {len(picked)} event(s),"
                f" {len(spans)} span(s) from {len(procs)} process(es)")

    # one causally-ordered timeline: spans (at their start time) + events
    rows: list[tuple[float, str]] = []
    for sp in spans.values():
        rows.append((
            sp["start"],
            f"span [{sp.get('role') or '-'}] {sp['name']}"
            f" {sp['duration_ms']}ms {sp['status']}"
            f" trace={sp['trace_id']}",
        ))
    for ev in picked:
        rows.append((ev["ts"], _why_describe(ev)))
    rows.sort(key=lambda r: r[0])
    t0 = rows[0][0] if rows else 0.0
    lines = [head]
    lines.extend(f"  +{ts - t0:8.3f}s  {body}" for ts, body in rows)
    if "out" in flags:
        # the bug-report artifact: the raw assembled timeline as JSON
        # (events + spans, pre-rendering), symmetric with cluster.heat
        # -out but machine-readable — attach it, don't screenshot it
        import json as _json

        doc = {
            "target": target,
            "kind": ("trace" if trace_id is not None
                     else "collection" if collection is not None
                     else "volume"),
            "window": window,
            "processes": sorted(procs),
            "spools": spool_dirs,
            "head": head,
            "events": sorted(picked, key=lambda e: e.get("ts", 0.0)),
            "spans": sorted(spans.values(), key=lambda s: s["start"]),
        }
        with open(flags["out"], "w") as f:
            _json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        return head + f"\ntimeline json written to {flags['out']}"
    return "\n".join(lines)


@command("cluster.scrub",
         "— integrity-scrub status across every volume server"
         " (/admin/scrub/status): bytes verified, scrub GB/s per kernel,"
         " unresolved findings, throttle budget")
def cmd_cluster_scrub(env: CommandEnv, args: list[str]) -> str:
    import time as _time

    statuses: dict[str, dict] = {}
    for sv in env.servers():
        try:
            statuses[sv.id] = env.get(
                f"{sv.http}/admin/scrub/status", timeout=10)
        except Exception as e:
            statuses[sv.id] = {"error": str(e)}
    if not statuses:
        raise ShellError("no volume servers in the topology")
    lines = [f"integrity scrub across {len(statuses)} volume server(s):"]
    total_findings = 0
    now = _time.time()
    for node, st in sorted(statuses.items()):
        if "error" in st:
            lines.append(f"  {node}: UNREACHABLE ({st['error']})")
            continue
        s = st.get("stats", {})
        gbps = (s.get("bytes_scanned", 0) / max(s.get("seconds", 0.0), 1e-9)
                / 1e9) if s.get("bytes_scanned") else 0.0
        last = s.get("last_pass_at", 0.0)
        age = f"{now - last:.0f}s ago" if last else "never"
        interval = st.get("interval", 0)
        lines.append(
            f"  {node}: {s.get('passes', 0)} pass(es) (last {age},"
            + (f" every {interval:g}s" if interval else " loop off")
            + f"), {s.get('needles_checked', 0)} needles +"
            f" {s.get('stripes_checked', 0)} stripe samples,"
            f" {_fmt_gb(s.get('bytes_scanned', 0))} verified"
            f" @ {gbps:.2f} GB/s,"
            f" budget {st.get('rate_bytes_per_sec', 0) / 1e6:.0f} MB/s"
            f" ({s.get('throttle_waits', 0)} throttle waits),"
            f" {s.get('tmp_removed', 0)} tmp swept"
        )
        unresolved = st.get("unresolved", [])
        total_findings += len(unresolved)
        for f in unresolved:
            lines.append(
                f"    finding: volume {f.get('volume_id')}"
                f" [{f.get('kind')}] {f.get('detail', '')}"
            )
    lines.append(
        "no unresolved findings — cluster integrity clean"
        if total_findings == 0
        else f"{total_findings} unresolved finding(s) — the maintenance"
             f" scrub task routes each to its heal"
    )
    return "\n".join(lines)


@command("cluster.faults",
         "[-list] | -arm <point> -mode <error|latency|torn|disk_full|"
         "partition|corrupt> [-rate r] [-ms n] [-frac f] [-count n] [-key id]"
         " | -disarm <point> | -disarmAll  [-node url] [-include url,url]"
         " — arm/disarm/list fault injection across discovered nodes")
def cmd_cluster_faults(env: CommandEnv, args: list[str]) -> str:
    """The cluster-wide switchboard for util/faults.py: every discovered
    /debug-capable endpoint (master, volume servers, filers, -include'd
    gateways) gets the POST; -node scopes to one endpoint. Single-process
    clusters share one registry — the listing dedups by fault state, and
    arming once is arming everywhere in-process (use -key to scope a
    seam to one server's identity there)."""
    flags = parse_flags(args)
    endpoints = _discover_endpoints(env, flags.get("include", ""))
    if "node" in flags:
        node = flags["node"].rstrip("/")
        if not node.startswith(("http://", "https://")):
            node = "http://" + node
        endpoints = {node}

    if "arm" in flags or "disarm" in flags or "disarmAll" in flags:
        if "arm" in flags:
            if "mode" not in flags:
                raise ShellError("cluster.faults -arm needs -mode")
            body = {"action": "arm", "point": flags["arm"],
                    "mode": flags["mode"]}
            try:
                for k in ("rate", "ms", "frac"):
                    if k in flags:
                        body[k] = float(flags[k])
                if "count" in flags:
                    body["count"] = int(flags["count"])
            except ValueError as e:
                raise ShellError(f"bad numeric flag: {e}")
            if "key" in flags:
                body["key"] = flags["key"]
            verb = f"armed {flags['arm']} ({flags['mode']})"
        elif "disarm" in flags:
            body = {"action": "disarm", "point": flags["disarm"]}
            verb = f"disarmed {flags['disarm']}"
        else:
            body = {"action": "disarm_all"}
            verb = "disarmed all"
        ok, failed = [], []
        for ep in sorted(endpoints):
            try:
                env.post(f"{ep}/debug/faults", body, timeout=10)
                ok.append(ep)
            except Exception as e:
                failed.append(f"{ep} ({e})")
        lines = [f"{verb} on {len(ok)}/{len(endpoints)} endpoint(s)"]
        lines.extend(f"  failed: {f}" for f in failed)
        if not ok:
            raise ShellError("\n".join(lines))
        return "\n".join(lines)

    # default: -list — aggregate state, deduped across shared processes
    seen: dict[tuple, set[str]] = {}
    reached = 0
    for ep in sorted(endpoints):
        try:
            out = env.get(f"{ep}/debug/faults", timeout=10)
        except Exception:
            continue
        reached += 1
        for p in out.get("points", []):
            armed = p.get("armed")
            key = (
                p["point"], p.get("fired", 0),
                tuple(sorted(armed.items())) if armed else None,
            )
            seen.setdefault(key, set()).add(ep)
    if not reached:
        raise ShellError("no /debug/faults endpoint reachable")
    lines = [f"fault points across {reached} endpoint(s):"]
    # sort key must not compare None with a tuple (a point armed on some
    # endpoints and disarmed on others yields both shapes)
    for (point, fired, armed), eps in sorted(
        seen.items(), key=lambda kv: (kv[0][0], kv[0][1], kv[0][2] or ())
    ):
        state = "disarmed" if armed is None else \
            " ".join(f"{k}={v}" for k, v in armed)
        lines.append(f"  {point}: {state}, fired={fired}"
                     f" [{len(eps)} endpoint(s)]")
    if len(seen) == 0:
        lines.append("  (no seams registered yet — servers not started?)")
    return "\n".join(lines)


@command("cluster.qos",
         "[-show] | [-limit 'coll=rps[:burst],…,*=rps'] [-default rps]"
         " [-queueDepth n] [-queueWait s] [-node url] [-include url,url]"
         " — show or set token-bucket admission limits across gateways")
def cmd_cluster_qos(env: CommandEnv, args: list[str]) -> str:
    """The admission-control switchboard (qos/admission.py): with no
    flags, fan out GET /debug/qos across every discovered endpoint and
    render armed state, per-collection limits, class gates and shed
    counters. With -limit/-default/-queueDepth/-queueWait, POST the new
    configuration to every gateway (filers and S3, plus -include'd
    endpoints) so the whole admission plane moves together. -node
    scopes either direction to one endpoint. Sheds show up in
    cluster.top's qos block and as qos_shed events in cluster.why."""
    flags = parse_flags(args)
    endpoints = _discover_endpoints(env, flags.get("include", ""))
    if "node" in flags:
        node = flags["node"].rstrip("/")
        if not node.startswith(("http://", "https://")):
            node = "http://" + node
        endpoints = {node}

    setters = {"limit", "default", "queueDepth", "queueWait"}
    if setters & flags.keys():
        body: dict = {}
        try:
            if "limit" in flags:
                body["spec"] = flags["limit"]
            if "default" in flags:
                body["default"] = float(flags["default"])
            if "queueDepth" in flags:
                body["queue_depth"] = int(flags["queueDepth"])
            if "queueWait" in flags:
                body["queue_wait"] = float(flags["queueWait"])
        except ValueError as e:
            raise ShellError(f"bad numeric flag: {e}")
        ok, failed = [], []
        armed_n = 0
        for ep in sorted(endpoints):
            try:
                out = env.post(f"{ep}/qos/limits", body, timeout=10)
                ok.append(ep)
                if out.get("armed"):
                    armed_n += 1
            except Exception as e:
                failed.append(f"{ep} ({e})")
        lines = [
            f"qos limits applied on {len(ok)}/{len(endpoints)}"
            f" endpoint(s), {armed_n} armed"
        ]
        lines.extend(f"  failed: {f}" for f in failed)
        if not ok:
            raise ShellError("\n".join(lines))
        return "\n".join(lines)

    # default: -show — per-endpoint admission state
    lines = []
    reached = 0
    for ep in sorted(endpoints):
        try:
            out = env.get(f"{ep}/debug/qos", timeout=10)
        except Exception:
            continue
        reached += 1
        armed = "armed" if out.get("armed") else "disarmed"
        role = out.get("role", "?")
        lines.append(f"  {ep} [{role}]: {armed}")
        limits = out.get("limits") or {}
        default = out.get("default")
        if limits or default is not None:
            parts = [
                f"{c}={v[0]:g}:{v[1]:g}" for c, v in sorted(limits.items())
            ]
            if default is not None:
                parts.append(f"*={default[0]:g}:{default[1]:g}")
            lines.append(f"    limits: {', '.join(parts)}")
        gates = out.get("gates") or {}
        tightened = {c: g for c, g in gates.items() if g < 1.0}
        if tightened:
            act = out.get("actuator") or {}
            lines.append(
                "    gates: " + ", ".join(
                    f"{c}={g:g}" for c, g in sorted(tightened.items()))
                + f" (actuator level {act.get('level', '?')},"
                  f" burn {act.get('burn', 0):.2f})"
            )
        # shed is {class: {"reason:collection": n}} — flatten for display
        flat = {
            f"{cls}/{key}": n
            for cls, by_key in (out.get("shed") or {}).items()
            for key, n in by_key.items()
        }
        if flat:
            top = sorted(flat.items(), key=lambda kv: -kv[1])[:4]
            lines.append(
                "    shed: " + ", ".join(f"{k}={int(v)}" for k, v in top))
    if not reached:
        raise ShellError("no /debug/qos endpoint reachable")
    return "\n".join(
        [f"qos admission state across {reached} endpoint(s):"] + lines)


# --- mq.* (`weed/shell/command_mq_topic_list.go` etc.) -----------------------
def _broker_url(env) -> str:
    ps = env.get(f"{env.master_url}/cluster/ps")
    brokers = ps.get("brokers") or []
    if not brokers:
        raise ShellError("no live mq brokers registered")
    return brokers[0]["address"]


@command("mq.topic.list", "list message-queue topics")
def cmd_mq_topic_list(env: CommandEnv, args: list[str]) -> str:
    import json as _json

    out = env.get(f"{_broker_url(env)}/topics/list")
    return _json.dumps(out["topics"], indent=2)


@command("mq.topic.create",
         "-topic <name> [-namespace default] [-partitionCount 4]")
def cmd_mq_topic_create(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    out = env.post(f"{_broker_url(env)}/topics/create", {
        "namespace": flags.get("namespace", "default"),
        "topic": flags["topic"],
        "partition_count": int(flags.get("partitionCount", 4)),
    })
    return f"created topic {flags['topic']} ({out['partition_count']} partitions)"


@command("mq.topic.describe", "-topic <name> [-namespace default]")
def cmd_mq_topic_describe(env: CommandEnv, args: list[str]) -> str:
    import json as _json

    flags = parse_flags(args)
    ns = flags.get("namespace", "default")
    out = env.get(
        f"{_broker_url(env)}/topics/describe?namespace={ns}"
        f"&topic={flags['topic']}"
    )
    return _json.dumps(out, indent=2)


@command("mq.balance", "rebalance topic partitions across live brokers")
def cmd_mq_balance(env: CommandEnv, args: list[str]) -> str:
    out = env.post(f"{_broker_url(env)}/balance", {})
    acts = out.get("actions", [])
    if not acts:
        return "already balanced"
    return "\n".join(
        f"moved {a['namespace']}/{a['topic']} p{a['partition']} "
        f"{a['from']} -> {a['to']}" for a in acts
    )


@command("cluster.raft.ps", "show raft member status on the master(s)")
def cmd_cluster_raft_ps(env: CommandEnv, args: list[str]) -> str:
    out = env.get(f"{env.master_url}/raft/status")
    if not out.get("enabled"):
        return f"raft disabled (single master at {env.master_url})"
    lines = [f"{out['id']}  role={out['role']} term={out['term']} "
             f"commit={out['commit_index']}"]
    for p in out.get("peers", []):
        try:
            ps = env.get(f"{p}/raft/status")
            lines.append(f"{ps['id']}  role={ps['role']} term={ps['term']} "
                         f"commit={ps['commit_index']}")
        except Exception as e:
            lines.append(f"{p}  unreachable ({e})")
    return "\n".join(lines)


@command("cluster.raft.add",
         "-address <master_url> — add a master to the raft cluster"
         " (replicated membership change)")
def cmd_cluster_raft_add(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    addr = flags.get("address") or flags.get("id")
    if not addr:
        raise ShellError("usage: cluster.raft.add -address <master_url>")
    try:
        out = env.post(f"{env.master_url}/raft/add", {"peer": addr})
    except IOError as e:
        raise ShellError(str(e))
    return f"added {addr}; members: {', '.join(out.get('peers', []))}"


@command("cluster.raft.remove",
         "-address <master_url> — remove a master from the raft cluster")
def cmd_cluster_raft_remove(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    addr = flags.get("address") or flags.get("id")
    if not addr:
        raise ShellError("usage: cluster.raft.remove -address <master_url>")
    try:
        out = env.post(f"{env.master_url}/raft/remove", {"peer": addr})
    except IOError as e:
        raise ShellError(str(e))
    return f"removed {addr}; members: {', '.join(out.get('peers', []))}"


@command("mq.topic.configure",
         "-topic <name> -partitionCount <n> [-namespace default] — grow a"
         " live topic's partition count")
def cmd_mq_topic_configure(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    ns = flags.get("namespace", "default")
    try:
        out = env.post(f"{_broker_url(env)}/topics/configure", {
            "namespace": ns, "topic": flags["topic"],
            "partition_count": int(flags["partitionCount"]),
        })
    except KeyError:
        raise ShellError("usage: mq.topic.configure -topic <name>"
                         " -partitionCount <n>")
    except IOError as e:
        raise ShellError(str(e))
    return (f"topic {ns}/{flags['topic']} now has"
            f" {out['partition_count']} partitions")


@command("mount.configure",
         "-dir <mountpoint> [-quotaMB n] — inspect/adjust a RUNNING mount"
         " via its local admin socket")
def cmd_mount_configure(env: CommandEnv, args: list[str]) -> str:
    """`command_mount_configure.go`: talks to the mount's admin listener
    (deterministic unix socket derived from the mountpoint)."""
    import urllib.parse as _u

    from seaweedfs_tpu.mount import admin_socket_path
    from seaweedfs_tpu.server.httpd import get_json, post_json

    flags = parse_flags(args)
    mp = flags.get("dir")
    if not mp:
        raise ShellError("usage: mount.configure -dir <mountpoint>"
                         " [-quotaMB n]")
    base = "http+unix://" + _u.quote(admin_socket_path(mp), safe="")
    if "quotaMB" in flags:
        try:
            quota_mb = int(flags["quotaMB"])
        except ValueError:
            raise ShellError(f"invalid -quotaMB {flags['quotaMB']!r}")
        try:
            out = post_json(base + "/configure", {"quotaMB": quota_mb})
        except (IOError, OSError) as e:
            raise ShellError(f"no running mount at {mp!r}? ({e})")
        return f"quota set to {out['quota_bytes']} bytes"
    try:
        out = get_json(base + "/status")
    except (IOError, OSError) as e:
        raise ShellError(f"no running mount at {mp!r}? ({e})")
    return (f"mount {out['mountpoint']}: used {out['used_bytes']} /"
            f" quota {out['quota_bytes'] or 'unlimited'}"
            f"{' [read-only]' if out['read_only'] else ''}")
