"""ec.* commands — the north-star workload's operational surface
(reference `weed/shell/command_ec_encode.go:58-300`, `command_ec_rebuild.go:99`,
`command_ec_decode.go:77`, `command_ec_balance.go`).

`ec.rebuild` runs in two modes. **classic** pulls every needed shard to
one rebuilder (10x shard-size of fan-in at that node) and decodes
locally. **pipelined** (repair-bandwidth-optimal: arXiv:1412.3022
regenerating codes, arXiv:1207.6744 RapidRAID) has each surviving
holder scale its OWN shards by the decode coefficients on its local
GFNI kernel and XOR-forward one partial sum hop to hop, the rebuilder
(last hop) writing the accumulated sum — no node moves more than
~targets x shard-size, and the GF math spreads across the cluster.
**auto** picks per repair from the surviving-holder count and the
maintenance scheduler's live pressure."""

from __future__ import annotations

import json
import time
import urllib.parse

from seaweedfs_tpu.storage.erasure_coding import decoder as ec_decoder

from .env import CommandEnv, ServerView, ShellError
from .registry import command, dry_run_flag, parse_flags, render_plan

TOTAL_SHARDS = 14
DATA_SHARDS = 10

# partial chunk ceiling: ranges per chain pass. Big enough to amortize
# the hop HTTP overhead, small enough that a mid-chain death retries
# cheaply.
PARTIAL_CHUNK = 4 * 1024 * 1024
# auto chunk sizing (chunk=None): aim for ~STREAM_TARGET_CHUNKS chunks
# per shard so the hop-parallel overlap engages proportionally on ANY
# shard size — a 32KB test shard pipelines 8+ chunks just like a 4GB
# production shard, instead of degenerating to one serial pass
PARTIAL_CHUNK_MIN = 4096
STREAM_TARGET_CHUNKS = 16

# streaming sessions: per-hop in-flight chunk window (the bounded queue
# each hop parks computed chunks on while its forwarder ships them)
STREAM_WINDOW = 4


def auto_chunk(shard_size: int) -> int:
    """The chunk size apply_rebuild_pipelined uses when none is forced:
    ~1/16th of the shard, clamped to [PARTIAL_CHUNK_MIN, PARTIAL_CHUNK]."""
    want = -(-max(shard_size, 1) // STREAM_TARGET_CHUNKS)
    return min(PARTIAL_CHUNK, max(PARTIAL_CHUNK_MIN, want))


def _spread_plan(
    servers: list[ServerView], source: ServerView
) -> dict[str, list[int]]:
    """Assign the 14 shards across servers, rack-aware round-robin
    (`command_ec_encode.go spreadEcShards` via pickNEcShardsToMove)."""
    # order servers: spread racks first, most free slots first
    by_rack: dict[tuple, list[ServerView]] = {}
    for sv in servers:
        by_rack.setdefault((sv.dc, sv.rack), []).append(sv)
    for group in by_rack.values():
        group.sort(key=lambda s: -s.free_slots())
    rotation: list[ServerView] = []
    while any(by_rack.values()):
        for key in sorted(by_rack, key=lambda k: -sum(s.free_slots() for s in by_rack[k])):
            if by_rack[key]:
                rotation.append(by_rack[key].pop(0))
    if not rotation:
        rotation = [source]
    plan: dict[str, list[int]] = {}
    for shard in range(TOTAL_SHARDS):
        sv = rotation[shard % len(rotation)]
        plan.setdefault(sv.id, []).append(shard)
    return plan


def _collect_ec_volume_ids(env: CommandEnv, flags: dict) -> list[tuple[int, str]]:
    if "volumeId" in flags:
        vid = int(flags["volumeId"])
        for sv in env.servers():
            if vid in sv.volumes:
                return [(vid, sv.volumes[vid].get("collection", ""))]
        raise ShellError(f"volume {vid} not found")
    # -collection mode: every volume of the collection (quiet-volume detection
    # — fullness/quiet filters — are master-side in the reference; size filter here)
    collection = flags.get("collection", "")
    out = []
    seen = set()
    for sv in env.servers():
        for v in sv.volumes.values():
            if v.get("collection", "") == collection and v["id"] not in seen:
                seen.add(v["id"])
                out.append((v["id"], collection))
    return out


@command("ec.encode", "-volumeId <n> | -collection <name> — erasure-code volumes "
         "(RS(10,4) on the TPU path)", needs_lock=True)
def cmd_ec_encode(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    lines = []
    for vid, collection in _collect_ec_volume_ids(env, flags):
        lines.append(_ec_encode_one(env, vid, collection))
    return "\n".join(lines) if lines else "no volumes to encode"


def _ec_encode_one(env: CommandEnv, vid: int, collection: str) -> str:
    servers = env.servers()
    holders = [sv for sv in servers if vid in sv.volumes]
    if not holders:
        raise ShellError(f"volume {vid} not found")
    source = holders[0]
    # 1. freeze all replicas (`doEcEncode` marks readonly first)
    for sv in holders:
        env.post(f"{sv.http}/admin/volume/readonly",
                 {"volume": vid, "readonly": True})
    # 2. generate 14 shards + .ecx + .vif on the source server
    env.post(f"{source.http}/admin/ec/generate",
             {"volume": vid, "collection": collection}, timeout=3600)
    # 3. spread shards rack-aware; receivers pull from the source
    plan = _spread_plan(servers, source)
    for sv_id, shards in plan.items():
        sv = next(s for s in servers if s.id == sv_id)
        if sv.id != source.id:
            env.post(
                f"{sv.http}/admin/ec/copy",
                {"volume": vid, "collection": collection, "shards": shards,
                 "source": source.http},
                timeout=3600,
            )
    # 4. delete source shards that now live elsewhere, then mount everywhere
    keep = plan.get(source.id, [])
    drop = [s for s in range(TOTAL_SHARDS) if s not in keep]
    if drop:
        env.post(
            f"{source.http}/admin/ec/delete_shards",
            {"volume": vid, "collection": collection, "shards": drop},
        )
    for sv_id in plan:
        sv = next(s for s in servers if s.id == sv_id)
        env.post(f"{sv.http}/admin/ec/mount",
                 {"volume": vid, "collection": collection})
    # 5. drop the original volume replicas (`doEcEncode` final step)
    for sv in holders:
        env.post(f"{sv.http}/admin/ec/delete_volume", {"volume": vid})
    placed = ", ".join(f"{k}:{v}" for k, v in sorted(plan.items()))
    return f"ec.encode volume {vid}: shards spread {placed}"


@command("ec.decode", "-volumeId <n> [-collection name] — reconstruct the "
         "normal volume from EC shards", needs_lock=True)
def cmd_ec_decode(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    collection = flags.get("collection", "")
    servers = env.servers()
    holders = [sv for sv in servers if vid in sv.ec_shards]
    if not holders:
        raise ShellError(f"no EC shards for volume {vid}")
    # collect every shard onto one server (`command_ec_decode.go:77`)
    target = max(holders, key=lambda sv: len(sv.ec_shards[vid]))
    have = set(target.ec_shards[vid])
    for sv in holders:
        if sv.id == target.id:
            continue
        missing = [s for s in sv.ec_shards[vid] if s not in have]
        if missing:
            env.post(
                f"{target.http}/admin/ec/copy",
                {"volume": vid, "collection": collection, "shards": missing,
                 "source": sv.http},
                timeout=3600,
            )
            have.update(missing)
    if len([s for s in have if s < DATA_SHARDS]) < DATA_SHARDS and len(have) < DATA_SHARDS:
        raise ShellError(f"only {len(have)} shards available, need {DATA_SHARDS}")
    env.post(
        f"{target.http}/admin/ec/to_volume",
        {"volume": vid, "collection": collection}, timeout=3600,
    )
    # unmount EC + delete shards everywhere
    for sv in holders:
        env.post(f"{sv.http}/admin/ec/unmount", {"volume": vid})
        env.post(
            f"{sv.http}/admin/ec/delete_shards",
            {"volume": vid, "collection": collection,
             "shards": list(range(TOTAL_SHARDS)), "delete_index": True},
        )
    return f"ec.decode volume {vid}: reconstructed on {target.id}"


def plan_rebuild(env: CommandEnv, vid: int, collection: str = "") -> dict | None:
    """The rebuild plan for one EC volume: which holder rebuilds, which
    shards it pulls from whom, which shards are missing. None when all 14
    shards are present; raises when fewer than 10 survive. Shared between
    the `ec.rebuild` verb and the maintenance daemon's ec_rebuild executor."""
    servers = env.servers()
    holders = [sv for sv in servers if vid in sv.ec_shards]
    present = sorted({s for sv in holders for s in sv.ec_shards[vid]})
    missing = [s for s in range(TOTAL_SHARDS) if s not in present]
    if not missing:
        return None
    if len(present) < DATA_SHARDS:
        raise ShellError(
            f"volume {vid}: only {len(present)} shards left, cannot rebuild"
        )
    # rebuilder = holder with the most local shards and enough free slots
    rebuilder = max(holders, key=lambda sv: (len(sv.ec_shards[vid]), sv.free_slots()))
    local = set(rebuilder.ec_shards[vid])
    pulls = []
    for sv in holders:
        if sv.id == rebuilder.id:
            continue
        pull = [s for s in sv.ec_shards[vid] if s not in local]
        if pull:
            pulls.append({"source": sv.id, "source_url": sv.http,
                          "shards": pull})
            local.update(pull)
    return {
        "volume": vid, "collection": collection,
        "rebuilder": rebuilder.id, "rebuilder_url": rebuilder.http,
        "missing": missing, "present": present, "pulls": pulls,
        "own": sorted(rebuilder.ec_shards[vid]),
    }


def describe_rebuild(plan: dict) -> list[str]:
    """Display lines for a plan_rebuild plan — shared by the verb's
    dry-run output and /debug/maintenance history."""
    steps = [
        f"pull shards {p['shards']} from {p['source']} to"
        f" {plan['rebuilder']}" for p in plan["pulls"]
    ]
    steps.append(f"rebuild shards {plan['missing']} on {plan['rebuilder']}")
    return steps


def apply_rebuild(env: CommandEnv, plan: dict) -> list[int]:
    """Execute a plan_rebuild plan: pull inputs, rebuild on the Pallas
    RS(10,4) path, drop pulled-only inputs, re-mount. The whole-shard
    pulls are flagged `repair` so the rebuilder counts them into
    ec_repair_bytes_on_wire{mode="classic"} — the baseline the pipelined
    mode is measured against."""
    _, mseconds, _, _ = ec_decoder.repair_metrics()
    vid, collection = plan["volume"], plan["collection"]
    rb = plan["rebuilder_url"]
    t0 = time.perf_counter()
    for p in plan["pulls"]:
        env.post(
            f"{rb}/admin/ec/copy",
            {"volume": vid, "collection": collection,
             "shards": p["shards"], "source": p["source_url"],
             "repair": True},
            timeout=3600,
        )
    mseconds.labels("classic", "pull").observe(time.perf_counter() - t0)
    t1 = time.perf_counter()
    out = env.post(
        f"{rb}/admin/ec/rebuild",
        {"volume": vid, "collection": collection}, timeout=3600,
    )
    mseconds.labels("classic", "decode").observe(time.perf_counter() - t1)
    # drop shards the rebuilder only pulled as rebuild inputs, keep its own +
    # the rebuilt ones, then re-mount to refresh its shard list
    pulled = [s for p in plan["pulls"] for s in p["shards"]]
    keep = set(plan["own"]) | set(out.get("rebuilt", []))
    drop = [s for s in pulled if s not in keep]
    if drop:
        env.post(
            f"{rb}/admin/ec/delete_shards",
            {"volume": vid, "collection": collection, "shards": drop},
        )
    env.post(f"{rb}/admin/ec/mount",
             {"volume": vid, "collection": collection})
    return out.get("rebuilt", plan["missing"])


class PipelinedRebuildError(ShellError):
    """A pipelined rebuild could not complete; `reason` is one of
    decoder.REPAIR_FALLBACK_REASONS and the caller falls back to classic."""

    def __init__(self, reason: str, detail: str = "") -> None:
        super().__init__(f"pipelined rebuild failed ({reason}): {detail}")
        self.reason = reason
        self.detail = detail


def plan_rebuild_pipelined(
    env: CommandEnv, vid: int, collection: str = "",
    exclude: tuple[str, ...] = (),
    prefer_rebuilder: str | None = None,
) -> dict | None:
    """The partial-sum chain plan: decode coefficients per holder, hops
    ordered with the rebuilder LAST (it lands the accumulated sum in its
    /admin/ec/partial/start state). `exclude` drops dead hops on a chain
    restart. `prefer_rebuilder` pins the writer on restarts: the
    committed frontier lives in the old rebuilder's partial state, and
    the (shard-count, free_slots) ranking can flip between plans while
    volumes move underneath — switching writers would silently discard
    landed chunks, so a still-usable preferred holder always wins.
    None when nothing is missing; ShellError when the surviving
    (non-excluded) shards drop below 10."""
    servers = env.servers()
    all_holders = [sv for sv in servers if vid in sv.ec_shards]
    holders = [sv for sv in all_holders if sv.id not in exclude]
    # targets = shards missing from the WHOLE cluster; a dead hop's
    # shards are unavailable as chain inputs but not lost, so excluding
    # it shrinks the contributor set without inflating the rebuild
    present_all = sorted(
        {s for sv in all_holders for s in sv.ec_shards[vid]})
    missing = [s for s in range(TOTAL_SHARDS) if s not in present_all]
    if not missing:
        return None
    usable = sorted({s for sv in holders for s in sv.ec_shards[vid]})
    if len(usable) < DATA_SHARDS:
        raise ShellError(
            f"volume {vid}: only {len(usable)} usable shards"
            f" (excluding {list(exclude)}), cannot rebuild"
        )
    use, matrix = ec_decoder.repair_coefficients(usable, missing)
    rebuilder = next(
        (sv for sv in holders if sv.id == prefer_rebuilder), None
    ) or max(
        holders, key=lambda sv: (len(sv.ec_shards[vid]), sv.free_slots())
    )
    # each `use` shard contributes from exactly one hop; hops ordered
    # non-rebuilders first (stable by id), rebuilder last as the writer
    assigned: set[int] = set()
    chain: list[dict] = []
    others = sorted(
        (sv for sv in holders if sv.id != rebuilder.id),
        key=lambda sv: sv.id,
    )
    for sv in others + [rebuilder]:
        own = [
            s for s in sorted(sv.ec_shards[vid])
            if s in use and s not in assigned
        ]
        assigned.update(own)
        if not own and sv.id != rebuilder.id:
            continue  # nothing to contribute, not the writer: skip the hop
        chain.append({
            "server": sv.id, "url": sv.http, "shards": own,
            "coefs": {
                str(s): [int(matrix[t, use.index(s)])
                         for t in range(len(missing))]
                for s in own
            },
            "write": sv.id == rebuilder.id,
        })
    return {
        "volume": vid, "collection": collection, "mode": "pipelined",
        "rebuilder": rebuilder.id, "rebuilder_url": rebuilder.http,
        "missing": missing, "present": present_all, "use": use,
        "chain": chain,
    }


def describe_rebuild_pipelined(plan: dict) -> list[str]:
    steps = []
    for hop in plan["chain"]:
        if hop["write"]:
            steps.append(
                f"{hop['server']}: add shards {hop['shards']}, write"
                f" rebuilt {plan['missing']} (chain terminal)"
            )
        else:
            steps.append(
                f"{hop['server']}: scale shards {hop['shards']},"
                f" XOR-forward one partial"
            )
    steps.append(
        f"bytes-on-wire at rebuilder ~{len(plan['missing'])}x shard-size"
        f" (classic: {DATA_SHARDS}x)"
    )
    return steps


def choose_rebuild_mode(pplan: dict | None, pressure: dict | None = None
                        ) -> tuple[str, str]:
    """auto-mode policy, per the repair-bandwidth trade: a chain needs
    >= 3 contributing nodes before hop-forwarding beats one pull burst; a
    2-node chain is still worth it when the maintenance scheduler is
    under pressure (token bucket drained / in-flight near the global or
    per-node cap — spreading the GF math and halving the rebuilder's
    fan-in matters exactly when repairs contend); single-holder volumes
    rebuild locally either way, so classic's simpler ladder wins."""
    if pplan is None:
        return "classic", "no pipelined plan"
    hops = len(pplan["chain"])
    if hops >= 3:
        return "pipelined", f"{hops}-hop chain cuts rebuilder fan-in" \
            f" {DATA_SHARDS}x -> {len(pplan['missing'])}x"
    if hops == 2 and pressure is not None:
        node_hot = any(
            n >= pressure.get("per_node_limit", 1)
            for n in pressure.get("node_inflight", {}).values()
        )
        if (
            pressure.get("tokens", 2.0) < 1.0
            or pressure.get("in_flight", 0)
            >= max(1, pressure.get("global_limit", 4) - 1)
            or node_hot
        ):
            return "pipelined", "2-hop chain under repair-scheduler pressure"
    return "classic", "too_few_holders"


def apply_rebuild_pipelined(
    env: CommandEnv, plan: dict, chunk: int | None = None,
    stream: bool | None = None, window: int = STREAM_WINDOW,
    stall_timeout: float | None = None,
) -> tuple[list[int], dict]:
    """Execute a pipelined plan with the retry ladder: a dead hop
    restarts the chain minus that hop (re-planned coefficients) while
    the survivors still cover 10 shards; a CRC mismatch or stream stall
    restarts the SAME chain once (the server that reported it is the
    detector, not the corruptor, and a stalled downstream may just have
    been slow — excluding either would punish a healthy holder) and
    escalates to the typed fallback on a repeat; exhausted restarts
    raise PipelinedRebuildError so the caller falls back to classic.

    Restarts RESUME: the rebuilder's partial-write state survives a
    failed chain (chunks land in order, so its committed frontier is
    exact) and the re-planned chain re-sends only the uncommitted
    suffix — the already-committed bytes are counted into
    ec_repair_resumed_bytes_total instead of crossing the wire again.
    The state is aborted only on terminal failure.

    `stream=None` auto-picks: multi-hop, multi-chunk repairs use the
    streaming session mode (hop-parallel, ~(hops + chunks) chunk-times);
    True/False forces. `chunk=None` sizes chunks via auto_chunk() off
    the real shard size. Returns (rebuilt shard ids, wire stats)."""
    _, mseconds, _, mrestarts = ec_decoder.repair_metrics()
    excluded: list[str] = []
    restarts = 0
    strikes = {r: 0 for r in ("crc_mismatch", "chunk_crc", "stream_stall")}
    rb_url = plan["rebuilder_url"]
    try:
        while True:
            try:
                return _run_chain(env, plan, chunk, mseconds, restarts,
                                  stream=stream, window=window,
                                  stall_timeout=stall_timeout)
            except PipelinedRebuildError:
                raise
            except _HopFailed as e:
                reason = e.reason \
                    if e.reason in ec_decoder.REPAIR_RESTART_REASONS \
                    else "hop_failed"
                mrestarts.labels(reason).inc()
                from seaweedfs_tpu.stats import events as events_mod

                events_mod.emit(
                    "chain_restart", volume=plan["volume"],
                    node=e.server, reason=reason, detail=e.detail[:200],
                    **({"chunk": e.chunk} if e.chunk is not None else {}),
                )
                restarts += 1
                if reason in strikes:
                    strikes[reason] += 1
                    if strikes[reason] >= 2:  # twice: stop pretending
                        raise PipelinedRebuildError(reason, e.detail)
                elif e.server:
                    excluded.append(e.server)
                elif restarts > 1:
                    # a hop failed twice without ever being attributable
                    # (pure transport noise): classic is honest fallback
                    raise PipelinedRebuildError("hop_failed", e.detail)
                try:
                    new_plan = plan_rebuild_pipelined(
                        env, plan["volume"], plan["collection"],
                        exclude=tuple(excluded),
                        prefer_rebuilder=plan["rebuilder"],
                    )
                except ShellError as err:
                    raise PipelinedRebuildError(
                        "insufficient_shards", str(err))
                if new_plan is None:  # healed underneath us
                    return [], {"bytes_on_wire_total": 0,
                                "bytes_on_wire_rebuilder": 0,
                                "hops": 0, "restarts": restarts}
                if new_plan["rebuilder_url"] != rb_url:
                    # the committed frontier lives on the OLD rebuilder:
                    # drop its state, the new writer starts from byte 0
                    try:
                        env.post(f"{rb_url}/admin/ec/partial/abort",
                                 {"volume": plan["volume"]}, timeout=30)
                    except Exception:
                        pass
                    rb_url = new_plan["rebuilder_url"]
                plan = new_plan
    except BaseException as e:
        # terminal exit (typed fallback or unexpected): the partial
        # state will not be resumed — abort it so only .tmp litter
        # (swept by scrub GC) can remain. Success returns above.
        if not isinstance(e, GeneratorExit):
            try:
                env.post(f"{rb_url}/admin/ec/partial/abort",
                         {"volume": plan["volume"]}, timeout=30)
            except Exception:
                pass
        raise


class _HopFailed(Exception):
    def __init__(self, server: str, reason: str, detail: str = "",
                 chunk: int | None = None) -> None:
        super().__init__(f"chain hop {server or '?'} failed: {reason}")
        self.server = server
        self.reason = reason
        self.detail = detail
        self.chunk = chunk


def _json_or_empty(out: bytes) -> dict:
    try:
        return json.loads(out) if out else {}
    except ValueError:
        return {}


def _reason_of(resp: dict) -> str:
    err = resp.get("error", "")
    return err if err in ec_decoder.REPAIR_RESTART_REASONS else "hop_failed"


def _run_chain(env, plan, chunk, mseconds, restarts, stream=None,
               window=STREAM_WINDOW,
               stall_timeout=None) -> tuple[list[int], dict]:
    vid, collection = plan["volume"], plan["collection"]
    rb = plan["rebuilder_url"]
    chain = plan["chain"]
    targets = plan["missing"]
    t0 = time.perf_counter()
    try:
        start = env.post(
            f"{rb}/admin/ec/partial/start",
            {"volume": vid, "collection": collection, "targets": targets,
             "resume": True},
            timeout=60,
        )
    except Exception as e:
        raise PipelinedRebuildError("start_failed", str(e)[:200])
    shard_size = int(start["shard_size"])
    committed = int(start.get("committed", 0))
    if chunk is None:
        chunk = auto_chunk(shard_size)
    mseconds.labels("pipelined", "start").observe(time.perf_counter() - t0)
    saved = 0
    if committed and len(chain) > 1:
        # bytes a from-scratch restart would have re-sent: the committed
        # prefix, stacked per target, over every hop link. A 1-hop chain
        # moves no partial-sum bytes at all (the writer computes from
        # its own shards; the chunk POSTs carry empty bodies), so there
        # are no wire savings to count.
        saved = committed * len(targets) * (len(chain) - 1)
        ec_decoder.stream_metrics()[1].inc(saved)
    use_stream = stream if stream is not None else (
        len(chain) > 1 and shard_size - committed > chunk)
    t1 = time.perf_counter()
    if use_stream:
        received, read_bytes = _stream_chunks(
            env, plan, chunk, window, shard_size, committed,
            stall_timeout=stall_timeout)
    else:
        received, read_bytes = _serial_chunks(
            env, plan, chunk, shard_size, committed)
    mseconds.labels("pipelined", "chain").observe(time.perf_counter() - t1)
    t2 = time.perf_counter()
    out = env.post(
        f"{rb}/admin/ec/partial/commit",
        {"volume": vid, "collection": collection}, timeout=60,
    )
    mseconds.labels("pipelined", "commit").observe(
        time.perf_counter() - t2)
    stats = {
        "bytes_on_wire_total": sum(received),
        "bytes_on_wire_rebuilder": received[-1] if received else 0,
        "shard_size": shard_size,
        "hops": len(chain),
        "restarts": restarts,
        "per_hop_received": received,
        "survivor_bytes_read": sum(read_bytes),
        "per_hop_read": read_bytes,
        "resumed_bytes_saved": saved,
        "streamed": bool(use_stream),
        "targets": len(targets),
    }
    return out.get("rebuilt", targets), stats


def _chunk_spans(shard_size: int, committed: int, chunk: int):
    for off in range(committed, max(shard_size, 1), chunk):
        size = min(chunk, shard_size - off)
        if size <= 0:
            return
        yield off, size


def _serial_chunks(env, plan, chunk, shard_size, committed):
    """One nested chain pass per chunk (the pre-streaming dataflow, kept
    for single-chunk repairs, 1-hop chains and as the forced-comparison
    baseline the bench measures the streaming win against)."""
    from seaweedfs_tpu.server.httpd import http_request

    vid, collection = plan["volume"], plan["collection"]
    chain = plan["chain"]
    targets = plan["missing"]
    targets_q = ",".join(str(t) for t in targets)
    received = [0] * len(chain)
    read_bytes = [0] * len(chain)
    for off, size in _chunk_spans(shard_size, committed, chunk):
        url = (
            chain[0]["url"] + f"/admin/ec/partial?volume={vid}"
            f"&collection={urllib.parse.quote(collection)}"
            f"&offset={off}&size={size}&targets={targets_q}"
            f"&chain={urllib.parse.quote(json.dumps(chain))}"
        )
        try:
            status, _, out = http_request("POST", url, b"", timeout=120)
        except (IOError, OSError) as e:
            raise _HopFailed(chain[0]["server"], "hop_failed",
                             str(e)[:200])
        resp = _json_or_empty(out)
        if status != 200:
            raise _HopFailed(
                resp.get("failed_hop_server") or chain[0]["server"],
                _reason_of(resp), str(resp)[:200],
            )
        for i, n in enumerate(resp.get("received", [])[-len(chain):]):
            received[i] += int(n)
        for i, n in enumerate(resp.get("read", [])[-len(chain):]):
            read_bytes[i] += int(n)
    return received, read_bytes


def _stream_chunks(env, plan, chunk, window, shard_size, committed,
                   stall_timeout=None):
    """The hop-parallel dataflow: open a session along the chain once,
    then fire chunk POSTs that each hop ACKs after local compute +
    enqueue — chunk k rides the forwarder threads downstream while every
    hop computes chunk k+1, so the pass costs ~(hops + chunks)
    chunk-times instead of hops x chunks. close() flushes, cascades, and
    reports per-hop wire/read accounting + the writer's committed
    frontier (the resume point when anything failed)."""
    import uuid

    from seaweedfs_tpu.server.httpd import http_request

    vid, collection = plan["volume"], plan["collection"]
    chain = plan["chain"]
    targets = plan["missing"]
    head = chain[0]
    session = uuid.uuid4().hex
    open_payload = {
        "session": session, "volume": vid, "collection": collection,
        "targets": targets, "chain": chain, "window": window,
    }
    if stall_timeout is not None:
        open_payload["stall_timeout"] = stall_timeout
    open_body = json.dumps(open_payload).encode()
    try:
        status, _, out = http_request(
            "POST", head["url"] + "/admin/ec/partial/stream/open",
            open_body, headers={"Content-Type": "application/json"},
            timeout=120,
        )
    except (IOError, OSError) as e:
        raise _HopFailed(head["server"], "hop_failed", str(e)[:200])
    resp = _json_or_empty(out)
    if status != 200:
        raise _HopFailed(
            resp.get("failed_hop_server") or head["server"],
            _reason_of(resp), str(resp)[:200], chunk=resp.get("chunk"),
        )
    close_url = (head["url"]
                 + f"/admin/ec/partial/stream/close?session={session}")
    try:
        for seq, (off, size) in enumerate(
                _chunk_spans(shard_size, committed, chunk)):
            url = (
                head["url"] + "/admin/ec/partial/stream/chunk"
                f"?session={session}&seq={seq}&offset={off}&size={size}"
            )
            try:
                status, _, out = http_request("POST", url, b"", timeout=120)
            except (IOError, OSError) as e:
                raise _HopFailed(head["server"], "hop_failed",
                                 str(e)[:200], chunk=seq)
            resp = _json_or_empty(out)
            if status != 200:
                raise _HopFailed(
                    resp.get("failed_hop_server") or head["server"],
                    _reason_of(resp), str(resp)[:200],
                    chunk=resp.get("chunk", seq),
                )
    except _HopFailed:
        try:  # tear the session down chain-wide; the ladder resumes
            http_request("POST", close_url, b"", timeout=60)
        except Exception:
            pass
        raise
    try:
        status, _, out = http_request("POST", close_url, b"", timeout=240)
    except (IOError, OSError) as e:
        raise _HopFailed(head["server"], "hop_failed", str(e)[:200])
    close = _json_or_empty(out)
    if status != 200 or not close.get("ok"):
        raise _HopFailed(
            close.get("failed_hop_server") or head["server"],
            _reason_of(close), str(close)[:300], chunk=close.get("chunk"),
        )
    landed = close.get("committed")
    if landed is not None and int(landed) < shard_size:
        raise _HopFailed(
            "", "hop_failed",
            f"stream closed at {landed}/{shard_size} committed")
    received = [int(n) for n in close.get("received", [])]
    read_bytes = [int(n) for n in close.get("read", [])]
    while len(received) < len(chain):
        received.append(0)
    while len(read_bytes) < len(chain):
        read_bytes.append(0)
    return received, read_bytes


def run_rebuild(
    env: CommandEnv, vid: int, collection: str = "", mode: str = "auto",
    pressure: dict | None = None, dry_run: bool = False,
    stream: bool | None = None,
) -> dict:
    """The ONE choose-mode + apply + typed-fallback path, shared by the
    `ec.rebuild` verb and the maintenance ec_rebuild executor — so both
    entry points produce identical repair behavior AND identical
    fallbacks/restarts metric series. Returns a dict:
    {healed} | {dry_run, mode, planned} |
    {mode, planned, rebuilt, rebuilder, stats?}.

    The whole repair runs inside an `ec.rebuild` trace span: every hop
    POST inherits its X-Sw-Trace-Id (httpd's automatic propagation), so
    `cluster.trace` shows the start -> partial hops -> commit chain as
    ONE cross-node trace — from the daemon it nests under the
    maintenance.ec_rebuild root, from the shell it IS the root."""
    from seaweedfs_tpu.stats import trace as trace_mod

    with trace_mod.span("ec.rebuild", volume=vid, mode=mode):
        return _run_rebuild(env, vid, collection, mode, pressure, dry_run,
                            stream)


def _run_rebuild(
    env: CommandEnv, vid: int, collection: str, mode: str,
    pressure: dict | None, dry_run: bool, stream: bool | None = None,
) -> dict:
    if mode not in ("auto",) + ec_decoder.REPAIR_MODES:
        raise ShellError(f"mode must be auto|classic|pipelined, got {mode}")
    plan = plan_rebuild(env, vid, collection)
    if plan is None:
        return {"healed": True, "planned": [], "mode": mode}
    pplan = None
    if mode != "classic":
        try:
            pplan = plan_rebuild_pipelined(env, vid, collection)
        except (ShellError, IOError, OSError):
            pplan = None  # no usable chain (or a transient topology
            #               fetch failure): classic still repairs
    from seaweedfs_tpu.stats import events as events_mod

    if mode == "auto":
        mode, _why = choose_rebuild_mode(pplan, pressure)
        if mode == "classic" and pplan is not None:
            ec_decoder.repair_metrics()[2].labels("too_few_holders").inc()
            events_mod.emit("fallback_repair", volume=vid,
                            reason="too_few_holders")
    if mode == "pipelined" and pplan is None:
        ec_decoder.repair_metrics()[2].labels("insufficient_shards").inc()
        events_mod.emit("fallback_repair", volume=vid,
                        reason="insufficient_shards")
        mode = "classic"
    if dry_run:
        planned = describe_rebuild_pipelined(pplan) if mode == "pipelined" \
            else describe_rebuild(plan)
        return {"dry_run": True, "mode": mode, "planned": planned}
    if mode == "pipelined":
        planned = describe_rebuild_pipelined(pplan)
        try:
            rebuilt, stats = apply_rebuild_pipelined(env, pplan,
                                                     stream=stream)
            return {"mode": "pipelined", "planned": planned,
                    "rebuilt": rebuilt, "rebuilder": pplan["rebuilder"],
                    "stats": stats}
        except PipelinedRebuildError as e:
            ec_decoder.repair_metrics()[2].labels(e.reason).inc()
            events_mod.emit("fallback_repair", volume=vid, reason=e.reason,
                            detail=e.detail[:200])
            # classic stays the fallback: re-plan (the chain attempts may
            # have changed nothing — partial state aborted server-side)
            plan = plan_rebuild(env, vid, collection)
            if plan is None:
                return {"healed": True, "planned": planned, "mode": mode}
    planned = describe_rebuild(plan)
    rebuilt = apply_rebuild(env, plan)
    return {"mode": "classic", "planned": planned, "rebuilt": rebuilt,
            "rebuilder": plan["rebuilder"]}


@command("ec.rebuild", "-volumeId <n> [-collection name]"
         " [-mode pipelined|classic|auto] [-stream true|false]"
         " [-dryRun|-apply] — rebuild missing shards; pipelined streams"
         " GF partial sums hop to hop (~1x shard-size at the rebuilder"
         " vs 10x classic), chunks pipelined hop-parallel by default",
         needs_lock=True)
def cmd_ec_rebuild(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    stream = None
    if "stream" in flags:
        stream = flags["stream"] not in ("false", "0", "no")
    out = run_rebuild(
        env, vid, flags.get("collection", ""),
        mode=flags.get("mode", "auto"), dry_run=dry_run_flag(flags),
        stream=stream,
    )
    if out.get("healed"):
        return f"volume {vid}: all {TOTAL_SHARDS} shards present"
    if out.get("dry_run"):
        return render_plan(f"ec.rebuild [{out['mode']}]", out["planned"])
    stats = out.get("stats")
    if stats is not None:
        return (
            f"volume {vid}: rebuilt shards {out['rebuilt']} on"
            f" {out['rebuilder']} (pipelined"
            f"{', streamed' if stats.get('streamed') else ''},"
            f" {stats['hops']} hops,"
            f" {stats['bytes_on_wire_rebuilder']} B at rebuilder,"
            f" {stats['bytes_on_wire_total']} B total on wire)"
        )
    return f"volume {vid}: rebuilt shards {out['rebuilt']} on" \
        f" {out['rebuilder']} (classic)"


@command("ec.balance", "spread EC shards evenly across servers "
         "(ref command_ec_balance.go)", needs_lock=True)
def cmd_ec_balance(env: CommandEnv, args: list[str]) -> str:
    servers = env.servers()
    moves = []
    # per EC volume: if one server holds more than ceil(14/N) shards, move extras
    vids = sorted({vid for sv in servers for vid in sv.ec_shards})
    for vid in vids:
        holders = [sv for sv in servers if vid in sv.ec_shards]
        collection = ""
        all_servers = sorted(servers, key=lambda sv: len(sv.ec_shards.get(vid, [])))
        cap = -(-TOTAL_SHARDS // max(len(servers), 1))  # ceil
        for sv in holders:
            extra = len(sv.ec_shards[vid]) - cap
            while extra > 0:
                shard = sv.ec_shards[vid][-1]
                # move to the server with fewest shards of this volume
                dst = all_servers[0]
                if dst.id == sv.id:
                    break
                env.post(
                    f"{dst.http}/admin/ec/copy",
                    {"volume": vid, "collection": collection, "shards": [shard],
                     "source": sv.http},
                    timeout=3600,
                )
                env.post(f"{dst.http}/admin/ec/mount",
                         {"volume": vid, "collection": collection})
                env.post(
                    f"{sv.http}/admin/ec/delete_shards",
                    {"volume": vid, "collection": collection, "shards": [shard]},
                )
                sv.ec_shards[vid].remove(shard)
                dst.ec_shards.setdefault(vid, []).append(shard)
                moves.append(f"volume {vid} shard {shard}: {sv.id} -> {dst.id}")
                extra -= 1
                all_servers.sort(key=lambda s: len(s.ec_shards.get(vid, [])))
    return "\n".join(moves) if moves else "EC shards already balanced"
