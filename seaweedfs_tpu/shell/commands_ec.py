"""ec.* commands — the north-star workload's operational surface
(reference `weed/shell/command_ec_encode.go:58-300`, `command_ec_rebuild.go:99`,
`command_ec_decode.go:77`, `command_ec_balance.go`)."""

from __future__ import annotations

from .env import CommandEnv, ServerView, ShellError
from .registry import command, dry_run_flag, parse_flags, render_plan

TOTAL_SHARDS = 14
DATA_SHARDS = 10


def _spread_plan(
    servers: list[ServerView], source: ServerView
) -> dict[str, list[int]]:
    """Assign the 14 shards across servers, rack-aware round-robin
    (`command_ec_encode.go spreadEcShards` via pickNEcShardsToMove)."""
    # order servers: spread racks first, most free slots first
    by_rack: dict[tuple, list[ServerView]] = {}
    for sv in servers:
        by_rack.setdefault((sv.dc, sv.rack), []).append(sv)
    for group in by_rack.values():
        group.sort(key=lambda s: -s.free_slots())
    rotation: list[ServerView] = []
    while any(by_rack.values()):
        for key in sorted(by_rack, key=lambda k: -sum(s.free_slots() for s in by_rack[k])):
            if by_rack[key]:
                rotation.append(by_rack[key].pop(0))
    if not rotation:
        rotation = [source]
    plan: dict[str, list[int]] = {}
    for shard in range(TOTAL_SHARDS):
        sv = rotation[shard % len(rotation)]
        plan.setdefault(sv.id, []).append(shard)
    return plan


def _collect_ec_volume_ids(env: CommandEnv, flags: dict) -> list[tuple[int, str]]:
    if "volumeId" in flags:
        vid = int(flags["volumeId"])
        for sv in env.servers():
            if vid in sv.volumes:
                return [(vid, sv.volumes[vid].get("collection", ""))]
        raise ShellError(f"volume {vid} not found")
    # -collection mode: every volume of the collection (quiet-volume detection
    # — fullness/quiet filters — are master-side in the reference; size filter here)
    collection = flags.get("collection", "")
    out = []
    seen = set()
    for sv in env.servers():
        for v in sv.volumes.values():
            if v.get("collection", "") == collection and v["id"] not in seen:
                seen.add(v["id"])
                out.append((v["id"], collection))
    return out


@command("ec.encode", "-volumeId <n> | -collection <name> — erasure-code volumes "
         "(RS(10,4) on the TPU path)", needs_lock=True)
def cmd_ec_encode(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    lines = []
    for vid, collection in _collect_ec_volume_ids(env, flags):
        lines.append(_ec_encode_one(env, vid, collection))
    return "\n".join(lines) if lines else "no volumes to encode"


def _ec_encode_one(env: CommandEnv, vid: int, collection: str) -> str:
    servers = env.servers()
    holders = [sv for sv in servers if vid in sv.volumes]
    if not holders:
        raise ShellError(f"volume {vid} not found")
    source = holders[0]
    # 1. freeze all replicas (`doEcEncode` marks readonly first)
    for sv in holders:
        env.post(f"{sv.http}/admin/volume/readonly",
                 {"volume": vid, "readonly": True})
    # 2. generate 14 shards + .ecx + .vif on the source server
    env.post(f"{source.http}/admin/ec/generate",
             {"volume": vid, "collection": collection}, timeout=3600)
    # 3. spread shards rack-aware; receivers pull from the source
    plan = _spread_plan(servers, source)
    for sv_id, shards in plan.items():
        sv = next(s for s in servers if s.id == sv_id)
        if sv.id != source.id:
            env.post(
                f"{sv.http}/admin/ec/copy",
                {"volume": vid, "collection": collection, "shards": shards,
                 "source": source.http},
                timeout=3600,
            )
    # 4. delete source shards that now live elsewhere, then mount everywhere
    keep = plan.get(source.id, [])
    drop = [s for s in range(TOTAL_SHARDS) if s not in keep]
    if drop:
        env.post(
            f"{source.http}/admin/ec/delete_shards",
            {"volume": vid, "collection": collection, "shards": drop},
        )
    for sv_id in plan:
        sv = next(s for s in servers if s.id == sv_id)
        env.post(f"{sv.http}/admin/ec/mount",
                 {"volume": vid, "collection": collection})
    # 5. drop the original volume replicas (`doEcEncode` final step)
    for sv in holders:
        env.post(f"{sv.http}/admin/ec/delete_volume", {"volume": vid})
    placed = ", ".join(f"{k}:{v}" for k, v in sorted(plan.items()))
    return f"ec.encode volume {vid}: shards spread {placed}"


@command("ec.decode", "-volumeId <n> [-collection name] — reconstruct the "
         "normal volume from EC shards", needs_lock=True)
def cmd_ec_decode(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    collection = flags.get("collection", "")
    servers = env.servers()
    holders = [sv for sv in servers if vid in sv.ec_shards]
    if not holders:
        raise ShellError(f"no EC shards for volume {vid}")
    # collect every shard onto one server (`command_ec_decode.go:77`)
    target = max(holders, key=lambda sv: len(sv.ec_shards[vid]))
    have = set(target.ec_shards[vid])
    for sv in holders:
        if sv.id == target.id:
            continue
        missing = [s for s in sv.ec_shards[vid] if s not in have]
        if missing:
            env.post(
                f"{target.http}/admin/ec/copy",
                {"volume": vid, "collection": collection, "shards": missing,
                 "source": sv.http},
                timeout=3600,
            )
            have.update(missing)
    if len([s for s in have if s < DATA_SHARDS]) < DATA_SHARDS and len(have) < DATA_SHARDS:
        raise ShellError(f"only {len(have)} shards available, need {DATA_SHARDS}")
    env.post(
        f"{target.http}/admin/ec/to_volume",
        {"volume": vid, "collection": collection}, timeout=3600,
    )
    # unmount EC + delete shards everywhere
    for sv in holders:
        env.post(f"{sv.http}/admin/ec/unmount", {"volume": vid})
        env.post(
            f"{sv.http}/admin/ec/delete_shards",
            {"volume": vid, "collection": collection,
             "shards": list(range(TOTAL_SHARDS)), "delete_index": True},
        )
    return f"ec.decode volume {vid}: reconstructed on {target.id}"


def plan_rebuild(env: CommandEnv, vid: int, collection: str = "") -> dict | None:
    """The rebuild plan for one EC volume: which holder rebuilds, which
    shards it pulls from whom, which shards are missing. None when all 14
    shards are present; raises when fewer than 10 survive. Shared between
    the `ec.rebuild` verb and the maintenance daemon's ec_rebuild executor."""
    servers = env.servers()
    holders = [sv for sv in servers if vid in sv.ec_shards]
    present = sorted({s for sv in holders for s in sv.ec_shards[vid]})
    missing = [s for s in range(TOTAL_SHARDS) if s not in present]
    if not missing:
        return None
    if len(present) < DATA_SHARDS:
        raise ShellError(
            f"volume {vid}: only {len(present)} shards left, cannot rebuild"
        )
    # rebuilder = holder with the most local shards and enough free slots
    rebuilder = max(holders, key=lambda sv: (len(sv.ec_shards[vid]), sv.free_slots()))
    local = set(rebuilder.ec_shards[vid])
    pulls = []
    for sv in holders:
        if sv.id == rebuilder.id:
            continue
        pull = [s for s in sv.ec_shards[vid] if s not in local]
        if pull:
            pulls.append({"source": sv.id, "source_url": sv.http,
                          "shards": pull})
            local.update(pull)
    return {
        "volume": vid, "collection": collection,
        "rebuilder": rebuilder.id, "rebuilder_url": rebuilder.http,
        "missing": missing, "present": present, "pulls": pulls,
        "own": sorted(rebuilder.ec_shards[vid]),
    }


def describe_rebuild(plan: dict) -> list[str]:
    """Display lines for a plan_rebuild plan — shared by the verb's
    dry-run output and /debug/maintenance history."""
    steps = [
        f"pull shards {p['shards']} from {p['source']} to"
        f" {plan['rebuilder']}" for p in plan["pulls"]
    ]
    steps.append(f"rebuild shards {plan['missing']} on {plan['rebuilder']}")
    return steps


def apply_rebuild(env: CommandEnv, plan: dict) -> list[int]:
    """Execute a plan_rebuild plan: pull inputs, rebuild on the Pallas
    RS(10,4) path, drop pulled-only inputs, re-mount."""
    vid, collection = plan["volume"], plan["collection"]
    rb = plan["rebuilder_url"]
    for p in plan["pulls"]:
        env.post(
            f"{rb}/admin/ec/copy",
            {"volume": vid, "collection": collection,
             "shards": p["shards"], "source": p["source_url"]},
            timeout=3600,
        )
    out = env.post(
        f"{rb}/admin/ec/rebuild",
        {"volume": vid, "collection": collection}, timeout=3600,
    )
    # drop shards the rebuilder only pulled as rebuild inputs, keep its own +
    # the rebuilt ones, then re-mount to refresh its shard list
    pulled = [s for p in plan["pulls"] for s in p["shards"]]
    keep = set(plan["own"]) | set(out.get("rebuilt", []))
    drop = [s for s in pulled if s not in keep]
    if drop:
        env.post(
            f"{rb}/admin/ec/delete_shards",
            {"volume": vid, "collection": collection, "shards": drop},
        )
    env.post(f"{rb}/admin/ec/mount",
             {"volume": vid, "collection": collection})
    return out.get("rebuilt", plan["missing"])


@command("ec.rebuild", "-volumeId <n> [-collection name] [-dryRun|-apply] —"
         " rebuild missing shards (ref command_ec_rebuild.go:99)",
         needs_lock=True)
def cmd_ec_rebuild(env: CommandEnv, args: list[str]) -> str:
    flags = parse_flags(args)
    vid = int(flags["volumeId"])
    collection = flags.get("collection", "")
    plan = plan_rebuild(env, vid, collection)
    if plan is None:
        return f"volume {vid}: all {TOTAL_SHARDS} shards present"
    if dry_run_flag(flags):
        return render_plan("ec.rebuild", describe_rebuild(plan))
    rebuilt = apply_rebuild(env, plan)
    return f"volume {vid}: rebuilt shards {rebuilt} on {plan['rebuilder']}"


@command("ec.balance", "spread EC shards evenly across servers "
         "(ref command_ec_balance.go)", needs_lock=True)
def cmd_ec_balance(env: CommandEnv, args: list[str]) -> str:
    servers = env.servers()
    moves = []
    # per EC volume: if one server holds more than ceil(14/N) shards, move extras
    vids = sorted({vid for sv in servers for vid in sv.ec_shards})
    for vid in vids:
        holders = [sv for sv in servers if vid in sv.ec_shards]
        collection = ""
        all_servers = sorted(servers, key=lambda sv: len(sv.ec_shards.get(vid, [])))
        cap = -(-TOTAL_SHARDS // max(len(servers), 1))  # ceil
        for sv in holders:
            extra = len(sv.ec_shards[vid]) - cap
            while extra > 0:
                shard = sv.ec_shards[vid][-1]
                # move to the server with fewest shards of this volume
                dst = all_servers[0]
                if dst.id == sv.id:
                    break
                env.post(
                    f"{dst.http}/admin/ec/copy",
                    {"volume": vid, "collection": collection, "shards": [shard],
                     "source": sv.http},
                    timeout=3600,
                )
                env.post(f"{dst.http}/admin/ec/mount",
                         {"volume": vid, "collection": collection})
                env.post(
                    f"{sv.http}/admin/ec/delete_shards",
                    {"volume": vid, "collection": collection, "shards": [shard]},
                )
                sv.ec_shards[vid].remove(shard)
                dst.ec_shards.setdefault(vid, []).append(shard)
                moves.append(f"volume {vid} shard {shard}: {sv.id} -> {dst.id}")
                extra -= 1
                all_servers.sort(key=lambda s: len(s.ec_shards.get(vid, [])))
    return "\n".join(moves) if moves else "EC shards already balanced"
