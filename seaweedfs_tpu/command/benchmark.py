"""`weed-tpu benchmark`: self-contained write/read load generator with
latency percentiles (reference: `weed/command/benchmark.go:113-260`)."""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import random
import time


def _percentiles(samples: list[float]) -> dict:
    if not samples:
        return {}
    s = sorted(samples)

    def pct(p: float) -> float:
        return s[min(len(s) - 1, int(p * len(s)))]

    return {
        "p50_ms": round(pct(0.50) * 1000, 2),
        "p90_ms": round(pct(0.90) * 1000, 2),
        "p99_ms": round(pct(0.99) * 1000, 2),
        "max_ms": round(s[-1] * 1000, 2),
    }


def run(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu benchmark")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument("-n", type=int, default=1000, help="number of files")
    p.add_argument("-size", type=int, default=1024, help="file size bytes")
    p.add_argument("-c", type=int, default=16, help="concurrency")
    p.add_argument("-collection", default="benchmark")
    p.add_argument("-seed", type=int, default=0)
    opts = p.parse_args(args)
    report = run_benchmark(
        opts.master, n=opts.n, size=opts.size, c=opts.c,
        collection=opts.collection, seed=opts.seed,
    )
    print(json.dumps(report, indent=2))
    return 0


def run_benchmark(
    master: str,
    n: int = 1000,
    size: int = 1024,
    c: int = 16,
    collection: str = "benchmark",
    seed: int = 0,
) -> dict:
    """Write n files of `size` bytes at concurrency c, then read them back
    shuffled; returns the req/s + latency-percentile report (the reference's
    `weed benchmark` loop, `benchmark.go:113-260`)."""
    import types

    from seaweedfs_tpu.server.httpd import PooledHTTP, peer_url

    opts = types.SimpleNamespace(
        master=master, n=n, size=size, c=c, collection=collection, seed=seed
    )
    masters = [peer_url(u).rstrip("/") for u in opts.master.split(",") if u]
    state = {"master": masters[0]}
    pool = PooledHTTP()  # keep-alive per worker thread, like the Go client
    rng = random.Random(opts.seed)
    payload = bytes(rng.randrange(256) for _ in range(opts.size))

    def assign() -> dict:
        for _ in range(len(masters) + 2):  # follow raft leader hints
            status, _, body = pool.request(
                "GET",
                f"{state['master']}/dir/assign?count=1"
                f"&collection={opts.collection}",
            )
            if status >= 400:
                try:
                    out = json.loads(body)
                except ValueError:
                    raise IOError(f"assign -> {status}: {body[:120]!r}")
                leader = out.get("leader")
                if out.get("error") == "raft.not.leader" and leader:
                    state["master"] = peer_url(leader).rstrip("/")
                    continue
                raise IOError(f"assign -> {status}: {out}")
            out = json.loads(body)
            if out.get("error"):
                raise IOError(f"assign: {out['error']}")
            return out
        raise IOError("assign: no leader found")

    write_lat: list[float] = []
    fids: list[str] = []

    def do_write(i: int):
        t0 = time.perf_counter()
        a = assign()
        url = f"{peer_url(a['publicUrl'])}/{a['fid']}"
        headers = {}
        if a.get("auth"):
            headers["Authorization"] = f"BEARER {a['auth']}"
        status, _, body = pool.request("POST", url, payload, headers)
        if status >= 300:
            raise IOError(f"upload -> {status}: {body[:120]!r}")
        # remember the volume location: the reader reuses it instead of
        # paying a lookup per read (the Go benchmark caches locations too)
        return a["fid"], a["publicUrl"], time.perf_counter() - t0

    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(opts.c) as ex:
        for fid, loc, dt in ex.map(do_write, range(opts.n)):
            fids.append((fid, loc))
            write_lat.append(dt)
    write_wall = time.perf_counter() - t_start

    read_lat: list[float] = []

    def do_read(item):
        fid, loc = item
        t0 = time.perf_counter()
        status, _, data = pool.request("GET", f"{peer_url(loc)}/{fid}")
        assert status == 200 and len(data) == opts.size, (status, len(data))
        return time.perf_counter() - t0

    order = fids[:]
    rng.shuffle(order)
    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(opts.c) as ex:
        read_lat = list(ex.map(do_read, order))
    read_wall = time.perf_counter() - t_start

    return {
        "write": {
            "requests": opts.n,
            "req_per_sec": round(opts.n / write_wall, 1),
            "mb_per_sec": round(opts.n * opts.size / write_wall / 1e6, 2),
            **_percentiles(write_lat),
        },
        "read": {
            "requests": len(order),
            "req_per_sec": round(len(order) / read_wall, 1),
            "mb_per_sec": round(len(order) * opts.size / read_wall / 1e6, 2),
            **_percentiles(read_lat),
        },
    }
