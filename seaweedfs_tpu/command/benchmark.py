"""`weed-tpu benchmark`: self-contained write/read load generator with
latency percentiles (reference: `weed/command/benchmark.go:113-260`)."""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import random
import time


def _percentiles(samples: list[float]) -> dict:
    if not samples:
        return {}
    s = sorted(samples)

    def pct(p: float) -> float:
        return s[min(len(s) - 1, int(p * len(s)))]

    return {
        "p50_ms": round(pct(0.50) * 1000, 2),
        "p90_ms": round(pct(0.90) * 1000, 2),
        "p99_ms": round(pct(0.99) * 1000, 2),
        "max_ms": round(s[-1] * 1000, 2),
    }


def run(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu benchmark")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument("-n", type=int, default=1000, help="number of files")
    p.add_argument("-size", type=int, default=1024, help="file size bytes")
    p.add_argument("-c", type=int, default=16, help="concurrency")
    p.add_argument("-collection", default="benchmark")
    p.add_argument("-seed", type=int, default=0)
    opts = p.parse_args(args)
    report = run_benchmark(
        opts.master, n=opts.n, size=opts.size, c=opts.c,
        collection=opts.collection, seed=opts.seed,
    )
    print(json.dumps(report, indent=2))
    return 0


def run_benchmark(
    master: str,
    n: int = 1000,
    size: int = 1024,
    c: int = 16,
    collection: str = "benchmark",
    seed: int = 0,
) -> dict:
    """Write n files of `size` bytes at concurrency c, then read them back
    shuffled; returns the req/s + latency-percentile report (the reference's
    `weed benchmark` loop, `benchmark.go:113-260`)."""
    import types

    from seaweedfs_tpu.filer.wdclient import WeedClient

    opts = types.SimpleNamespace(
        master=master, n=n, size=size, c=c, collection=collection, seed=seed
    )
    client = WeedClient(opts.master)
    rng = random.Random(opts.seed)
    payload = bytes(rng.randrange(256) for _ in range(opts.size))

    write_lat: list[float] = []
    fids: list[str] = []

    def do_write(i: int):
        t0 = time.perf_counter()
        out = client.upload(payload, collection=opts.collection)
        dt = time.perf_counter() - t0
        return out["fid"], dt

    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(opts.c) as ex:
        for fid, dt in ex.map(do_write, range(opts.n)):
            fids.append(fid)
            write_lat.append(dt)
    write_wall = time.perf_counter() - t_start

    read_lat: list[float] = []

    def do_read(fid: str):
        t0 = time.perf_counter()
        data = client.fetch(fid)
        assert len(data) == opts.size
        return time.perf_counter() - t0

    order = fids[:]
    rng.shuffle(order)
    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(opts.c) as ex:
        read_lat = list(ex.map(do_read, order))
    read_wall = time.perf_counter() - t_start

    return {
        "write": {
            "requests": opts.n,
            "req_per_sec": round(opts.n / write_wall, 1),
            "mb_per_sec": round(opts.n * opts.size / write_wall / 1e6, 2),
            **_percentiles(write_lat),
        },
        "read": {
            "requests": len(order),
            "req_per_sec": round(len(order) / read_wall, 1),
            "mb_per_sec": round(len(order) * opts.size / read_wall / 1e6, 2),
            **_percentiles(read_lat),
        },
    }
