"""`weed-tpu benchmark`: self-contained write/read load generator with
latency percentiles (reference: `weed/command/benchmark.go:113-260`)."""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import random
import time


def _percentiles(samples: list[float]) -> dict:
    if not samples:
        return {}
    s = sorted(samples)

    def pct(p: float) -> float:
        return s[min(len(s) - 1, int(p * len(s)))]

    return {
        "p50_ms": round(pct(0.50) * 1000, 2),
        "p90_ms": round(pct(0.90) * 1000, 2),
        "p99_ms": round(pct(0.99) * 1000, 2),
        "max_ms": round(s[-1] * 1000, 2),
    }


def run(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu benchmark")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument("-n", type=int, default=1000, help="number of files")
    p.add_argument("-size", type=int, default=1024, help="file size bytes")
    p.add_argument("-c", type=int, default=16, help="concurrency")
    p.add_argument("-collection", default="benchmark")
    p.add_argument("-seed", type=int, default=0)
    p.add_argument(
        "-assignBatch", type=int, default=64,
        help="fids minted per master assign (count=N + fid_delta sub-fids); "
        "1 = one assign RPC per file",
    )
    opts = p.parse_args(args)
    report = run_benchmark(
        opts.master, n=opts.n, size=opts.size, c=opts.c,
        collection=opts.collection, seed=opts.seed,
        assign_batch=opts.assignBatch,
    )
    print(json.dumps(report, indent=2))
    return 0


def run_benchmark(
    master: str,
    n: int = 1000,
    size: int = 1024,
    c: int = 16,
    collection: str = "benchmark",
    seed: int = 0,
    assign_batch: int = 64,
) -> dict:
    """Write n files of `size` bytes at concurrency c, then read them back
    shuffled; returns the req/s + latency-percentile report (the reference's
    `weed benchmark` loop, `benchmark.go:113-260`).

    Assigns are batched: one `/dir/assign?count=N` mints N sequential fids
    (`fid`, `fid_1`, ... — the volume server resolves the `_delta` suffix,
    `needle.go:ParsePath`), so the allocation RPC amortizes across
    `assign_batch` uploads instead of doubling every write's round trips.
    Falls back to per-file assigns when the master mints per-fid write JWTs
    (a batch token would only cover the base fid)."""
    import types

    from seaweedfs_tpu.server.httpd import PooledHTTP, peer_url

    opts = types.SimpleNamespace(
        master=master, n=n, size=size, c=c, collection=collection, seed=seed
    )
    assign_batch = max(1, assign_batch)
    masters = [peer_url(u).rstrip("/") for u in opts.master.split(",") if u]
    state = {"master": masters[0]}
    pool = PooledHTTP()  # keep-alive per worker thread, like the Go client
    rng = random.Random(opts.seed)
    payload = bytes(rng.randrange(256) for _ in range(opts.size))

    def assign(count: int = 1) -> dict:
        for _ in range(len(masters) + 2):  # follow raft leader hints
            status, _, body = pool.request(
                "GET",
                f"{state['master']}/dir/assign?count={count}"
                f"&collection={opts.collection}",
            )
            if status >= 400:
                try:
                    out = json.loads(body)
                except ValueError:
                    raise IOError(f"assign -> {status}: {body[:120]!r}")
                leader = out.get("leader")
                if out.get("error") == "raft.not.leader" and leader:
                    state["master"] = peer_url(leader).rstrip("/")
                    continue
                raise IOError(f"assign -> {status}: {out}")
            out = json.loads(body)
            if out.get("error"):
                raise IOError(f"assign: {out['error']}")
            return out
        raise IOError("assign: no leader found")

    write_lat: list[float] = []
    fids: list[str] = []

    import collections
    import threading

    fid_pool: collections.deque = collections.deque()
    fid_lock = threading.Lock()
    batching = {"on": assign_batch > 1}

    def next_fid() -> tuple[str, str, str | None]:
        """One pre-minted (fid, location, auth) — refills with a single
        count=assign_batch RPC when the pool runs dry. Once batching is
        OFF, assigns run per-call OUTSIDE the lock: holding it across the
        RPC would serialize all c workers behind one master round-trip
        (worse than the unbatched client this replaces)."""
        if not batching["on"]:
            a = assign(count=1)
            return a["fid"], a["publicUrl"], a.get("auth")
        with fid_lock:
            if batching["on"] and not fid_pool:
                a = assign(count=assign_batch)
                base, loc = a["fid"], a["publicUrl"]
                got = int(a.get("count", 1))
                if a.get("auth") or got < 2:
                    # per-fid JWT (or a master that ignored count): the
                    # delta sub-fids would be unauthorized/unminted
                    batching["on"] = False
                    fid_pool.append((base, loc, a.get("auth")))
                else:
                    fid_pool.extend(
                        (base if i == 0 else f"{base}_{i}", loc, None)
                        for i in range(got)
                    )
            if fid_pool:
                return fid_pool.popleft()
        a = assign(count=1)  # batching just disabled and the pool drained
        return a["fid"], a["publicUrl"], a.get("auth")

    def do_write(i: int):
        t0 = time.perf_counter()
        fid, loc, auth = next_fid()
        url = f"{peer_url(loc)}/{fid}"
        headers = {}
        if auth:
            headers["Authorization"] = f"BEARER {auth}"
        status, _, body = pool.request("POST", url, payload, headers)
        if status >= 300:
            raise IOError(f"upload -> {status}: {body[:120]!r}")
        # remember the volume location: the reader reuses it instead of
        # paying a lookup per read (the Go benchmark caches locations too)
        return fid, loc, time.perf_counter() - t0

    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(opts.c) as ex:
        for fid, loc, dt in ex.map(do_write, range(opts.n)):
            fids.append((fid, loc))
            write_lat.append(dt)
    write_wall = time.perf_counter() - t_start

    read_lat: list[float] = []

    def do_read(item):
        fid, loc = item
        t0 = time.perf_counter()
        status, _, data = pool.request("GET", f"{peer_url(loc)}/{fid}")
        assert status == 200 and len(data) == opts.size, (status, len(data))
        return time.perf_counter() - t0

    order = fids[:]
    rng.shuffle(order)
    t_start = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(opts.c) as ex:
        read_lat = list(ex.map(do_read, order))
    read_wall = time.perf_counter() - t_start

    return {
        "write": {
            "requests": opts.n,
            "req_per_sec": round(opts.n / write_wall, 1),
            "mb_per_sec": round(opts.n * opts.size / write_wall / 1e6, 2),
            **_percentiles(write_lat),
        },
        "read": {
            "requests": len(order),
            "req_per_sec": round(len(order) / read_wall, 1),
            "mb_per_sec": round(len(order) * opts.size / read_wall / 1e6, 2),
            **_percentiles(read_lat),
        },
    }
