"""`weed-tpu scaffold` — print starter TOML configs
(`weed/command/scaffold.go` + `weed/command/scaffold/*.toml`)."""

from __future__ import annotations

import argparse

TEMPLATES = {
    "security": '''\
# security.toml — JWT signing + IP guard
# put this file to ./ , ~/.seaweedfs/ , or /etc/seaweedfs/

[jwt.signing]
key = ""                      # base64 or raw secret; empty = auth disabled
expires_after_seconds = 10

[jwt.signing.read]
key = ""
expires_after_seconds = 60

[guard]
white_list = []               # e.g. ["127.0.0.1", "10.0.0.0/8"]

[tls]                         # mutual TLS for every listener + client
ca = ""                       # e.g. "/etc/seaweedfs/ca.pem"; empty = plain HTTP
cert = ""                     # this node's certificate (signed by ca)
key = ""                      # this node's private key
allowed_commonNames = ""      # e.g. "master1,volume*"; "" = any CA-signed cert
''',
    "filer": '''\
# filer.toml — filer metadata store
[filer.options]
recursive_delete = false

[memory]                      # non-durable, dev only
enabled = true

[sqlite]
enabled = false
dbFile = "./filer.db"

[leveldb]                     # embedded WAL+snapshot KV store
enabled = false
dir = "./filerldb"

[lsm]                         # embedded LSM/SSTable store (leveldb-class;
enabled = false               # cold metadata stays on disk)
dir = "./filerlsm"
''',
    "master": '''\
# master.toml — volume growth + sequencer
[master.volume_growth]
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1

[master.sequencer]
type = "raft"                 # raft | snowflake
''',
    "notification": '''\
# notification.toml — filer mutation event bus
[notification.log]
enabled = false

[notification.file]
enabled = false
spool_dir = "./notify-spool"

[notification.kafka]
enabled = false
hosts = ["localhost:9092"]
topic = "seaweedfs_filer"
''',
    "replication": '''\
# replication.toml — filer.replicate sinks
[source.filer]
enabled = true
grpcAddress = "localhost:8888"

[sink.local]
enabled = false
directory = "/backup"

[sink.filer]
enabled = false
grpcAddress = "localhost:8889"
''',
    "shell": '''\
# shell.toml — admin shell defaults
[cluster]
default = "localhost"

[cluster.localhost]
master = "localhost:9333"
filer = "localhost:8888"
''',
}


def run(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu scaffold")
    p.add_argument("-config", default="filer",
                   choices=sorted(TEMPLATES.keys()))
    p.add_argument("-output", default="", help="write to dir instead of stdout")
    opts = p.parse_args(args)
    body = TEMPLATES[opts.config]
    if opts.output:
        import os

        path = os.path.join(opts.output, f"{opts.config}.toml")
        with open(path, "w") as f:
            f.write(body)
        print(f"wrote {path}")
    else:
        print(body, end="")
    return 0
