"""Server subcommands: master / volume / filer / combined server
(reference: `weed/command/master.go`, `volume.go`, `filer.go`, `server.go`).
"""

from __future__ import annotations

import argparse
import signal
import threading

from seaweedfs_tpu.server.httpd import peer_url


def _load_security():
    """security.toml discovery once per process: JWT keys + IP guard for
    the servers, and the [tls] section installed process-wide (mTLS on
    every listener and outbound client, `weed/security/tls.go`)."""
    from seaweedfs_tpu.security import load_security_config

    cfg = load_security_config()
    cfg.apply_tls()
    return cfg


def _wait_forever() -> int:
    stop = threading.Event()

    def handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, handler)
    signal.signal(signal.SIGTERM, handler)
    stop.wait()
    return 0


def _add_faults_flag(p) -> None:
    p.add_argument(
        "-faults", nargs="?", const="", default=None,
        help="enable fault injection for this process and optionally arm"
             " points at boot: point=mode[:k=v,...][;point=mode...] — e.g."
             " 'volume.read.dat=error:rate=0.5;master.assign=latency:ms=20'."
             " A bare -faults enables runtime control only"
             " (POST /debug/faults / cluster.faults); without the flag the"
             " runtime route 403s.",
    )


def _add_telemetry_flags(p) -> None:
    p.add_argument(
        "-telemetry.dir", dest="telemetry_dir", default=None,
        help="durable telemetry spool directory (stats/store.py):"
             " history samples + flight-recorder events persist in CRC'd"
             " segment files (5s raw -> 1m -> 10m rollups) and replay on"
             " restart, so /debug/metrics/history, /debug/events and"
             " cluster.why survive a crash; unset = in-memory only",
    )
    p.add_argument(
        "-telemetry.retention", dest="telemetry_retention", type=float,
        default=None,
        help="telemetry spool byte budget in MB (default 64), carved"
             " across the raw/1m/10m/event tiers; oldest segments evict"
             " first so the spool never fills the disk",
    )


def _add_qos_flag(p) -> None:
    p.add_argument(
        "-qos.limits", dest="qos_limits", default=None,
        help="arm QoS admission control (qos/admission.py) with"
             " per-collection token-bucket limits:"
             " 'tenant-a=100,tenant-b=50:200,*=25' (rps[:burst], '*' ="
             " default for unlisted tenants). Also starts the SLO-burn"
             " actuator; limits stay adjustable at runtime via"
             " POST /qos/limits and the cluster.qos shell verb. Unset ="
             " admission disarmed (one attribute check per request)",
    )


def _arm_faults(opts) -> None:
    if getattr(opts, "faults", None) is None:
        return
    from seaweedfs_tpu.util import faults

    faults.enable()  # opt the process into runtime POST /debug/faults
    if opts.faults:
        armed = faults.arm_from_spec(opts.faults)
        print(f"fault injection armed: {', '.join(armed)}")


def run_master(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu master")
    p.add_argument("-port", type=int, default=9333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-mdir", default=None, help="metadata dir (sequence state)")
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    p.add_argument("-defaultReplication", default="000")
    p.add_argument("-garbageThreshold", type=float, default=0.3)
    p.add_argument("-pulseSeconds", type=int, default=5)
    p.add_argument("-peers", default="",
                   help="comma-separated master urls (raft HA; include self)")
    p.add_argument("-slowMs", dest="slow_ms", type=float, default=None,
                   help="log requests slower than this many ms for this "
                        "server's role (overrides SEAWEEDFS_TPU_SLOW_MS)")
    p.add_argument("-maintenance", action="store_true",
                   help="run the autonomous maintenance daemon "
                        "(detect -> plan -> heal; off by default)")
    p.add_argument("-maintenance.dryRun", dest="maintenance_dry_run",
                   action="store_true",
                   help="maintenance plans repairs without executing them")
    p.add_argument("-maintenance.interval", dest="maintenance_interval",
                   type=float, default=None,
                   help="maintenance scan interval seconds "
                        "(default: pulseSeconds)")
    p.add_argument("-repair.lazyWindow", dest="repair_lazy_window",
                   type=float, default=0.0,
                   help="defer single-shard ec_rebuild dispatch up to this "
                        "many seconds so co-stripe losses coalesce into "
                        "one multi-target chain pass (0 = immediate)")
    p.add_argument("-ec.online", dest="ec_online", default="",
                   help="comma-separated collections whose volumes stream-"
                        "encode RS(10,4) parity on ingest ('*' = all); "
                        "replication degrades to parity-only for them")
    p.add_argument("-ec.online.block", dest="ec_online_block", type=int,
                   default=None,
                   help="online-EC stripe block bytes per shard "
                        "(default 1MB)")
    _add_telemetry_flags(p)
    _add_faults_flag(p)
    opts = p.parse_args(args)
    _arm_faults(opts)
    from seaweedfs_tpu.server.master import MasterServer

    sec = _load_security()
    m = MasterServer(
        host=opts.ip,
        port=opts.port,
        volume_size_limit_mb=opts.volumeSizeLimitMB,
        pulse_seconds=opts.pulseSeconds,
        default_replication=opts.defaultReplication,
        meta_dir=opts.mdir,
        garbage_threshold=opts.garbageThreshold,
        security=sec,
        peers=[peer_url(u)
               for u in opts.peers.split(",") if u],
        raft_dir=opts.mdir,
        slow_ms=opts.slow_ms,
        maintenance=opts.maintenance or opts.maintenance_dry_run,
        maintenance_dry_run=opts.maintenance_dry_run,
        maintenance_interval=opts.maintenance_interval,
        repair_lazy_window=opts.repair_lazy_window,
        ec_online=opts.ec_online,
        ec_online_block=opts.ec_online_block,
        telemetry_dir=opts.telemetry_dir,
        telemetry_retention_mb=opts.telemetry_retention,
    )
    m.start()
    print(f"master listening at {m.url}")
    return _wait_forever()


def run_volume(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu volume")
    p.add_argument("-port", type=int, default=8080)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-dir", default="./data", help="comma-separated data dirs")
    p.add_argument("-mserver", default="http://127.0.0.1:9333")
    p.add_argument("-dataCenter", default="")
    p.add_argument("-rack", default="")
    p.add_argument("-max", type=int, default=100)
    p.add_argument("-publicUrl", default="")
    p.add_argument("-pulseSeconds", type=int, default=5)
    p.add_argument("-localSocket", default=None,
                   help="also serve on this unix domain socket")
    p.add_argument("-slowMs", dest="slow_ms", type=float, default=None,
                   help="log requests slower than this many ms for this "
                        "server's role (overrides SEAWEEDFS_TPU_SLOW_MS)")
    p.add_argument("-scrub.interval", dest="scrub_interval", type=float,
                   default=0.0,
                   help="seconds between background integrity-scrub passes"
                        " (CRC every needle, parity-check EC stripes, sweep"
                        " rebuild tmp litter); 0 disables the loop —"
                        " /admin/scrub/run and volume.scrub still work")
    p.add_argument("-scrub.rate", dest="scrub_rate", type=float,
                   default=8.0,
                   help="scrub read-budget in MB/s (token bucket; scrubbing"
                        " never starves foreground traffic)")
    _add_telemetry_flags(p)
    _add_faults_flag(p)
    opts = p.parse_args(args)
    _arm_faults(opts)
    from seaweedfs_tpu.server.volume import VolumeServer

    sec = _load_security()
    vs = VolumeServer(
        opts.dir.split(","),
        opts.mserver,
        security=sec,
        host=opts.ip,
        port=opts.port,
        public_url=opts.publicUrl,
        data_center=opts.dataCenter,
        rack=opts.rack,
        pulse_seconds=opts.pulseSeconds,
        max_volume_count=opts.max,
        local_socket=opts.localSocket,
        slow_ms=opts.slow_ms,
        scrub_interval=opts.scrub_interval,
        scrub_rate_mb=opts.scrub_rate,
        telemetry_dir=opts.telemetry_dir,
        telemetry_retention_mb=opts.telemetry_retention,
    )
    vs.start()
    print(f"volume server listening at {vs.url}")
    return _wait_forever()


def run_filer(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu filer")
    p.add_argument("-port", type=int, default=8888)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument(
        "-store", default="memory", choices=["memory", "sqlite", "leveldb", "lsm", "redis", "etcd", "mysql", "postgres"]
    )
    p.add_argument("-storePath", default=None)
    p.add_argument("-maxMB", type=int, default=4, help="chunk size")
    p.add_argument("-collection", default="")
    p.add_argument("-defaultReplicaPlacement", default="")
    p.add_argument("-encryptVolumeData", action="store_true",
                   help="AES-GCM encrypt chunk data on volume servers")
    p.add_argument("-compressData", default="true", choices=["true", "false"],
                   help="gzip-compress compressible chunks")
    p.add_argument("-chunkCacheDir", default=None,
                   help="on-disk tiered chunk cache directory")
    p.add_argument("-notification.spool", dest="notification_spool",
                   default=None,
                   help="publish metadata events to this file-queue spool dir")
    p.add_argument("-peers", default="",
                   help="comma-separated peer filer urls (lock ring + meta sync)")
    p.add_argument("-dedup", action="store_true",
                   help="content-defined-chunking dedup on uploads "
                        "(filer/dedup.py; incompatible with cipher)")
    p.add_argument("-localSocket", default=None,
                   help="also serve on this unix domain socket "
                        "(same-host mounts skip TCP; -filer.localSocket)")
    p.add_argument("-slowMs", dest="slow_ms", type=float, default=None,
                   help="log requests slower than this many ms for this "
                        "server's role (overrides SEAWEEDFS_TPU_SLOW_MS)")
    _add_telemetry_flags(p)
    _add_faults_flag(p)
    _add_qos_flag(p)
    opts = p.parse_args(args)
    _arm_faults(opts)
    from seaweedfs_tpu.server.filer import FilerServer

    sec = _load_security()
    queue = None
    if opts.notification_spool:
        from seaweedfs_tpu.notification import FileQueue

        queue = FileQueue(opts.notification_spool)

    f = FilerServer(
        opts.master,
        host=opts.ip,
        port=opts.port,
        store_kind=opts.store,
        store_path=opts.storePath,
        local_socket=opts.localSocket,
        chunk_size_mb=opts.maxMB,
        default_replication=opts.defaultReplicaPlacement,
        collection=opts.collection,
        cipher=opts.encryptVolumeData,
        compress=opts.compressData == "true",
        chunk_cache_dir=opts.chunkCacheDir,
        notification_queue=queue,
        peers=[peer_url(u)
               for u in opts.peers.split(",") if u],
        dedup=opts.dedup,
        security=sec,
        slow_ms=opts.slow_ms,
        telemetry_dir=opts.telemetry_dir,
        telemetry_retention_mb=opts.telemetry_retention,
        qos_limits=opts.qos_limits,
    )
    f.start()
    print(f"filer listening at {f.url}")
    return _wait_forever()


def run_server(args: list[str]) -> int:
    """Combined master + volume + filer (+S3) in one process
    (`weed/command/server.go`)."""
    p = argparse.ArgumentParser(prog="weed-tpu server")
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-master.port", dest="master_port", type=int, default=9333)
    p.add_argument("-volume.port", dest="volume_port", type=int, default=8080)
    p.add_argument("-filer.port", dest="filer_port", type=int, default=8888)
    p.add_argument("-s3.port", dest="s3_port", type=int, default=8333)
    p.add_argument("-dir", default="./data")
    p.add_argument("-filer", action="store_true", help="also run filer")
    p.add_argument("-s3", action="store_true", help="also run S3 gateway")
    p.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    p.add_argument("-defaultReplication", default="000")
    p.add_argument("-filer.store", dest="filer_store", default="memory")
    p.add_argument("-filer.storePath", dest="filer_store_path", default=None)
    p.add_argument("-filer.encryptVolumeData", dest="filer_cipher",
                   action="store_true")
    p.add_argument("-filer.compressData", dest="filer_compress",
                   default="true", choices=["true", "false"])
    p.add_argument("-filer.dedup", dest="filer_dedup", action="store_true",
                   help="content-defined-chunking dedup on filer uploads")
    p.add_argument("-s3.config", dest="s3_config", default=None,
                   help="identities json (s3.json)")
    p.add_argument("-maintenance", action="store_true",
                   help="run the autonomous maintenance daemon "
                        "(detect -> plan -> heal; off by default)")
    p.add_argument("-maintenance.dryRun", dest="maintenance_dry_run",
                   action="store_true",
                   help="maintenance plans repairs without executing them")
    p.add_argument("-maintenance.interval", dest="maintenance_interval",
                   type=float, default=None,
                   help="maintenance scan interval seconds "
                        "(default: pulseSeconds)")
    p.add_argument("-repair.lazyWindow", dest="repair_lazy_window",
                   type=float, default=0.0,
                   help="defer single-shard ec_rebuild dispatch up to this "
                        "many seconds so co-stripe losses coalesce into "
                        "one multi-target chain pass (0 = immediate)")
    p.add_argument("-ec.online", dest="ec_online", default="",
                   help="comma-separated collections whose volumes stream-"
                        "encode RS(10,4) parity on ingest ('*' = all)")
    p.add_argument("-ec.online.block", dest="ec_online_block", type=int,
                   default=None,
                   help="online-EC stripe block bytes per shard "
                        "(default 1MB)")
    p.add_argument("-scrub.interval", dest="scrub_interval", type=float,
                   default=0.0,
                   help="seconds between background integrity-scrub passes"
                        " on the volume server; 0 disables the loop")
    p.add_argument("-scrub.rate", dest="scrub_rate", type=float,
                   default=8.0,
                   help="scrub read-budget in MB/s (token bucket)")
    _add_telemetry_flags(p)
    _add_faults_flag(p)
    _add_qos_flag(p)
    opts = p.parse_args(args)
    _arm_faults(opts)

    from seaweedfs_tpu.server.master import MasterServer
    from seaweedfs_tpu.server.volume import VolumeServer

    sec = _load_security()
    m = MasterServer(
        host=opts.ip,
        port=opts.master_port,
        volume_size_limit_mb=opts.volumeSizeLimitMB,
        default_replication=opts.defaultReplication,
        security=sec,
        maintenance=opts.maintenance or opts.maintenance_dry_run,
        maintenance_dry_run=opts.maintenance_dry_run,
        maintenance_interval=opts.maintenance_interval,
        repair_lazy_window=opts.repair_lazy_window,
        ec_online=opts.ec_online,
        ec_online_block=opts.ec_online_block,
        telemetry_dir=opts.telemetry_dir,
        telemetry_retention_mb=opts.telemetry_retention,
    )
    m.start()
    print(f"master listening at {m.url}")
    vs = VolumeServer(
        opts.dir.split(","), m.url, host=opts.ip, port=opts.volume_port,
        security=sec,
        scrub_interval=opts.scrub_interval,
        scrub_rate_mb=opts.scrub_rate,
    )
    vs.start()
    print(f"volume server listening at {vs.url}")
    if opts.filer or opts.s3:
        from seaweedfs_tpu.server.filer import FilerServer

        f = FilerServer(
            m.url,
            host=opts.ip,
            port=opts.filer_port,
            store_kind=opts.filer_store,
            store_path=opts.filer_store_path,
            cipher=opts.filer_cipher,
            compress=opts.filer_compress == "true",
            dedup=opts.filer_dedup,
            security=sec,
            qos_limits=opts.qos_limits,
        )
        f.start()
        print(f"filer listening at {f.url}")
        if opts.s3:
            import json as _json

            from seaweedfs_tpu.s3api import S3Server

            config = None
            if opts.s3_config:
                with open(opts.s3_config) as fh:
                    config = _json.load(fh)
            s3 = S3Server(f.url, host=opts.ip, port=opts.s3_port,
                          config=config, master_url=m.url,
                          qos_limits=opts.qos_limits)
            s3.start()
            print(f"s3 gateway listening at {s3.url}")
    return _wait_forever()


def run_iam(args: list[str]) -> int:
    """Standalone IAM API against a running filer (`weed/command/iam.go`)."""
    p = argparse.ArgumentParser(prog="weed-tpu iam")
    p.add_argument("-port", type=int, default=8111)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="http://127.0.0.1:8888")
    opts = p.parse_args(args)
    from seaweedfs_tpu.iamapi import IamServer

    _load_security()

    filer = opts.filer
    if not filer.startswith("http"):
        filer = peer_url(filer)
    srv = IamServer(filer, host=opts.ip, port=opts.port)
    srv.start()
    print(f"iam api listening at {srv.url}")
    return _wait_forever()


def run_s3(args: list[str]) -> int:
    """Standalone S3 gateway against a running filer
    (`weed/command/s3.go`)."""
    p = argparse.ArgumentParser(prog="weed-tpu s3")
    p.add_argument("-port", type=int, default=8333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="http://127.0.0.1:8888")
    p.add_argument("-master", default="",
                   help="master url: ship telemetry frames (usage sketch +"
                        " SLO counters) to the cluster aggregator")
    p.add_argument("-config", default=None, help="identities json (s3.json)")
    p.add_argument("-slowMs", dest="slow_ms", type=float, default=None,
                   help="log requests slower than this many ms for this "
                        "server's role (overrides SEAWEEDFS_TPU_SLOW_MS)")
    _add_telemetry_flags(p)
    _add_faults_flag(p)
    _add_qos_flag(p)
    opts = p.parse_args(args)
    _arm_faults(opts)
    _load_security()
    import json as _json

    from seaweedfs_tpu.s3api import S3Server

    config = None
    if opts.config:
        with open(opts.config) as fh:
            config = _json.load(fh)
    filer = opts.filer
    if not filer.startswith("http"):
        filer = peer_url(filer)
    master = opts.master
    if master and not master.startswith("http"):
        master = peer_url(master)
    s3 = S3Server(filer, host=opts.ip, port=opts.port, config=config,
                  slow_ms=opts.slow_ms, master_url=master or None,
                  telemetry_dir=opts.telemetry_dir,
                  telemetry_retention_mb=opts.telemetry_retention,
                  qos_limits=opts.qos_limits)
    s3.start()
    print(f"s3 gateway listening at {s3.url}")
    return _wait_forever()


def run_webdav(args: list[str]) -> int:
    """WebDAV gateway against a running filer (`weed/command/webdav.go`)."""
    p = argparse.ArgumentParser(prog="weed-tpu webdav")
    p.add_argument("-port", type=int, default=7333)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="http://127.0.0.1:8888")
    p.add_argument("-master", default="",
                   help="master url: ship telemetry frames (usage sketch +"
                        " SLO counters) to the cluster aggregator")
    p.add_argument("-readOnly", action="store_true")
    p.add_argument("-slowMs", dest="slow_ms", type=float, default=None,
                   help="log requests slower than this many ms for this "
                        "server's role (overrides SEAWEEDFS_TPU_SLOW_MS)")
    opts = p.parse_args(args)
    _load_security()
    from seaweedfs_tpu.server.webdav import WebDavServer

    filer = opts.filer
    if not filer.startswith("http"):
        filer = peer_url(filer)
    master = opts.master
    if master and not master.startswith("http"):
        master = peer_url(master)
    srv = WebDavServer(filer, host=opts.ip, port=opts.port,
                       read_only=opts.readOnly, slow_ms=opts.slow_ms,
                       master_url=master or None)
    srv.start()
    print(f"webdav listening at {srv.url}")
    return _wait_forever()


def run_mq_broker(args: list[str]) -> int:
    """MQ broker against a running filer (`weed/command/mq_broker.go`)."""
    p = argparse.ArgumentParser(prog="weed-tpu mq.broker")
    p.add_argument("-port", type=int, default=17777)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="http://127.0.0.1:8888")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument("-peers", default="", help="comma-separated peer broker urls")
    opts = p.parse_args(args)
    _load_security()
    from seaweedfs_tpu.mq import BrokerServer

    filer = opts.filer
    if not filer.startswith("http"):
        filer = peer_url(filer)
    srv = BrokerServer(
        filer, master_url=opts.master, host=opts.ip, port=opts.port,
        peers=[peer_url(u)
               for u in opts.peers.split(",") if u],
    )
    srv.start()
    print(f"mq broker listening at {srv.url}")
    return _wait_forever()


def run_mount(args: list[str]) -> int:
    """FUSE-mount a filer path (`weed/command/mount.go`). Needs /dev/fuse +
    CAP_SYS_ADMIN; otherwise explains and exits."""
    p = argparse.ArgumentParser(prog="weed-tpu mount")
    p.add_argument("-filer", default="http://127.0.0.1:8888")
    p.add_argument("-dir", required=True, help="mountpoint")
    p.add_argument("-readOnly", action="store_true")
    p.add_argument("-chunkCacheDir", default=None)
    p.add_argument("-quotaMB", type=int, default=0,
                   help="limit mounted usage; writes past it fail ENOSPC"
                        " (adjustable at runtime via mount.configure)")
    opts = p.parse_args(args)
    _load_security()
    from seaweedfs_tpu.mount import WFS, mount_fs, start_admin_service

    filer = opts.filer
    if not filer.startswith("http"):
        filer = peer_url(filer)
    wfs = WFS(filer, read_only=opts.readOnly,
              chunk_cache_dir=opts.chunkCacheDir, quota_mb=opts.quotaMB)
    try:
        start_admin_service(wfs, opts.dir)  # mount.configure control point
        print(f"mounting {filer} at {opts.dir}")
        mount_fs(wfs, opts.dir)
    except (PermissionError, FileNotFoundError) as e:
        print(f"cannot mount: {e} (needs /dev/fuse and CAP_SYS_ADMIN)")
        return 1
    return 0


def run_ftp(args: list[str]) -> int:
    """FTP gateway against a running filer (reference ships only a stub —
    `weed/ftpd/ftp_server.go`; this one is wired)."""
    p = argparse.ArgumentParser(prog="weed-tpu ftp")
    p.add_argument("-port", type=int, default=2121)
    p.add_argument("-ip", default="127.0.0.1")
    p.add_argument("-filer", default="http://127.0.0.1:8888")
    p.add_argument("-user", default="")
    p.add_argument("-password", default="")
    p.add_argument("-anonymous", action="store_true",
                   help="explicitly allow login without credentials")
    opts = p.parse_args(args)
    _load_security()
    from seaweedfs_tpu.ftpd import FtpServer

    filer = opts.filer
    if not filer.startswith("http"):
        filer = peer_url(filer)
    srv = FtpServer(filer, host=opts.ip, port=opts.port,
                    user=opts.user, password=opts.password,
                    anonymous=opts.anonymous)
    srv.start()
    print(f"ftp gateway listening at {opts.ip}:{srv.port}")
    return _wait_forever()
