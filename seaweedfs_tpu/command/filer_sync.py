"""`weed-tpu filer.sync` + `filer.replicate` + `filer.backup`
(reference: `weed/command/filer_sync.go:119-385`, `filer_replication.go`,
`filer_backup.go`).

filer.sync: continuous bidirectional (or -oneWay) active-active sync between
two filers using metadata subscription with signature loop-prevention.
filer.replicate: consume a notification spool and apply to a sink.
filer.backup: mirror a filer tree into a local directory, then keep
following the metadata stream.
"""

from __future__ import annotations

import argparse
import threading
import time


def run_filer_sync(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu filer.sync")
    p.add_argument("-a", required=True, help="filer A url")
    p.add_argument("-b", required=True, help="filer B url")
    p.add_argument("-isActivePassive", action="store_true",
                   help="one-way A->B only")
    p.add_argument("-interval", type=float, default=1.0)
    opts = p.parse_args(args)

    from seaweedfs_tpu.replication import FilerSyncer

    stop = threading.Event()
    ab = FilerSyncer(opts.a, opts.b)
    threads = [threading.Thread(
        target=ab.run_forever, args=(opts.interval, stop), daemon=True
    )]
    print(f"sync {opts.a} -> {opts.b} (sig {ab.source_signature})")
    if not opts.isActivePassive:
        ba = FilerSyncer(opts.b, opts.a)
        threads.append(threading.Thread(
            target=ba.run_forever, args=(opts.interval, stop), daemon=True
        ))
        print(f"sync {opts.b} -> {opts.a} (sig {ba.source_signature})")
    for t in threads:
        t.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        stop.set()
    return 0


def run_filer_replicate(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu filer.replicate")
    p.add_argument("-notification.spool", dest="spool", required=True,
                   help="file-queue spool dir to consume")
    p.add_argument("-source", required=True, help="source filer url")
    p.add_argument("-sink.local", dest="sink_local", default=None,
                   help="mirror into this directory")
    p.add_argument("-sink.filer", dest="sink_filer", default=None,
                   help="replicate to this filer url")
    p.add_argument("-sink.s3.endpoint", dest="sink_s3_endpoint", default=None,
                   help="replicate into an S3 endpoint (any S3 API, incl. "
                        "this framework's own gateway)")
    p.add_argument("-sink.s3.bucket", dest="sink_s3_bucket", default="backup")
    p.add_argument("-sink.s3.prefix", dest="sink_s3_prefix", default="")
    p.add_argument("-sink.s3.accessKey", dest="sink_s3_ak", default="")
    p.add_argument("-sink.s3.secretKey", dest="sink_s3_sk", default="")
    p.add_argument("-sink.azure.account", dest="sink_az_account", default=None,
                   help="replicate into an Azure Blob container")
    p.add_argument("-sink.azure.key", dest="sink_az_key", default="")
    p.add_argument("-sink.azure.container", dest="sink_az_container",
                   default="backup")
    p.add_argument("-sink.azure.endpoint", dest="sink_az_endpoint",
                   default=None)
    p.add_argument("-sink.gcs.bucket", dest="sink_gcs_bucket", default=None,
                   help="replicate into a GCS bucket (JSON API)")
    p.add_argument("-sink.gcs.credentials", dest="sink_gcs_creds", default="",
                   help="service-account JSON key file")
    p.add_argument("-sink.gcs.endpoint", dest="sink_gcs_endpoint",
                   default="https://storage.googleapis.com")
    p.add_argument("-sink.b2.accountId", dest="sink_b2_account", default=None,
                   help="replicate into a Backblaze B2 bucket")
    p.add_argument("-sink.b2.applicationKey", dest="sink_b2_key", default="")
    p.add_argument("-sink.b2.bucket", dest="sink_b2_bucket", default="backup")
    p.add_argument("-sink.b2.endpoint", dest="sink_b2_endpoint",
                   default="https://api.backblazeb2.com")
    p.add_argument("-interval", type=float, default=1.0)
    p.add_argument("-once", action="store_true", help="drain spool and exit")
    opts = p.parse_args(args)

    from seaweedfs_tpu.filer.filer_client import FilerClient
    from seaweedfs_tpu.notification import FileQueue
    from seaweedfs_tpu.replication import (
        FilerSink,
        LocalSink,
        Replicator,
        S3Sink,
    )

    if opts.sink_local:
        sink = LocalSink(opts.sink_local)
    elif opts.sink_filer:
        sink = FilerSink(opts.sink_filer)
    elif opts.sink_s3_endpoint:
        sink = S3Sink(
            opts.sink_s3_endpoint, opts.sink_s3_bucket,
            access_key=opts.sink_s3_ak, secret_key=opts.sink_s3_sk,
            prefix=opts.sink_s3_prefix,
        )
    elif opts.sink_az_account:
        from seaweedfs_tpu.replication.cloud_sinks import AzureSink

        sink = AzureSink(opts.sink_az_account, opts.sink_az_key,
                         opts.sink_az_container,
                         endpoint=opts.sink_az_endpoint)
    elif opts.sink_gcs_bucket:
        import json as _json

        from seaweedfs_tpu.replication.cloud_sinks import (
            GcsSink,
            service_account_token_provider,
        )

        if not opts.sink_gcs_creds:
            print("-sink.gcs.bucket needs -sink.gcs.credentials "
                  "(service-account JSON key file)")
            return 1
        with open(opts.sink_gcs_creds) as fh:
            creds = _json.load(fh)
        sink = GcsSink(opts.sink_gcs_bucket,
                       service_account_token_provider(creds),
                       endpoint=opts.sink_gcs_endpoint)
    elif opts.sink_b2_account:
        from seaweedfs_tpu.replication.cloud_sinks import B2Sink

        sink = B2Sink(opts.sink_b2_account, opts.sink_b2_key,
                      opts.sink_b2_bucket, endpoint=opts.sink_b2_endpoint)
    else:
        print("need a -sink.{local,filer,s3,azure,gcs,b2} target")
        return 1
    src = FilerClient(opts.source)
    rep = Replicator(sink, read_content=lambda path, entry: src.read(path))
    queue = FileQueue(opts.spool)
    seen = 0
    while True:
        msgs = queue.read_all()
        for _, message in msgs[seen:]:
            try:
                rep.replicate(message)
            except Exception as e:
                print(f"replicate error: {e}")
        seen = len(msgs)
        if opts.once:
            return 0
        time.sleep(opts.interval)


def run_filer_backup(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu filer.backup")
    p.add_argument("-filer", required=True)
    p.add_argument("-output", required=True, help="local mirror directory")
    p.add_argument("-path", default="/", help="subtree to back up")
    p.add_argument("-interval", type=float, default=1.0)
    p.add_argument("-once", action="store_true",
                   help="full copy + drain, then exit")
    opts = p.parse_args(args)

    from seaweedfs_tpu.filer.filer_client import FilerClient
    from seaweedfs_tpu.replication import FilerSyncer, LocalSink, Replicator

    client = FilerClient(opts.filer)
    sink = LocalSink(opts.output)
    rep = Replicator(sink, read_content=lambda path, entry: client.read(path))

    # initial full walk (the reference starts from a timestamp; we snapshot)
    def walk(dir_path: str) -> None:
        for e in client.list(dir_path).get("Entries") or []:
            full = e["FullPath"]
            if not full.startswith(opts.path) and not opts.path.startswith(full):
                continue
            if e["IsDirectory"]:
                sink.create_entry(full, {"is_directory": True}, None)
                walk(full)
            else:
                sink.create_entry(full, {}, client.read(full))

    start_ns = time.time_ns()
    walk("/")
    print(f"initial backup of {opts.path} complete")
    syncer = FilerSyncer.__new__(FilerSyncer)  # follow stream into LocalSink
    syncer.source = client
    syncer.source_url = opts.filer
    syncer.target_signature = -1  # never skip
    syncer.replicator = rep
    syncer.cursor_ns = start_ns
    if opts.once:
        syncer.run_once()
        return 0
    while True:
        try:
            syncer.run_once(wait=opts.interval)
        except Exception as e:
            print(f"backup follow error: {e}")
            time.sleep(opts.interval)


def run_filer_remote_sync(args: list[str]) -> int:
    """`weed-tpu filer.remote.sync`: follow a mounted directory's metadata
    stream and write local changes back to the remote store
    (`weed/command/filer_remote_sync.go`). Cache-fill updates echo one
    idempotent write per object; stub creations (no chunks/content) are
    skipped."""
    p = argparse.ArgumentParser(prog="weed-tpu filer.remote.sync")
    p.add_argument("-filer", required=True)
    p.add_argument("-dir", required=True, help="mounted directory")
    p.add_argument("-interval", type=float, default=1.0)
    p.add_argument("-once", action="store_true")
    p.add_argument("-timeAgo", type=float, default=0.0,
                   help="start from this many seconds in the past")
    opts = p.parse_args(args)

    import json as _json

    from seaweedfs_tpu.filer.filer_client import FilerClient
    from seaweedfs_tpu.remote_storage import REMOTE_KEY, make_remote_client
    from seaweedfs_tpu.server.httpd import http_request

    filer_url = opts.filer.rstrip("/")
    client = FilerClient(filer_url)
    mount_dir = opts.dir.rstrip("/")

    status, _, body = http_request("GET", f"{filer_url}/__remote__/mounts")
    mounts = _json.loads(body)["mounts"]
    if mount_dir not in mounts:
        print(f"{mount_dir} is not remote-mounted on {filer_url}")
        return 1
    mount = mounts[mount_dir]
    status, _, body = http_request("GET", f"{filer_url}/{mount_dir.strip('/')}")

    # conf lives on the filer; fetch it via the configure listing
    status, _, body = http_request(
        "GET", f"{filer_url}/etc/remote/remote.conf"
    )
    confs = _json.loads(body)
    remote = make_remote_client(confs[mount["config"]])
    base = mount.get("path", "").strip("/")

    def remote_key(full_path: str) -> str:
        rel = full_path[len(mount_dir):].lstrip("/")
        return f"{base}/{rel}".lstrip("/") if base else rel

    cursor = time.time_ns() - int(opts.timeAgo * 1e9)

    def run_once(wait: float = 0.0) -> int:
        nonlocal cursor
        status, _, body = http_request(
            "GET",
            f"{filer_url}/__meta__/events?since_ns={cursor}&wait={wait}",
            timeout=wait + 30,
        )
        out = _json.loads(body)
        applied = 0
        for ev in out["events"]:
            new, old = ev.get("new_entry"), ev.get("old_entry")
            if new is not None:
                path = new["full_path"]
                if not path.startswith(mount_dir + "/"):
                    continue
                if new.get("is_directory"):
                    continue
                if not new.get("chunks") and not new.get("content"):
                    continue  # remote stub, nothing local to push
                try:
                    data = client.read(path)
                except OSError:
                    continue  # deleted/overwritten since the event was logged
                remote.write_file(remote_key(path), data)
                applied += 1
            elif old is not None:
                path = old["full_path"]
                if not path.startswith(mount_dir + "/"):
                    continue
                if old.get("is_directory"):
                    continue
                remote.delete_file(remote_key(path))
                applied += 1
        cursor = out["next_ts_ns"]
        return applied

    print(f"write-back {mount_dir} -> {mount['config']}")
    if opts.once:
        run_once()
        return 0
    while True:
        try:
            run_once(wait=opts.interval)
        except Exception as e:
            print(f"remote sync error: {e}")
            time.sleep(opts.interval)
