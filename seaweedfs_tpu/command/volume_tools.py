"""`weed-tpu backup` / `compact` / `export` — offline volume tools
(reference: `weed/command/backup.go`, `compact.go`, `export.go`)."""

from __future__ import annotations

import argparse
import os


def run_compact(args: list[str]) -> int:
    """Offline vacuum of a local volume (`weed/command/compact.go`)."""
    p = argparse.ArgumentParser(prog="weed-tpu compact")
    p.add_argument("-dir", default=".")
    p.add_argument("-collection", default="")
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(opts.dir, opts.collection, opts.volumeId)
    before = v.size()
    garbage = v.garbage_level()
    v.compact()
    v.commit_compact()
    after = v.size()
    v.close()
    print(
        f"volume {opts.volumeId}: {before} -> {after} bytes "
        f"(garbage was {garbage:.1%})"
    )
    return 0


def run_export(args: list[str]) -> int:
    """Dump live needles to a tar or directory (`weed/command/export.go`)."""
    import tarfile
    import time

    p = argparse.ArgumentParser(prog="weed-tpu export")
    p.add_argument("-dir", default=".")
    p.add_argument("-collection", default="")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-o", default="", help="output .tar (default: stdout list)")
    p.add_argument("-outputDir", default="", help="extract into a directory")
    opts = p.parse_args(args)
    from seaweedfs_tpu.storage.volume import Volume

    v = Volume(opts.dir, opts.collection, opts.volumeId)
    tar = tarfile.open(opts.o, "w") if opts.o else None
    count = 0
    for key, offset, size in v.nm.ascending_visit():
        n = v.read_needle(key)
        name = (
            n.name.decode("utf-8", "replace")
            if n.has_name() and n.name else f"{key:x}"
        )
        if tar is not None:
            info = tarfile.TarInfo(name=f"vol{opts.volumeId}/{name}")
            info.size = len(n.data)
            info.mtime = n.last_modified or int(time.time())
            import io

            tar.addfile(info, io.BytesIO(n.data))
        elif opts.outputDir:
            dst = os.path.join(opts.outputDir, name)
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            with open(dst, "wb") as f:
                f.write(n.data)
        else:
            print(f"{key:x}\t{name}\t{len(n.data)}")
        count += 1
    if tar is not None:
        tar.close()
        print(f"exported {count} needles -> {opts.o}")
    elif opts.outputDir:
        print(f"exported {count} needles -> {opts.outputDir}")
    v.close()
    return 0


def run_backup(args: list[str]) -> int:
    """Incrementally mirror a live volume to a local dir
    (`weed/command/backup.go`: full copy first, then AppendAtNs-tail)."""
    p = argparse.ArgumentParser(prog="weed-tpu backup")
    p.add_argument("-server", required=True, help="volume server host:port")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-dir", default=".", help="local backup directory")
    opts = p.parse_args(args)

    from seaweedfs_tpu.server.httpd import http_request
    from seaweedfs_tpu.storage.volume import Volume, volume_file_name

    server = opts.server
    if not server.startswith("http"):
        server = f"http://{server}"
    base = volume_file_name(opts.dir, opts.collection, opts.volumeId)
    os.makedirs(opts.dir, exist_ok=True)

    def pull(ext: str, dest: str) -> None:
        offset = 0
        with open(dest + ".pull", "wb") as f:
            while True:
                url = (
                    f"{server}/admin/volume/raw?volume={opts.volumeId}"
                    f"&ext={ext}&collection={opts.collection}"
                    f"&offset={offset}&size={16 * 1024 * 1024}"
                )
                status, headers, body = http_request("GET", url, timeout=120)
                if status != 200:
                    raise IOError(f"pull {ext}: {status} {body[:200]!r}")
                f.write(body)
                offset += len(body)
                total = int(headers.get("X-Total-Size", offset))
                if offset >= total or not body:
                    break
        os.replace(dest + ".pull", dest)

    if not os.path.exists(base + ".dat"):
        pull(".dat", base + ".dat")
        pull(".idx", base + ".idx")
        print(f"full backup of volume {opts.volumeId} -> {base}.dat")
        return 0

    # incremental: ship only needles appended after our last timestamp
    v = Volume(opts.dir, opts.collection, opts.volumeId)
    since = v.last_append_at_ns
    v.close()
    status, _, delta = http_request(
        "GET",
        f"{server}/admin/tail?volume={opts.volumeId}&since_ns={since}",
        timeout=120,
    )
    if status != 200:
        raise IOError(f"tail: {status} {delta[:200]!r}")
    if delta:
        with open(base + ".dat", "ab") as f:
            f.write(delta)
        # rebuild the idx from the dat (same scan as `weed-tpu fix`)
        from seaweedfs_tpu.command.fix import run as fix_run

        fix_run(["-dir", opts.dir, "-collection", opts.collection,
                 "-volumeId", str(opts.volumeId)])
    print(
        f"incremental backup of volume {opts.volumeId}: +{len(delta)} bytes"
    )
    return 0
