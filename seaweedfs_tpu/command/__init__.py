"""CLI entrypoints (`weed-tpu ...`), mirroring the reference's command registry
(`weed/command/command.go`)."""
