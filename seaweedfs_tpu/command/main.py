"""`weed-tpu` command dispatch (reference: `weed/weed.go:50`, `weed/command/`).

Subcommands are registered lazily; each module under seaweedfs_tpu.command
exposes `run(args) -> int` and `HELP`.
"""

from __future__ import annotations

import importlib
import sys

COMMANDS: dict[str, tuple[str, str]] = {
    # name -> (module, one-line help)
    "version": ("seaweedfs_tpu.command.version", "print version"),
}


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print("weed-tpu: TPU-native distributed object store\n\ncommands:")
        for name, (_, help_line) in sorted(COMMANDS.items()):
            print(f"  {name:18s} {help_line}")
        return 0
    name, *rest = argv
    if name not in COMMANDS:
        print(f"unknown command {name!r}; see `weed-tpu help`", file=sys.stderr)
        return 2
    mod = importlib.import_module(COMMANDS[name][0])
    return int(mod.run(rest) or 0)


if __name__ == "__main__":
    raise SystemExit(main())
