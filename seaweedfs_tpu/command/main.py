"""`weed-tpu` command dispatch (reference: `weed/weed.go:50`, `weed/command/`).

Subcommands are registered lazily; each module under seaweedfs_tpu.command
exposes `run(args) -> int` and `HELP`.
"""

from __future__ import annotations

import importlib
import os
import sys

COMMANDS: dict[str, tuple[str, str, str]] = {
    # name -> (module, function, one-line help)
    "version": ("seaweedfs_tpu.command.version", "run", "print version"),
    "master": (
        "seaweedfs_tpu.command.server_cmds", "run_master",
        "start the cluster master (assign/lookup/heartbeats)",
    ),
    "volume": (
        "seaweedfs_tpu.command.server_cmds", "run_volume",
        "start a volume server (blob storage data plane)",
    ),
    "filer": (
        "seaweedfs_tpu.command.server_cmds", "run_filer",
        "start a filer (file namespace over the blob store)",
    ),
    "server": (
        "seaweedfs_tpu.command.server_cmds", "run_server",
        "start master + volume server (+ -filer, -s3) in one process",
    ),
    "s3": (
        "seaweedfs_tpu.command.server_cmds", "run_s3",
        "start the S3 gateway against a filer",
    ),
    "ftp": (
        "seaweedfs_tpu.command.server_cmds", "run_ftp",
        "start the FTP gateway against a filer",
    ),
    "iam": (
        "seaweedfs_tpu.command.server_cmds", "run_iam",
        "start the IAM management API against a filer",
    ),
    "shell": (
        "seaweedfs_tpu.shell.shell", "run",
        "interactive admin shell (ec.*, volume.*, fs.*)",
    ),
    "benchmark": (
        "seaweedfs_tpu.command.benchmark", "run",
        "write/read load generator with latency percentiles",
    ),
    "upload": ("seaweedfs_tpu.command.upload", "run", "upload files via assign+PUT"),
    "download": ("seaweedfs_tpu.command.upload", "run_download", "download a fid"),
    "backup": (
        "seaweedfs_tpu.command.volume_tools", "run_backup",
        "incrementally back up a live volume to a local directory",
    ),
    "compact": (
        "seaweedfs_tpu.command.volume_tools", "run_compact",
        "offline-vacuum a local volume",
    ),
    "export": (
        "seaweedfs_tpu.command.volume_tools", "run_export",
        "list or extract a volume's needles (tar / directory)",
    ),
    "scaffold": (
        "seaweedfs_tpu.command.scaffold", "run",
        "print starter TOML configs (security/filer/master/...)",
    ),
    "fix": (
        "seaweedfs_tpu.command.fix", "run",
        "rebuild a volume .idx from its .dat",
    ),
    "mount": (
        "seaweedfs_tpu.command.server_cmds", "run_mount",
        "FUSE-mount a filer as a local filesystem",
    ),
    "mq.broker": (
        "seaweedfs_tpu.command.server_cmds", "run_mq_broker",
        "start a message-queue broker against a filer",
    ),
    "webdav": (
        "seaweedfs_tpu.command.server_cmds", "run_webdav",
        "start the WebDAV gateway against a filer",
    ),
    "filer.sync": (
        "seaweedfs_tpu.command.filer_sync", "run_filer_sync",
        "continuous bidirectional sync between two filers",
    ),
    "filer.replicate": (
        "seaweedfs_tpu.command.filer_sync", "run_filer_replicate",
        "consume a notification spool and replicate to a sink",
    ),
    "filer.remote.sync": (
        "seaweedfs_tpu.command.filer_sync", "run_filer_remote_sync",
        "write back changes under a remote-mounted directory",
    ),
    "filer.backup": (
        "seaweedfs_tpu.command.filer_sync", "run_filer_backup",
        "mirror a filer tree into a local directory and follow changes",
    ),
}


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print("weed-tpu: TPU-native distributed object store\n\ncommands:")
        for name, (_, _, help_line) in sorted(COMMANDS.items()):
            print(f"  {name:18s} {help_line}")
        return 0
    name, *rest = argv
    if name not in COMMANDS:
        print(f"unknown command {name!r}; see `weed-tpu help`", file=sys.stderr)
        return 2
    dsn = os.environ.get("SEAWEEDFS_SENTRY_DSN", "")
    if dsn:  # reference: sentry.Init at each command's startup
        from seaweedfs_tpu.util.sentry import init_sentry

        init_sentry(dsn, environment=os.environ.get("SEAWEEDFS_ENV", ""))
    module, fn_name, _ = COMMANDS[name]
    mod = importlib.import_module(module)
    return int(getattr(mod, fn_name)(rest) or 0)


if __name__ == "__main__":
    raise SystemExit(main())
