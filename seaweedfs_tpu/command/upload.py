"""`weed-tpu upload` / `download` (reference: `weed/command/upload.go`,
`download.go`): assign + direct volume-server PUT/GET."""

from __future__ import annotations

import argparse
import mimetypes
import os
import sys


def run(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu upload")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument("-replication", default="")
    p.add_argument("-collection", default="")
    p.add_argument("-ttl", default="")
    p.add_argument("files", nargs="+")
    opts = p.parse_args(args)
    from seaweedfs_tpu.filer.wdclient import WeedClient

    client = WeedClient(opts.master)
    import json

    results = []
    for path in opts.files:
        with open(path, "rb") as f:
            data = f.read()
        mime = mimetypes.guess_type(path)[0] or ""
        out = client.upload(
            data,
            replication=opts.replication,
            collection=opts.collection,
            ttl=opts.ttl,
            filename=os.path.basename(path),
            mime=mime,
        )
        results.append(
            {"fileName": os.path.basename(path), "fid": out["fid"],
             "url": f"{out['url']}/{out['fid']}", "size": len(data)}
        )
    print(json.dumps(results, indent=2))
    return 0


def run_download(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu download")
    p.add_argument("-master", default="http://127.0.0.1:9333")
    p.add_argument("-dir", default=".")
    p.add_argument("fids", nargs="+")
    opts = p.parse_args(args)
    from seaweedfs_tpu.filer.wdclient import WeedClient

    client = WeedClient(opts.master)
    for fid in opts.fids:
        data = client.fetch(fid)
        out = os.path.join(opts.dir, fid.replace(",", "_"))
        with open(out, "wb") as f:
            f.write(data)
        print(f"{fid} -> {out} ({len(data)} bytes)")
    return 0
