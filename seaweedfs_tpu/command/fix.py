"""`weed-tpu fix`: rebuild a volume .idx by scanning its .dat
(reference: `weed/command/fix.go`)."""

from __future__ import annotations

import argparse
import os


def run(args: list[str]) -> int:
    p = argparse.ArgumentParser(prog="weed-tpu fix")
    p.add_argument("-dir", default=".")
    p.add_argument("-collection", default="")
    p.add_argument("-volumeId", type=int, required=True)
    opts = p.parse_args(args)

    from seaweedfs_tpu.storage import idx as idx_mod
    from seaweedfs_tpu.storage.needle import (
        Needle,
        needle_body_length,
    )
    from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE, SuperBlock
    from seaweedfs_tpu.storage.types import NEEDLE_HEADER_SIZE, TOMBSTONE_FILE_SIZE
    from seaweedfs_tpu.storage.volume import volume_file_name

    base = volume_file_name(opts.dir, opts.collection, opts.volumeId)
    dat = open(base + ".dat", "rb").read()
    sb = SuperBlock.from_bytes(dat[:SUPER_BLOCK_SIZE])
    offset = sb.block_size()
    entries: dict[int, tuple[int, int]] = {}
    scanned = 0
    while offset + NEEDLE_HEADER_SIZE <= len(dat):
        n = Needle()
        n.parse_header(dat[offset : offset + NEEDLE_HEADER_SIZE])
        body_len = needle_body_length(max(n.size, 0), sb.version)
        if n.size > 0:
            entries[n.id] = (offset, n.size)
        else:
            entries[n.id] = (offset, TOMBSTONE_FILE_SIZE)
        offset += NEEDLE_HEADER_SIZE + body_len
        scanned += 1
    with open(base + ".idx", "wb") as f:
        for key in sorted(entries):
            off, size = entries[key]
            if size == TOMBSTONE_FILE_SIZE:
                f.write(idx_mod.entry_to_bytes(key, 0, TOMBSTONE_FILE_SIZE))
            else:
                f.write(idx_mod.entry_to_bytes(key, off, size))
    print(f"scanned {scanned} needles -> {base}.idx ({len(entries)} keys)")
    return 0
