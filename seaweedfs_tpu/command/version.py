from __future__ import annotations

import seaweedfs_tpu

HELP = "print version"


def run(args: list[str]) -> int:
    print(f"seaweedfs-tpu {seaweedfs_tpu.__version__}")
    return 0
