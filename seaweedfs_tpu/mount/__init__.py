"""`weed mount` subsystem: FUSE filesystem over the filer.

Layers (SURVEY.md §2 FUSE mount, reference `weed/mount/` 5.3k LoC):
  - `fuse_proto` — kernel wire-format structs (no fuse library in image;
    direct /dev/fuse framing per SURVEY.md §2.2 item 7)
  - `weedfs.WFS` — inode map, meta cache w/ subscription, page-writer
    upload pipeline, chunked reads
  - `mount_fs()` — real kernel mount via /dev/fuse + mount(2) (needs
    CAP_SYS_ADMIN; tests use the in-memory transport instead)
"""

from __future__ import annotations

import ctypes
import os

from .weedfs import WFS  # noqa: F401


def admin_socket_path(mountpoint: str) -> str:
    """Deterministic unix-socket path for a mount's admin listener — how
    `mount.configure -dir <mp>` finds a RUNNING mount (the reference uses
    the same convention with a hashed /tmp socket, `mount.go`)."""
    import hashlib

    digest = hashlib.md5(
        os.path.abspath(mountpoint).encode()).hexdigest()[:10]
    return f"/tmp/seaweedfs-tpu-mount-{digest}.sock"


def start_admin_service(wfs: WFS, mountpoint: str):
    """Tiny control listener on the mount's unix socket: GET /status and
    POST /configure {"quotaMB": n} (`weed/mount/weedfs_grpc_server.go` /
    command_mount_configure.go surface). Returns the HTTPService."""
    from seaweedfs_tpu.server.httpd import HTTPService, Request, Response

    svc = HTTPService("127.0.0.1", 0)

    @svc.route("GET", r"/status")
    def status(req: Request) -> Response:
        return Response({
            "mountpoint": os.path.abspath(mountpoint),
            "quota_bytes": wfs.quota_bytes,
            "used_bytes": wfs._usage(),
            "read_only": wfs.read_only,
        })

    @svc.route("POST", r"/configure")
    def configure(req: Request) -> Response:
        p = req.json()
        if "quotaMB" in p:
            wfs.set_quota(int(p["quotaMB"]))
        return Response({"ok": True, "quota_bytes": wfs.quota_bytes})

    svc.plain_backend = True
    svc.start()  # enable_unix_socket needs the handler class start() builds
    svc.enable_unix_socket(admin_socket_path(mountpoint))
    # the TCP side was only scaffolding: close it so the unix socket is
    # the ONLY control surface (no stray unauthenticated loopback port)
    svc._httpd.shutdown()
    svc._httpd.server_close()
    svc._httpd = None
    return svc


def mount_fs(wfs: WFS, mountpoint: str) -> None:  # pragma: no cover
    """Open /dev/fuse, mount(2), serve. Raises PermissionError without
    CAP_SYS_ADMIN (the normal case in unprivileged containers)."""
    fd = os.open("/dev/fuse", os.O_RDWR)
    opts = f"fd={fd},rootmode=40000,user_id=0,group_id=0"
    libc = ctypes.CDLL(None, use_errno=True)
    ret = libc.mount(
        b"seaweedfs_tpu", mountpoint.encode(), b"fuse.seaweedfs_tpu",
        0, opts.encode(),
    )
    if ret != 0:
        err = ctypes.get_errno()
        os.close(fd)
        raise PermissionError(err, f"mount(2) failed: {os.strerror(err)}")
    try:
        wfs.serve(fd)
    finally:
        libc.umount2(mountpoint.encode(), 2)  # MNT_DETACH
        os.close(fd)


class VirtualFuseKernel:
    """Test-side 'kernel': speaks the same packed wire structs against
    WFS.handle — every op crosses the real protocol layer."""

    def __init__(self, wfs: WFS) -> None:
        from . import fuse_proto as fp

        self.fp = fp
        self.wfs = wfs
        self._unique = 0
        self.init()

    def call(self, opcode: int, nodeid: int, payload: bytes = b"",
             uid: int = 0, gid: int = 0):
        fp = self.fp
        self._unique += 1
        req = fp.pack_request(opcode, self._unique, nodeid, payload, uid, gid)
        out = self.wfs.handle(req)
        if out is None:
            return None, b""
        unique, error, body = fp.parse_reply(out)
        assert unique == self._unique
        return -error, body

    # convenience verbs mirroring libfuse client calls -----------------------
    def init(self):
        fp = self.fp
        err, body = self.call(fp.INIT, 0, fp.INIT_IN.pack(7, 31, 1 << 17, 0))
        assert err == 0
        return body

    def lookup(self, parent: int, name: str):
        fp = self.fp
        err, body = self.call(fp.LOOKUP, parent, name.encode() + b"\0")
        if err:
            return err, None, None
        ino, attr = fp.unpack_entry_out(body)
        return 0, ino, attr

    def getattr(self, ino: int):
        fp = self.fp
        err, body = self.call(fp.GETATTR, ino, b"\0" * 16)
        return err, (fp.unpack_attr_out(body) if not err else None)

    def mkdir(self, parent: int, name: str, mode: int = 0o755):
        fp = self.fp
        err, body = self.call(
            fp.MKDIR, parent, fp.MKDIR_IN.pack(mode, 0) + name.encode() + b"\0"
        )
        if err:
            return err, None
        ino, _ = fp.unpack_entry_out(body)
        return 0, ino

    def create(self, parent: int, name: str, mode: int = 0o644):
        fp = self.fp
        err, body = self.call(
            fp.CREATE, parent,
            fp.CREATE_IN.pack(os.O_RDWR, mode, 0, 0) + name.encode() + b"\0",
        )
        if err:
            return err, None, None
        ino, _ = fp.unpack_entry_out(body)
        fh = fp.unpack_open_out(body[128:])
        return 0, ino, fh

    def open(self, ino: int):
        fp = self.fp
        err, body = self.call(fp.OPEN, ino, b"\0" * 8)
        return err, (fp.unpack_open_out(body) if not err else None)

    def write(self, ino: int, fh: int, offset: int, data: bytes):
        fp = self.fp
        payload = fp.WRITE_IN.pack(fh, offset, len(data), 0, 0, 0, 0) + data
        err, body = self.call(fp.WRITE, ino, payload)
        if err:
            return err, 0
        return 0, fp.WRITE_OUT.unpack_from(body)[0]

    def read(self, ino: int, fh: int, offset: int, size: int):
        fp = self.fp
        payload = fp.READ_IN.pack(fh, offset, size, 0, 0, 0, 0)
        return self.call(fp.READ, ino, payload)

    def flush(self, ino: int, fh: int):
        fp = self.fp
        # kernel-accurate 24-byte fuse_flush_in
        return self.call(fp.FLUSH, ino, fp.FLUSH_IN.pack(fh, 0, 0, 0))[0]

    def release(self, ino: int, fh: int):
        fp = self.fp
        return self.call(
            fp.RELEASE, ino, fp.RELEASE_IN.pack(fh, 0, 0, 0)
        )[0]

    def readdir(self, ino: int, fh: int = 0, size: int = 1 << 16):
        fp = self.fp
        err, body = self.call(
            fp.READDIR, ino, fp.READ_IN.pack(fh, 0, size, 0, 0, 0, 0)
        )
        if err:
            return err, []
        return 0, fp.unpack_dirents(body)

    def unlink(self, parent: int, name: str):
        return self.call(self.fp.UNLINK, parent, name.encode() + b"\0")[0]

    def rmdir(self, parent: int, name: str):
        return self.call(self.fp.RMDIR, parent, name.encode() + b"\0")[0]

    def rename(self, parent: int, old: str, newparent: int, new: str):
        fp = self.fp
        payload = fp.RENAME_IN.pack(newparent) + old.encode() + b"\0" \
            + new.encode() + b"\0"
        return self.call(fp.RENAME, parent, payload)[0]

    def setattr_size(self, ino: int, size: int):
        fp = self.fp
        payload = fp.SETATTR_IN.pack(
            fp.FATTR_SIZE, 0, 0, size, 0, 0, 0, 0,
            0, 0, 0, 0, 0, 0, 0, 0,
        )
        err, body = self.call(fp.SETATTR, ino, payload)
        return err, (fp.unpack_attr_out(body) if not err else None)

    def statfs(self):
        return self.call(self.fp.STATFS, 1)
