"""Chunked dirty-page write pipeline for the mount.

Behavioral port of `weed/mount/page_writer/upload_pipeline.go:42-220` +
`dirty_pages_chunked.go`: writes land in fixed-size in-memory page chunks;
a full chunk is sealed and handed to a bounded pool of async uploaders;
flush seals the remainder, waits for uploads, and returns the FileChunk
list (logical intervals) for the entry commit. Overlapping writes within
one chunk just overwrite the buffer; cross-chunk ordering is preserved by
ModifiedTsNs so the filer's visible-interval resolution (LSM-style
latest-wins) reads back exactly what was written.
"""

from __future__ import annotations

import threading
import time

from seaweedfs_tpu.filer.entry import FileChunk
from seaweedfs_tpu.util.concurrency import LimitedConcurrentExecutor


class PageChunk:
    """One chunk-size buffer holding dirty [start,stop) spans."""

    def __init__(self, logical_index: int, chunk_size: int) -> None:
        self.index = logical_index
        self.chunk_size = chunk_size
        self.buf = bytearray(chunk_size)
        self.spans: list[tuple[int, int]] = []  # in-chunk [start, stop)

    def write(self, in_chunk_offset: int, data: bytes) -> None:
        stop = in_chunk_offset + len(data)
        self.buf[in_chunk_offset:stop] = data
        merged = []
        new = (in_chunk_offset, stop)
        for s, e in sorted(self.spans + [new]):
            if merged and s <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], e))
            else:
                merged.append((s, e))
        self.spans = [(s, e) for s, e in merged]

    def intervals(self) -> list[tuple[int, bytes]]:
        """(in-chunk offset, bytes) for each dirty span."""
        return [(s, bytes(self.buf[s:e])) for s, e in self.spans]


class UploadPipeline:
    def __init__(self, upload_fn, chunk_size: int = 4 * 1024 * 1024,
                 concurrency: int = 4) -> None:
        """upload_fn(data: bytes) -> file_id (assign + POST to a volume)."""
        self.upload_fn = upload_fn
        self.chunk_size = chunk_size
        self._writable: dict[int, PageChunk] = {}
        self._sealed: list[PageChunk] = []  # uploading, still readable
        self._lock = threading.Lock()
        self._executor = LimitedConcurrentExecutor(concurrency)
        self._pending: list = []  # futures -> list[FileChunk]
        self._errors: list[Exception] = []

    def write(self, offset: int, data: bytes) -> None:
        pos = 0
        while pos < len(data):
            abs_off = offset + pos
            idx = abs_off // self.chunk_size
            in_off = abs_off % self.chunk_size
            n = min(self.chunk_size - in_off, len(data) - pos)
            with self._lock:
                pc = self._writable.get(idx)
                if pc is None:
                    pc = PageChunk(idx, self.chunk_size)
                    self._writable[idx] = pc
                pc.write(in_off, data[pos:pos + n])
                # seal a fully-dirty chunk immediately (upload_pipeline.go
                # moveToSealed on full chunks)
                if pc.spans == [(0, self.chunk_size)]:
                    del self._writable[idx]
                    self._seal(pc)
            pos += n

    def read_back(self, offset: int, size: int) -> list[tuple[int, bytes]]:
        """Dirty spans overlapping [offset, offset+size) still buffered here
        — both writable chunks AND sealed chunks whose uploads haven't been
        committed to the entry yet (readback-before-upload)."""
        out = []
        with self._lock:
            chunks = self._sealed + list(self._writable.values())
        for pc in chunks:
            base = pc.index * self.chunk_size
            for s, data in pc.intervals():
                lo = base + s
                hi = lo + len(data)
                if hi <= offset or lo >= offset + size:
                    continue
                cut_lo = max(lo, offset)
                cut_hi = min(hi, offset + size)
                out.append((cut_lo, data[cut_lo - lo:cut_hi - lo]))
        return out

    def _seal(self, pc: PageChunk) -> None:
        ts_ns = time.time_ns()
        self._sealed.append(pc)  # caller holds _lock (or is single-owner)

        def do_upload():
            out = []
            base = pc.index * self.chunk_size
            for in_off, data in pc.intervals():
                fid = self.upload_fn(data)
                out.append(FileChunk(
                    file_id=fid, offset=base + in_off, size=len(data),
                    modified_ts_ns=ts_ns,
                ))
            return out

        self._pending.append(self._executor.execute(do_upload))

    def flush(self) -> list[FileChunk]:
        """Seal everything, wait for uploads, return accumulated chunks."""
        with self._lock:
            leftovers = list(self._writable.values())
            self._writable.clear()
            for pc in leftovers:
                self._seal(pc)
        chunks: list[FileChunk] = []
        pending, self._pending = self._pending, []
        errors = []
        for fut in pending:
            try:
                chunks.extend(fut.result(timeout=120))
            except Exception as e:  # surface on fsync like the reference
                errors.append(e)
        with self._lock:
            # sealed buffers are committed (or failed) — reads now come
            # from the entry's chunk list
            self._sealed.clear()
        if errors:
            raise errors[0]
        chunks.sort(key=lambda c: c.offset)
        return chunks

    def has_dirty(self) -> bool:
        with self._lock:
            return bool(self._writable) or bool(self._pending)
