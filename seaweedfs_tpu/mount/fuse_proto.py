"""FUSE lowlevel wire protocol: kernel struct framing.

The reference rides hanwen/go-fuse's raw loop (`weed/mount/weedfs.go`,
SURVEY.md §2.2 item 7 calls for direct /dev/fuse framing in this build —
no fuse library exists in the image). This module packs/unpacks the kernel
ABI structs (v7.31 layout for the ops we serve) so the same dispatcher
drives either a real `/dev/fuse` fd or the in-memory test transport.

Struct layouts follow include/uapi/linux/fuse.h.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

# opcodes (fuse.h enum fuse_opcode)
LOOKUP = 1
FORGET = 2
GETATTR = 3
SETATTR = 4
UNLINK = 10
LINK = 13
RMDIR = 11
RENAME = 12
OPEN = 14
READ = 15
WRITE = 16
STATFS = 17
RELEASE = 18
FSYNC = 20
FLUSH = 25
INIT = 26
OPENDIR = 27
READDIR = 28
RELEASEDIR = 29
ACCESS = 34
CREATE = 35
MKDIR = 9
MKNOD = 8
RENAME2 = 45

ERRNO_NOENT = 2
ERRNO_IO = 5
ERRNO_EXIST = 17
ERRNO_NOTDIR = 20
ERRNO_ISDIR = 21
ERRNO_INVAL = 22
ERRNO_NOTEMPTY = 39
ERRNO_NOSYS = 38
ERRNO_NOSPC = 28

IN_HEADER = struct.Struct("<IIQQIIII")  # len opcode unique nodeid uid gid pid pad
OUT_HEADER = struct.Struct("<IiQ")  # len error unique

S_IFDIR = 0o040000
S_IFREG = 0o100000


@dataclass
class InHeader:
    length: int
    opcode: int
    unique: int
    nodeid: int
    uid: int
    gid: int
    pid: int


def parse_in(buf: bytes) -> tuple[InHeader, bytes]:
    length, opcode, unique, nodeid, uid, gid, pid, _ = IN_HEADER.unpack_from(buf)
    return (
        InHeader(length, opcode, unique, nodeid, uid, gid, pid),
        buf[IN_HEADER.size:length],
    )


def pack_request(opcode: int, unique: int, nodeid: int, payload: bytes = b"",
                 uid: int = 0, gid: int = 0, pid: int = 0) -> bytes:
    """Build a kernel→daemon request (used by the virtual transport/tests)."""
    total = IN_HEADER.size + len(payload)
    return IN_HEADER.pack(total, opcode, unique, nodeid, uid, gid, pid, 0) \
        + payload


def reply(unique: int, payload: bytes = b"", error: int = 0) -> bytes:
    return OUT_HEADER.pack(OUT_HEADER.size + len(payload),
                           -error, unique) + payload


def parse_reply(buf: bytes) -> tuple[int, int, bytes]:
    """(unique, -errno, payload)"""
    length, error, unique = OUT_HEADER.unpack_from(buf)
    return unique, error, buf[OUT_HEADER.size:length]


# --- attr / entry ------------------------------------------------------------
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")  # 88 bytes (v7.9+)


def pack_attr(ino: int, size: int, mode: int, nlink: int = 1,
              uid: int = 0, gid: int = 0, mtime: float = 0.0,
              ctime: float = 0.0) -> bytes:
    blocks = (size + 511) // 512
    mt = int(mtime)
    mtn = int((mtime - mt) * 1e9)
    ct = int(ctime)
    ctn = int((ctime - ct) * 1e9)
    return ATTR.pack(
        ino, size, blocks,
        mt, mt, ct,  # atime mtime ctime (secs)
        mtn, mtn, ctn,  # nsecs
        mode, nlink, uid, gid, 0,  # rdev
        4096, 0,  # blksize padding
    )


def unpack_attr(buf: bytes) -> dict:
    (ino, size, blocks, atime, mtime, ctime, atn, mtn, ctn, mode, nlink,
     uid, gid, rdev, blksize, _) = ATTR.unpack_from(buf)
    return {"ino": ino, "size": size, "mode": mode, "nlink": nlink,
            "uid": uid, "gid": gid, "mtime": mtime + mtn / 1e9}


ENTRY_OUT_HEAD = struct.Struct("<QQQQII")  # nodeid gen entry_valid attr_valid + nsecs


def pack_entry_out(nodeid: int, attr: bytes, entry_valid: float = 1.0,
                   attr_valid: float = 1.0) -> bytes:
    ev, av = int(entry_valid), int(attr_valid)
    return ENTRY_OUT_HEAD.pack(
        nodeid, 0, ev, av,
        int((entry_valid - ev) * 1e9), int((attr_valid - av) * 1e9),
    ) + attr


def unpack_entry_out(buf: bytes) -> tuple[int, dict]:
    nodeid = struct.unpack_from("<Q", buf)[0]
    return nodeid, unpack_attr(buf[ENTRY_OUT_HEAD.size:])


ATTR_OUT_HEAD = struct.Struct("<QII")  # attr_valid, nsec, dummy


def pack_attr_out(attr: bytes, valid: float = 1.0) -> bytes:
    v = int(valid)
    return ATTR_OUT_HEAD.pack(v, int((valid - v) * 1e9), 0) + attr


def unpack_attr_out(buf: bytes) -> dict:
    return unpack_attr(buf[ATTR_OUT_HEAD.size:])


OPEN_OUT = struct.Struct("<QII")  # fh open_flags padding


def pack_open_out(fh: int, flags: int = 0) -> bytes:
    return OPEN_OUT.pack(fh, flags, 0)


def unpack_open_out(buf: bytes) -> int:
    return OPEN_OUT.unpack_from(buf)[0]


WRITE_OUT = struct.Struct("<II")


READ_IN = struct.Struct("<QQIIQII")  # fh offset size read_flags lock_owner flags pad
WRITE_IN = READ_IN  # same layout (write_flags in place of read_flags)
FLUSH_IN = struct.Struct("<QIIQ")  # fh unused padding lock_owner (24 bytes)
RELEASE_IN = struct.Struct("<QIIQ")  # fh flags release_flags lock_owner
FSYNC_IN = struct.Struct("<QII")  # fh fsync_flags padding (16 bytes)

INIT_IN = struct.Struct("<IIII")  # major minor max_readahead flags
INIT_OUT = struct.Struct("<IIIIHHIIHH32x")  # through map_alignment + unused

CREATE_IN = struct.Struct("<IIII")  # flags mode umask padding
MKDIR_IN = struct.Struct("<II")  # mode umask
RENAME_IN = struct.Struct("<Q")  # newdir
RENAME2_IN = struct.Struct("<QII")  # newdir flags padding
SETATTR_IN = struct.Struct("<IIQQQQQQIIIIIIII")  # 88 bytes (fuse_setattr_in)

FATTR_SIZE = 1 << 3
FATTR_MTIME = 1 << 5

DIRENT_HEAD = struct.Struct("<QQII")  # ino off namelen type


def pack_dirent(ino: int, off: int, name: bytes, dtype: int) -> bytes:
    entry = DIRENT_HEAD.pack(ino, off, len(name), dtype) + name
    pad = (8 - len(entry) % 8) % 8
    return entry + b"\0" * pad


def unpack_dirents(buf: bytes) -> list[tuple[int, str, int]]:
    """[(ino, name, dtype)]"""
    out = []
    pos = 0
    while pos + DIRENT_HEAD.size <= len(buf):
        ino, off, namelen, dtype = DIRENT_HEAD.unpack_from(buf, pos)
        name = buf[pos + DIRENT_HEAD.size: pos + DIRENT_HEAD.size + namelen]
        out.append((ino, name.decode(), dtype))
        entry_len = DIRENT_HEAD.size + namelen
        pos += entry_len + (8 - entry_len % 8) % 8
    return out


STATFS_OUT = struct.Struct("<QQQQQIIII28x")  # fuse_kstatfs


def pack_statfs(blocks=1 << 30, bfree=1 << 29, bavail=1 << 29,
                files=1 << 20, ffree=1 << 19) -> bytes:
    return STATFS_OUT.pack(blocks, bfree, bavail, files, ffree,
                           4096, 255, 4096, 0)
