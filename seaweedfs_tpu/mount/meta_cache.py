"""Mount-side metadata cache kept fresh by the filer's event stream.

Behavioral port of `weed/mount/meta_cache/`: entry lookups hit a local
cache; a background subscriber tails `/__meta__/events` and invalidates
(or updates) affected paths, so kernel-visible attributes converge on
external changes without per-op round trips.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict


class MetaCache:
    def __init__(self, filer_url: str, capacity: int = 4096) -> None:
        from seaweedfs_tpu.filer.filer_client import FilerClient

        self.fc = FilerClient(filer_url)
        self.capacity = capacity
        self._map: OrderedDict[str, dict | None] = OrderedDict()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --- lookups --------------------------------------------------------------
    def get_entry(self, path: str) -> dict | None:
        with self._lock:
            if path in self._map:
                self._map.move_to_end(path)
                return self._map[path]
        entry = self.fc.get_entry(path)
        with self._lock:
            self._map[path] = entry
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
        return entry

    def invalidate(self, path: str) -> None:
        with self._lock:
            self._map.pop(path, None)

    def put(self, path: str, entry: dict | None) -> None:
        with self._lock:
            self._map[path] = entry
            self._map.move_to_end(path)

    # --- subscription ---------------------------------------------------------
    def start_subscriber(self) -> None:
        self._thread = threading.Thread(target=self._follow, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _follow(self) -> None:
        from seaweedfs_tpu.server.httpd import http_request

        cursor = time.time_ns()
        url = self.fc.filer_url
        while not self._stop.is_set():
            try:
                status, _, body = http_request(
                    "GET",
                    f"{url}/__meta__/events?since_ns={cursor}&wait=2",
                    timeout=10,
                )
                if status != 200:
                    time.sleep(0.5)
                    continue
                out = json.loads(body)
                for ev in out["events"]:
                    for key in ("old_entry", "new_entry"):
                        e = ev.get(key)
                        if e:
                            self.invalidate(e["full_path"])
                cursor = out["next_ts_ns"]
            except Exception:
                time.sleep(0.5)
