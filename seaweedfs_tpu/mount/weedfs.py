"""WFS: the mount's filesystem logic over the FUSE wire protocol.

Behavioral port of `weed/mount/weedfs.go` + `weedfs_file_write.go:37` +
`weedfs_file_read.go` + `weedfs_file_sync.go`: inode↔path map, meta cache
with subscription invalidation, chunked page-writer pipeline on the write
path (sealed chunks upload asynchronously; FLUSH/FSYNC commits the entry),
visible-interval reads with a tiered chunk cache and readback of unflushed
dirty pages.

`WFS.handle(request_bytes) -> reply_bytes | None` serves one kernel
request; `serve(fd)` loops over a real /dev/fuse fd, and the test
transport calls `handle` directly with packed structs (same bytes either
way).
"""

from __future__ import annotations

import json
import stat as stat_mod
import threading
import time

from seaweedfs_tpu.filer.entry import FileChunk
from seaweedfs_tpu.filer.filechunks import view_from_chunks
from seaweedfs_tpu.util.chunk_cache import TieredChunkCache

from . import fuse_proto as fp
from .meta_cache import MetaCache
from .page_writer import UploadPipeline


def struct_unpack_fh(payload: bytes) -> tuple[int]:
    """Leading u64 fh shared by flush/release/fsync/releasedir structs."""
    import struct

    return struct.unpack_from("<Q", payload)


class FileHandle:
    def __init__(self, fh: int, path: str, wfs: "WFS") -> None:
        self.fh = fh
        self.path = path
        self.pipeline = UploadPipeline(
            wfs._upload_chunk_data, chunk_size=wfs.chunk_size
        )
        self.size_hint = 0
        self.dirty = False


class WFS:
    def __init__(self, filer_url: str, chunk_size: int | None = None,
                 read_only: bool = False,
                 chunk_cache_dir: str | None = None,
                 quota_mb: int = 0) -> None:
        from seaweedfs_tpu.filer.filer_client import FilerClient
        from seaweedfs_tpu.filer.wdclient import WeedClient
        from seaweedfs_tpu.server.httpd import get_json

        self.fc = FilerClient(filer_url)
        self.meta = MetaCache(filer_url)
        info = get_json(filer_url.rstrip("/") + "/__meta__/info")
        self.weed = WeedClient(info["master"])
        self.chunk_size = chunk_size or int(info.get("chunk_size") or 4 << 20)
        self.read_only = read_only
        self.chunk_cache = TieredChunkCache(disk_dir=chunk_cache_dir)

        self._ino_to_path: dict[int, str] = {1: "/"}
        self._path_to_ino: dict[str, int] = {"/": 1}
        self._next_ino = 2
        self._handles: dict[int, FileHandle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        # mount quota (`weed/mount/weedfs_quota.go` semantics): writes
        # fail ENOSPC once the mounted namespace's usage exceeds it, and
        # statfs advertises it as the filesystem size. 0 = unlimited;
        # adjustable at runtime via mount.configure (set_quota). Usage is
        # refreshed by a BACKGROUND ticker, like the reference — a walk
        # inside the single-threaded FUSE dispatch would freeze the whole
        # mount for the duration of a large namespace listing.
        self.quota_bytes = quota_mb * 1024 * 1024
        self._usage_bytes = 0
        self._usage_kick = threading.Event()
        self._usage_thread: threading.Thread | None = None
        if self.quota_bytes > 0:
            self._start_usage_ticker()

    # --- quota ---------------------------------------------------------------
    def set_quota(self, quota_mb: int) -> None:
        self.quota_bytes = quota_mb * 1024 * 1024
        if self.quota_bytes > 0:
            self._start_usage_ticker()
        self._usage_kick.set()  # refresh promptly

    def _start_usage_ticker(self) -> None:
        if self._usage_thread is not None and self._usage_thread.is_alive():
            return
        self._refresh_usage()  # first number synchronously (mount start)
        t = threading.Thread(target=self._usage_loop, daemon=True,
                             name="mount-quota-usage")
        self._usage_thread = t
        t.start()

    def _usage_loop(self) -> None:  # pragma: no cover - timing loop
        while True:
            self._usage_kick.wait(15.0)
            self._usage_kick.clear()
            self._refresh_usage()

    def _refresh_usage(self) -> None:
        def du(path: str) -> int:
            total = 0
            last = ""
            while True:
                out = self.fc.list(path, limit=10000, last_file_name=last)
                entries = out.get("Entries") or []
                for e in entries:
                    if e["IsDirectory"]:
                        total += du(e["FullPath"])
                    else:
                        total += int(e.get("FileSize") or 0)
                if len(entries) < 10000:
                    return total
                last = entries[-1]["FullPath"].rsplit("/", 1)[-1]

        try:
            self._usage_bytes = du("/")
        except Exception:
            pass  # filer hiccup / non-JSON error body: keep the stale value

    def _usage(self) -> int:
        return self._usage_bytes

    def _quota_exceeded(self) -> bool:
        return self.quota_bytes > 0 and self._usage_bytes >= self.quota_bytes

    # --- inode table ----------------------------------------------------------
    def _ino_for(self, path: str, entry: dict | None = None) -> int:
        """Inode for a path. All names of one hardlink set share an inode
        (keyed by the hardlink id — reference inodeToPath.AddPath in
        `weedfs_link.go`) so st_ino-based tools (rsync -H, du) see them as
        one file; reverse lookup keeps the first name."""
        with self._lock:
            hl = (entry or {}).get("hard_link_id") or ""
            if hl:
                key = "\0hl:" + hl  # cannot collide with a real path
                ino = self._path_to_ino.get(key)
                if ino is None:
                    ino = self._next_ino
                    self._next_ino += 1
                    self._path_to_ino[key] = ino
                if ino not in self._ino_to_path:
                    self._ino_to_path[ino] = path
                self._path_to_ino[path] = ino
                return ino
            ino = self._path_to_ino.get(path)
            if ino is None:
                ino = self._next_ino
                self._next_ino += 1
                self._path_to_ino[path] = ino
                self._ino_to_path[ino] = path
            return ino

    def _path_of(self, ino: int) -> str | None:
        with self._lock:
            return self._ino_to_path.get(ino)

    def _rename_ino(self, old: str, new: str) -> None:
        with self._lock:
            ino = self._path_to_ino.pop(old, None)
            if ino is not None:
                self._path_to_ino[new] = ino
                self._ino_to_path[ino] = new

    # --- storage helpers ------------------------------------------------------
    def _upload_chunk_data(self, data: bytes) -> str:
        out = self.weed.upload(data)
        return out["fid"]

    def _attr_from_entry(self, path: str, entry: dict) -> bytes:
        attrs = entry.get("attributes") or {}
        is_dir = bool(entry.get("is_directory"))
        size = attrs.get("file_size", 0)
        if not is_dir and entry.get("chunks"):
            size = max(size, max(
                c["offset"] + c["size"] for c in entry["chunks"]
            ))
        if not is_dir and entry.get("content"):
            size = max(size, len(bytes.fromhex(entry["content"])))
        mode = attrs.get("mode", 0o755 if is_dir else 0o644) & 0o7777
        mode |= fp.S_IFDIR if is_dir else fp.S_IFREG
        return fp.pack_attr(
            self._ino_for(path, entry), size, mode,
            nlink=2 if is_dir else max(1, entry.get("hard_link_counter", 0)),
            uid=attrs.get("uid", 0), gid=attrs.get("gid", 0),
            mtime=attrs.get("mtime", 0.0), ctime=attrs.get("crtime", 0.0),
        )

    def _commit_handle(self, h: FileHandle) -> int:
        """Seal + upload dirty pages, then write the entry with the merged
        chunk list (`weedfs_file_sync.go` doFlush)."""
        if not h.dirty:
            return 0
        try:
            new_chunks = h.pipeline.flush()
        except Exception:
            return fp.ERRNO_IO
        entry = self.meta.fc.get_entry(h.path) or {
            "full_path": h.path, "is_directory": False,
            "attributes": {"mode": 0o644, "mtime": time.time()},
            "chunks": [], "extended": {}, "content": "",
        }
        chunks = [FileChunk.from_dict(c) for c in entry.get("chunks") or []]
        chunks.extend(new_chunks)
        size = max(
            [h.size_hint] + [c.offset + c.size for c in chunks] or [0]
        )
        entry["chunks"] = [c.to_dict() for c in chunks]
        attrs = entry.setdefault("attributes", {})
        attrs["file_size"] = size
        attrs["mtime"] = time.time()
        entry["content"] = ""
        try:
            self.fc.put_entry(h.path, entry)
        except OSError:
            return fp.ERRNO_IO
        self.meta.put(h.path, self.fc.get_entry(h.path))
        h.dirty = False
        return 0

    def _read_range(self, entry: dict, offset: int, size: int,
                    handle: FileHandle | None) -> bytes:
        buf = bytearray(size)
        filled = 0
        if entry.get("content"):
            raw = bytes.fromhex(entry["content"])
            piece = raw[offset:offset + size]
            buf[:len(piece)] = piece
            filled = len(piece)
        chunks = [FileChunk.from_dict(c) for c in entry.get("chunks") or []]
        if chunks:
            views = view_from_chunks(chunks, offset, size)
            for view in views:
                data = self.chunk_cache.get_chunk(view.file_id)
                if data is None:
                    data = self.weed.fetch(view.file_id)
                    self.chunk_cache.set_chunk(view.file_id, data)
                piece = data[view.offset_in_chunk:
                             view.offset_in_chunk + view.size]
                dst = view.view_offset - offset
                buf[dst:dst + len(piece)] = piece
                filled = max(filled, dst + len(piece))
        # overlay unflushed dirty spans (readback-before-upload)
        if handle is not None:
            for abs_off, data in handle.pipeline.read_back(offset, size):
                dst = abs_off - offset
                buf[dst:dst + len(data)] = data
                filled = max(filled, dst + len(data))
        # clamp to logical EOF
        attrs = entry.get("attributes") or {}
        logical = attrs.get("file_size", 0)
        if chunks:
            logical = max(logical, max(c.offset + c.size for c in chunks))
        if handle is not None:
            logical = max(logical, handle.size_hint)
        end = min(size, max(filled, min(logical - offset, size)))
        return bytes(buf[:max(0, end)])

    # --- dispatcher -----------------------------------------------------------
    def handle(self, buf: bytes) -> bytes | None:
        hdr, payload = fp.parse_in(buf)
        op = hdr.opcode
        try:
            fn = {
                fp.INIT: self._op_init,
                fp.LOOKUP: self._op_lookup,
                fp.GETATTR: self._op_getattr,
                fp.SETATTR: self._op_setattr,
                fp.OPENDIR: self._op_open,
                fp.OPEN: self._op_open,
                fp.READDIR: self._op_readdir,
                fp.RELEASEDIR: self._op_releasedir,
                fp.CREATE: self._op_create,
                fp.MKDIR: self._op_mkdir,
                fp.WRITE: self._op_write,
                fp.READ: self._op_read,
                fp.FLUSH: self._op_flush,
                fp.FSYNC: self._op_flush,
                fp.RELEASE: self._op_release,
                fp.UNLINK: self._op_unlink,
                fp.LINK: self._op_link,
                fp.RMDIR: self._op_rmdir,
                fp.RENAME: self._op_rename,
                fp.RENAME2: self._op_rename2,
                fp.STATFS: self._op_statfs,
                fp.ACCESS: lambda h, p: fp.reply(h.unique),
            }.get(op)
            if op == fp.FORGET:
                return None  # no reply by protocol
            if fn is None:
                return fp.reply(hdr.unique, error=fp.ERRNO_NOSYS)
            return fn(hdr, payload)
        except Exception:
            return fp.reply(hdr.unique, error=fp.ERRNO_IO)

    MAX_WRITE = 1 << 17  # negotiated in INIT; read buffer must exceed it

    def serve(self, fd: int) -> None:  # pragma: no cover - needs /dev/fuse
        import errno
        import os

        self.meta.start_subscriber()
        bufsize = self.MAX_WRITE + (1 << 16)  # kernel demands max_write+header
        while True:
            try:
                req = os.read(fd, bufsize)
            except OSError as e:
                if e.errno in (errno.EINTR, errno.EAGAIN):
                    continue
                break  # ENODEV = unmounted
            if not req:
                break
            out = self.handle(req)
            if out is not None:
                try:
                    os.write(fd, out)
                except OSError:
                    pass  # request aborted (e.g. interrupted syscall)

    # --- ops ------------------------------------------------------------------
    def _op_init(self, hdr, payload) -> bytes:
        major, minor, max_ra, flags = fp.INIT_IN.unpack_from(payload)
        out = fp.INIT_OUT.pack(
            7, min(31, minor), max_ra, 0,  # no special flags
            12, 10,  # max_background, congestion
            self.MAX_WRITE, 1,  # max_write, time_gran
            (self.MAX_WRITE // 4096), 0,  # max_pages, map_alignment
        )
        return fp.reply(hdr.unique, out)

    def _child_path(self, parent_ino: int, name: str) -> str | None:
        parent = self._path_of(parent_ino)
        if parent is None:
            return None
        return (parent.rstrip("/") + "/" + name) if parent != "/" \
            else "/" + name

    def _op_lookup(self, hdr, payload) -> bytes:
        name = payload.split(b"\0", 1)[0].decode()
        path = self._child_path(hdr.nodeid, name)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        entry = self.meta.get_entry(path)
        if entry is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        attr = self._attr_from_entry(path, entry)
        return fp.reply(
            hdr.unique, fp.pack_entry_out(self._ino_for(path), attr)
        )

    def _op_getattr(self, hdr, payload) -> bytes:
        path = self._path_of(hdr.nodeid)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        if path == "/":
            attr = fp.pack_attr(1, 0, fp.S_IFDIR | 0o755, nlink=2)
            return fp.reply(hdr.unique, fp.pack_attr_out(attr))
        entry = self.meta.get_entry(path)
        if entry is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        return fp.reply(
            hdr.unique, fp.pack_attr_out(self._attr_from_entry(path, entry))
        )

    def _op_setattr(self, hdr, payload) -> bytes:
        path = self._path_of(hdr.nodeid)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        fields = fp.SETATTR_IN.unpack_from(payload)
        valid, _, fh, new_size = fields[0], fields[1], fields[2], fields[3]
        entry = self.meta.fc.get_entry(path)
        if entry is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        if valid & fp.FATTR_SIZE:
            # truncate (`weedfs_attr.go` setAttr size change)
            chunks = [FileChunk.from_dict(c)
                      for c in entry.get("chunks") or []]
            kept = [c for c in chunks if c.offset < new_size]
            for c in kept:
                if c.offset + c.size > new_size:
                    c.size = new_size - c.offset
            entry["chunks"] = [c.to_dict() for c in kept]
            if entry.get("content"):
                entry["content"] = bytes.fromhex(
                    entry["content"])[:new_size].hex()
            entry.setdefault("attributes", {})["file_size"] = new_size
            self.fc.put_entry(path, entry)
            self.meta.invalidate(path)
            entry = self.meta.get_entry(path)
        return fp.reply(
            hdr.unique, fp.pack_attr_out(self._attr_from_entry(path, entry))
        )

    def _op_open(self, hdr, payload) -> bytes:
        path = self._path_of(hdr.nodeid)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = FileHandle(fh, path, self)
        return fp.reply(hdr.unique, fp.pack_open_out(fh))

    def _op_create(self, hdr, payload) -> bytes:
        if self.read_only:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        flags, mode, umask, _ = fp.CREATE_IN.unpack_from(payload)
        name = payload[fp.CREATE_IN.size:].split(b"\0", 1)[0].decode()
        path = self._child_path(hdr.nodeid, name)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        entry = {
            "full_path": path, "is_directory": False,
            "attributes": {"mode": mode & 0o7777, "mtime": time.time(),
                           "crtime": time.time(), "file_size": 0,
                           "uid": hdr.uid, "gid": hdr.gid},
            "chunks": [], "extended": {}, "content": "",
        }
        try:
            self.fc.put_entry(path, entry)
        except OSError:
            return fp.reply(hdr.unique, error=fp.ERRNO_IO)
        self.meta.put(path, self.fc.get_entry(path))
        ino = self._ino_for(path)
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = FileHandle(fh, path, self)
        attr = self._attr_from_entry(path, self.meta.get_entry(path) or entry)
        return fp.reply(
            hdr.unique,
            fp.pack_entry_out(ino, attr) + fp.pack_open_out(fh),
        )

    def _op_mkdir(self, hdr, payload) -> bytes:
        if self.read_only:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        mode, umask = fp.MKDIR_IN.unpack_from(payload)
        name = payload[fp.MKDIR_IN.size:].split(b"\0", 1)[0].decode()
        path = self._child_path(hdr.nodeid, name)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        try:
            self.fc.mkdir(path)
        except OSError:
            return fp.reply(hdr.unique, error=fp.ERRNO_EXIST)
        self.meta.invalidate(path)
        entry = self.meta.get_entry(path)
        if entry is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_IO)
        return fp.reply(
            hdr.unique,
            fp.pack_entry_out(self._ino_for(path),
                              self._attr_from_entry(path, entry)),
        )

    def _op_readdir(self, hdr, payload) -> bytes:
        fields = fp.READ_IN.unpack_from(payload)
        offset, size = fields[1], fields[2]
        path = self._path_of(hdr.nodeid)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        names: list[tuple[str, bool]] = [(".", True), ("..", True)]
        listing = self.fc.list(path, limit=100000)
        for e in listing.get("Entries") or []:
            names.append(
                (e["FullPath"].rsplit("/", 1)[-1], e["IsDirectory"])
            )
        out = b""
        for i, (name, is_dir) in enumerate(names):
            if i < offset:
                continue
            child = path if name in (".", "..") else (
                (path.rstrip("/") + "/" + name) if path != "/" else "/" + name
            )
            ent = fp.pack_dirent(
                self._ino_for(child), i + 1, name.encode(),
                stat_mod.S_IFDIR >> 12 if is_dir else stat_mod.S_IFREG >> 12,
            )
            if len(out) + len(ent) > size:
                break
            out += ent
        return fp.reply(hdr.unique, out)

    def _op_releasedir(self, hdr, payload) -> bytes:
        return fp.reply(hdr.unique)

    def _op_write(self, hdr, payload) -> bytes:
        if self.read_only:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        if self._quota_exceeded():
            return fp.reply(hdr.unique, error=fp.ERRNO_NOSPC)
        fields = fp.WRITE_IN.unpack_from(payload)
        fh, offset, size = fields[0], fields[1], fields[2]
        data = payload[fp.WRITE_IN.size:fp.WRITE_IN.size + size]
        h = self._handles.get(fh)
        if h is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        h.pipeline.write(offset, data)
        h.dirty = True
        h.size_hint = max(h.size_hint, offset + len(data))
        return fp.reply(hdr.unique, fp.WRITE_OUT.pack(len(data), 0))

    def _op_read(self, hdr, payload) -> bytes:
        fields = fp.READ_IN.unpack_from(payload)
        fh, offset, size = fields[0], fields[1], fields[2]
        h = self._handles.get(fh)
        path = h.path if h is not None else self._path_of(hdr.nodeid)
        if path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        entry = self.meta.get_entry(path)
        if entry is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        data = self._read_range(entry, offset, size, h)
        return fp.reply(hdr.unique, data)

    def _op_flush(self, hdr, payload) -> bytes:
        # fuse_flush_in/fsync_in lead with the fh (24/16-byte structs —
        # NOT read_in; the kernel rejects daemons that misparse these)
        (fh,) = struct_unpack_fh(payload)
        h = self._handles.get(fh)
        if h is None:
            return fp.reply(hdr.unique)
        err = self._commit_handle(h)
        return fp.reply(hdr.unique, error=err)

    def _op_release(self, hdr, payload) -> bytes:
        (fh,) = struct_unpack_fh(payload)
        h = self._handles.pop(fh, None)
        if h is not None:
            self._commit_handle(h)
        return fp.reply(hdr.unique)

    def _op_unlink(self, hdr, payload) -> bytes:
        if self.read_only:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        name = payload.split(b"\0", 1)[0].decode()
        path = self._child_path(hdr.nodeid, name)
        if path is None or self.meta.get_entry(path) is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        self.fc.delete(path)
        self.meta.invalidate(path)
        return fp.reply(hdr.unique)

    def _op_rmdir(self, hdr, payload) -> bytes:
        if self.read_only:
            return fp.reply(hdr.unique, error=fp.ERRNO_INVAL)
        name = payload.split(b"\0", 1)[0].decode()
        path = self._child_path(hdr.nodeid, name)
        if path is None or self.meta.get_entry(path) is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        listing = self.fc.list(path)
        if listing.get("Entries"):
            return fp.reply(hdr.unique, error=fp.ERRNO_NOTEMPTY)
        self.fc.delete(path, recursive=True)
        self.meta.invalidate(path)
        return fp.reply(hdr.unique)

    def _rename_common(self, hdr, newdir: int, rest: bytes) -> bytes:
        old_name, new_name = rest.split(b"\0")[:2]
        old_path = self._child_path(hdr.nodeid, old_name.decode())
        new_path = self._child_path(newdir, new_name.decode())
        if old_path is None or new_path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        try:
            self.fc.rename(old_path, new_path)
        except OSError:
            return fp.reply(hdr.unique, error=fp.ERRNO_IO)
        self._rename_ino(old_path, new_path)
        self.meta.invalidate(old_path)
        self.meta.invalidate(new_path)
        return fp.reply(hdr.unique)

    def _op_link(self, hdr, payload) -> bytes:
        """Hard link (`weed/mount/weedfs_link.go`): payload is
        fuse_link_in{oldnodeid u64} + name; nodeid is the new parent."""
        import struct as _struct

        (oldnodeid,) = _struct.unpack_from("<Q", payload)
        name = payload[8:].split(b"\0", 1)[0].decode()
        old_path = self._path_of(oldnodeid)
        new_path = self._child_path(hdr.nodeid, name)
        if old_path is None or new_path is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_NOENT)
        try:
            self.meta.fc.link(old_path, new_path)
        except IOError:
            return fp.reply(hdr.unique, error=fp.ERRNO_IO)
        self.meta.invalidate(old_path)
        self.meta.invalidate(new_path)  # clear the cached negative lookup
        entry = self.meta.get_entry(new_path)
        if entry is None:
            return fp.reply(hdr.unique, error=fp.ERRNO_IO)
        attr = self._attr_from_entry(new_path, entry)
        return fp.reply(
            hdr.unique, fp.pack_entry_out(self._ino_for(new_path), attr)
        )

    def _op_rename(self, hdr, payload) -> bytes:
        (newdir,) = fp.RENAME_IN.unpack_from(payload)
        return self._rename_common(hdr, newdir, payload[fp.RENAME_IN.size:])

    def _op_rename2(self, hdr, payload) -> bytes:
        newdir, flags, _ = fp.RENAME2_IN.unpack_from(payload)
        return self._rename_common(hdr, newdir, payload[fp.RENAME2_IN.size:])

    def _op_statfs(self, hdr, payload) -> bytes:
        if self.quota_bytes > 0:
            blocks = max(1, self.quota_bytes // 4096)
            free = max(0, (self.quota_bytes - self._usage()) // 4096)
            return fp.reply(hdr.unique, fp.pack_statfs(
                blocks=blocks, bfree=free, bavail=free))
        return fp.reply(hdr.unique, fp.pack_statfs())
