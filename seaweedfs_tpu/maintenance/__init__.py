"""Autonomous maintenance: detect → plan → heal.

The subsystem that turns four PRs of observability (topology gauges,
under-replication / missing-shard counts, history rings, alerts) into
automated operation: detectors (detectors.py) scan the master's live
topology and emit typed RepairTasks, a bounded scheduler (scheduler.py)
dedups/prioritizes/throttles them, and executors (executors.py) heal
through the same plan/apply helpers the admin-shell repair verbs use.
MaintenanceDaemon (daemon.py) runs the loop inside the master behind
`-maintenance` (off by default; `-maintenance.dryRun` plans without
executing) and serves /debug/maintenance.
"""

from .daemon import ALERT_SCANS, MAINTENANCE_FAMILIES, MaintenanceDaemon, \
    ensure_metrics
from .detectors import DETECTORS, TASK_TYPES, RepairTask, TaskSpec, scan
from .executors import EXECUTORS, execute
from .scheduler import RepairScheduler

__all__ = [
    "ALERT_SCANS", "DETECTORS", "EXECUTORS", "MAINTENANCE_FAMILIES",
    "MaintenanceDaemon", "RepairScheduler", "RepairTask", "TASK_TYPES",
    "TaskSpec", "ensure_metrics", "execute", "scan",
]
