"""Integrity scrubbing & anti-entropy: find silent damage before a read does.

Every robustness layer so far is *reactive* — degraded reads reconstruct
(PR 9), the daemon heals what heartbeats and gauges reveal (PR 5), the
flight recorder explains it afterwards (PR 13). But bitrot in a cold
needle, a torn sealed shard, or a silently diverged replica is invisible
until a client read trips over it. This module is the *proactive* loop:

  * **Needle scrub** — walk a volume's needle map in bounded batches and
    CRC-verify every live record. Equal-length data segments verify in
    bulk through the batched CRC32C kernel (`ops/crc32c_kernel.py`
    crc32c_batch — the GF(2) matmul bulk-hash offload of
    arXiv:1202.3669), odd sizes through the scalar `storage/crc.py`
    path; scrub GB/s is recorded per kernel so the speedup is measured,
    not assumed.
  * **EC parity scrub** — recompute-and-compare a sampled column slice
    per stripe through the same GF kernel the encoders use; a slice
    mismatch escalates to a full-width check that LOCATES the corrupt
    shard (the erasure code's redundancy is the checksum).
  * **Anti-entropy digests** — each volume hashes its live needle map
    into an order-independent digest that rides the heartbeat, so the
    master detects replica divergence without moving a byte of data.
  * **Tmp GC** — abandoned `_ShardWriters` `.tmp` litter from aborted /
    replaced pipelined rebuilds (PR 11) is swept, age-gated so in-flight
    rebuilds are never touched.

Findings are typed `ScrubFinding`s. They ride the heartbeat to the
master, whose `scrub` maintenance task routes each kind to an EXISTING
heal (this module plans/applies, the PR-5 scheduler paces):

    corrupt_needle     -> re-copy the one needle from a verified-good
                          replica (or reconstruct locally from EC parity)
    corrupt_shard      -> delete the corrupt shard (silent damage becomes
                          visible loss) -> the missing-shard detector's
                          ec_rebuild heals it, pipelined per PR 11
    parity_mismatch    -> /admin/ec/online/rebuild re-arms the striper
                          and re-encodes from the durable .dat
    replica_divergence -> needle-level re-sync from the digest-majority
                          holder (size-ordered tie-break: append-only
                          volumes grow on every op, so the longest .dat
                          has seen the most history)
    tmp_litter         -> removed by the scrub pass itself (reported,
                          never routed)

Scrubbing must never starve foreground traffic (the arXiv:1709.05365
throttling lesson): every byte the scrubber reads is paid for through a
token bucket, `now`/`sleep` injectable so the pacing is deterministic
under test.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass, field

import numpy as np

# Finding kinds: they ride into the `kind` label of
# SeaweedFS_volume_scrub_{findings,repairs}_total and the scrub_finding
# flight-recorder event — linted by tools/check_metric_names.py like the
# other reason sets.
SCRUB_FINDING_KINDS = (
    "corrupt_needle",      # a live needle's data fails its CRC32C
    "corrupt_shard",       # a sealed EC shard is short, unreadable, or
                           # located as the stripe-parity mismatch
    "parity_mismatch",     # an online-EC stripe's recomputed parity
                           # disagrees with the durable parity bytes
    "replica_divergence",  # replica needle-map digests disagree
    "tmp_litter",          # abandoned .tmp shard files (aborted rebuild)
)

# .tmp litter pattern: the _ShardWriters convention (shard file + .tmp)
_TMP_RE = re.compile(r"\.ec\d\d\.tmp$")

# batch only groups at least this big through the device kernel: smaller
# groups aren't worth a compile/launch, the scalar path wins
MIN_BATCH = 16
# and only blocks up to this long (the (n, L*8) x (L*8, 32) operand
# grows linearly with L; past this the scalar slice-by-8 is fine)
MAX_BATCH_BLOCK = 1 << 20

_metrics_cache = None


def ensure_metrics(registry=None):
    """Register (idempotently) the scrub families; returns
    (bytes_total{kernel}, seconds{kernel}, findings_total{kind},
    repairs_total{kind})."""
    global _metrics_cache
    if registry is None and _metrics_cache is not None:
        return _metrics_cache
    from seaweedfs_tpu.stats import default_registry

    reg = registry if registry is not None else default_registry()
    out = (
        reg.counter(
            "SeaweedFS_volume_scrub_bytes_total",
            "bytes integrity-verified by the scrubber, by kernel"
            " (batched = bulk CRC32C matmul, scalar = table CRC,"
            " gf = EC parity recompute)",
            ("kernel",),
        ),
        reg.histogram(
            "SeaweedFS_volume_scrub_seconds",
            "wall seconds per scrub verification slice, by kernel"
            " (GB/s = bytes/sum)",
            ("kernel",),
        ),
        reg.counter(
            "SeaweedFS_volume_scrub_findings_total",
            "silent-damage findings detected by scrub passes, by kind",
            ("kind",),
        ),
        reg.counter(
            "SeaweedFS_volume_scrub_repairs_total",
            "scrub findings routed into a repair, by kind",
            ("kind",),
        ),
    )
    if registry is None:
        _metrics_cache = out
    return out


@dataclass(frozen=True)
class ScrubFinding:
    """One piece of silent damage a scrub pass proved. `node` is the
    holder that detected it (and that the repair targets); `source_node`
    is only set for replica_divergence (the digest-majority holder to
    re-sync from)."""

    kind: str
    volume_id: int
    node: str = ""
    collection: str = ""
    needle: int | None = None
    shard: int | None = None
    source_node: str = ""
    detail: str = ""
    detected_at: float = field(default_factory=time.time)

    def __post_init__(self):
        if self.kind not in SCRUB_FINDING_KINDS:
            raise ValueError(f"unknown scrub finding kind {self.kind!r}")

    @property
    def key(self) -> tuple:
        return (self.kind, self.volume_id, self.needle, self.shard)

    def to_dict(self) -> dict:
        out = {
            "kind": self.kind, "volume_id": self.volume_id,
            "node": self.node, "collection": self.collection,
            "detail": self.detail,
            "detected_at": round(self.detected_at, 3),
        }
        if self.needle is not None:
            out["needle"] = self.needle
        if self.shard is not None:
            out["shard"] = self.shard
        if self.source_node:
            out["source_node"] = self.source_node
        return out

    @staticmethod
    def from_dict(d: dict) -> "ScrubFinding":
        return ScrubFinding(
            kind=d["kind"], volume_id=int(d["volume_id"]),
            node=d.get("node", ""), collection=d.get("collection", ""),
            needle=d.get("needle"), shard=d.get("shard"),
            source_node=d.get("source_node", ""),
            detail=d.get("detail", ""),
            detected_at=float(d.get("detected_at", 0.0)) or time.time(),
        )


class TokenBucket:
    """Byte-budget throttle: take(n) returns how long the caller must
    sleep before the n bytes are within budget. Deterministic under an
    injected clock — the foreground-impact bound is a provable property
    of the pacing, not a hope."""

    def __init__(self, rate: float, burst: float | None = None) -> None:
        self.rate = float(rate)  # bytes per second
        self.burst = float(burst if burst is not None else rate)
        self._tokens = self.burst
        self._ts: float | None = None

    def take(self, n: int, now: float) -> float:
        """Spend n bytes; returns seconds to sleep (0.0 when within
        budget). The bucket may go negative — the debt converts into the
        returned sleep, so any window's bytes stay <= rate*window+burst."""
        if self.rate <= 0:
            return 0.0
        if self._ts is None:
            self._ts = now
        self._tokens = min(
            self.burst, self._tokens + (now - self._ts) * self.rate
        )
        self._ts = now
        self._tokens -= n
        if self._tokens >= 0:
            return 0.0
        return -self._tokens / self.rate


# --- anti-entropy digest -----------------------------------------------------
# the digest itself lives with the needle maps (storage/needle_map.py —
# storage must not import maintenance); re-exported here because the
# scrub subsystem is its consumer-facing home
from seaweedfs_tpu.storage.needle_map import (  # noqa: E402,F401
    EMPTY_NEEDLE_DIGEST,
    needle_set_digest,
)


# --- needle record light parse ----------------------------------------------
def _light_parse(blob: bytes, size: int):
    """(data_bytes, stored_crc) from a raw v2/v3 needle record WITHOUT
    verifying — the scrubber verifies in bulk. Raises ValueError on a
    structurally torn record."""
    from seaweedfs_tpu.storage.types import (
        NEEDLE_HEADER_SIZE,
        get_u32,
    )

    if size <= 0:
        return b"", 0
    if len(blob) < NEEDLE_HEADER_SIZE + size + 4:
        raise ValueError("record shorter than its declared size")
    data_size = get_u32(blob, NEEDLE_HEADER_SIZE)
    if data_size + 4 > size:
        raise ValueError("data section out of range")
    data = blob[NEEDLE_HEADER_SIZE + 4:NEEDLE_HEADER_SIZE + 4 + data_size]
    stored = get_u32(blob, NEEDLE_HEADER_SIZE + size)
    return data, stored


def _batch_crc32c(blocks: np.ndarray) -> np.ndarray:
    """Bulk CRC32C of (n, L) uint8 blocks: one GIL-released native
    `sw_crc32c_batch` call when the host lib is present (the serving
    path's batch hasher — ~6x the scalar loop on 4K blobs, BENCH r03),
    else the GF(2)-matmul device kernel (ops/crc32c_kernel.py)."""
    try:
        from seaweedfs_tpu.native import lib

        if lib is not None:
            return lib.crc32c_batch(blocks, *blocks.shape)
    except Exception:
        pass
    from seaweedfs_tpu.ops.crc32c_kernel import crc32c_batch

    return crc32c_batch(blocks)


def _crc_batch_ok(datas: list[bytes], stored: list[int],
                  use_batch: bool) -> tuple[list[bool], str]:
    """Verify equal-length data blocks against their stored CRCs.
    Returns (ok flags, kernel used). The batched path accepts the legacy
    on-disk CRC transform exactly like Needle.from_bytes does."""
    from seaweedfs_tpu.storage import crc as crc_mod

    n = len(datas)
    length = len(datas[0])
    if use_batch and n >= MIN_BATCH and 0 < length <= MAX_BATCH_BLOCK:
        try:
            blocks = np.frombuffer(
                b"".join(datas), dtype=np.uint8
            ).reshape(n, length)
            actual = _batch_crc32c(blocks).astype(np.uint64)
            stored_a = np.asarray(stored, dtype=np.uint64)
            # legacy value: rotate + magic, vectorized (crc.legacy_value)
            rotated = ((actual >> np.uint64(15)) | (actual << np.uint64(17))) \
                & np.uint64(0xFFFFFFFF)
            legacy = (rotated + np.uint64(0xA282EAD8)) & np.uint64(0xFFFFFFFF)
            ok = (stored_a == actual) | (stored_a == legacy)
            return [bool(x) for x in ok], "batched"
        except Exception:
            pass  # no native lib, no jax: the scalar path is the answer
    out = []
    for data, want in zip(datas, stored):
        actual = crc_mod.crc32c(data)
        out.append(want == actual or want == crc_mod.legacy_value(actual))
    return out, "scalar"


class VolumeScrubber:
    """Background integrity scrubber for one volume server's Store.

    A pass walks every volume (or one, when scoped): live needles are
    CRC-verified in bulk, online-EC parity is recomputed-and-compared on
    sampled stripe rows, sealed EC shards are length- and parity-checked,
    and stale `.tmp` rebuild litter is swept. Every byte read pays the
    token bucket first, so a pass can never starve foreground reads.
    Findings persist (deduped by key) until a later pass — or a repair
    endpoint — resolves them; unresolved findings ride the heartbeat."""

    def __init__(
        self,
        store,
        node_id: str = "",
        rate_mb: float = 8.0,
        batch_bytes: int = 4 << 20,
        sample_bytes: int = 4096,
        sample_rows: int = 4,
        tmp_max_age: float = 3600.0,
        use_batch: bool = True,
        active_tmp_paths=None,
        now=None,
        sleep=None,
    ) -> None:
        self.store = store
        self.node_id = node_id
        self.bucket = TokenBucket(rate_mb * 1024 * 1024)
        self.batch_bytes = batch_bytes
        self.sample_bytes = sample_bytes
        self.sample_rows = sample_rows
        self.tmp_max_age = tmp_max_age
        self.use_batch = use_batch
        # callback -> set of .tmp paths belonging to IN-FLIGHT rebuilds
        # (the server's _partial_rebuilds writers): never swept, any age
        self._active_tmp_paths = active_tmp_paths or (lambda: set())
        self._now = now or time.monotonic
        self._sleep = sleep or time.sleep
        self._lock = threading.Lock()
        self._findings: dict[tuple, ScrubFinding] = {}
        # the volumes scrub passes are scanning RIGHT NOW (refcounted —
        # the periodic loop and an operator/repair-driven targeted pass
        # can overlap). Rides heartbeats as `scrub_active` so the
        # master's vacuum detector skips them: a compaction swapping
        # (nm, dat) mid-scrub wastes the pass at best and fabricates
        # suspects at worst.
        self._scrub_holds: dict[int, int] = {}
        (self._m_bytes, self._m_seconds, self._m_findings,
         self._m_repairs) = ensure_metrics()
        self.stats = {
            "passes": 0, "bytes_scanned": 0, "seconds": 0.0,
            "needles_checked": 0, "stripes_checked": 0,
            "findings": 0, "resolved": 0, "tmp_removed": 0,
            "throttle_waits": 0, "last_pass_at": 0.0,
        }

    # --- throttle -------------------------------------------------------------
    def _pay(self, nbytes: int) -> None:
        wait = self.bucket.take(nbytes, self._now())
        if wait > 0:
            self.stats["throttle_waits"] += 1
            self._sleep(wait)

    def _observe(self, kernel: str, nbytes: int, dt: float) -> None:
        self.stats["bytes_scanned"] += nbytes
        self.stats["seconds"] += dt
        self._m_bytes.labels(kernel).inc(nbytes)
        self._m_seconds.labels(kernel).observe(dt)

    # --- findings -------------------------------------------------------------
    def _record(self, f: ScrubFinding) -> None:
        with self._lock:
            fresh = f.key not in self._findings
            self._findings[f.key] = f
        if fresh:
            self.stats["findings"] += 1
            self._m_findings.labels(f.kind).inc()
            from seaweedfs_tpu.stats import events as events_mod

            events_mod.emit("scrub_finding", volume=f.volume_id,
                            node=f.node or None, kind=f.kind,
                            collection=f.collection or "default",
                            **({"needle": f"{f.needle:x}"}
                               if f.needle is not None else {}),
                            **({"shard": f.shard}
                               if f.shard is not None else {}),
                            detail=f.detail[:120])

    def resolve(self, kind: str | None = None, volume: int | None = None,
                needle: int | None = None) -> int:
        """Drop findings a repair just addressed (re-verification at the
        next pass is the ground truth; this keeps the heartbeat from
        re-advertising healed damage for a whole scrub interval)."""
        dropped = 0
        with self._lock:
            for key in list(self._findings):
                f = self._findings[key]
                if kind is not None and f.kind != kind:
                    continue
                if volume is not None and f.volume_id != volume:
                    continue
                if needle is not None and f.needle != needle:
                    continue
                del self._findings[key]
                dropped += 1
        self.stats["resolved"] += dropped
        return dropped

    def unresolved(self) -> list[dict]:
        with self._lock:
            return [f.to_dict() for f in self._findings.values()]

    def active_volumes(self) -> list[int]:
        """Volume ids scrub passes hold RIGHT NOW (one per concurrent
        pass). Rides heartbeats so `vacuum_candidates` skips them until
        the pass moves on."""
        with self._lock:
            return sorted(self._scrub_holds)

    def _hold(self, vid: int | None, prev: int | None) -> int | None:
        """Move one pass's hold from `prev` to `vid` (refcounted: an
        overlapping pass on the same volume keeps it held). Returns
        `vid` so callers can thread the current hold through."""
        with self._lock:
            if prev is not None:
                n = self._scrub_holds.get(prev, 0) - 1
                if n <= 0:
                    self._scrub_holds.pop(prev, None)
                else:
                    self._scrub_holds[prev] = n
            if vid is not None:
                self._scrub_holds[vid] = self._scrub_holds.get(vid, 0) + 1
        return vid

    # --- the pass -------------------------------------------------------------
    def scrub_pass(self, volume_id: int | None = None) -> list[ScrubFinding]:
        """One bounded, throttled pass. Returns the findings of THIS
        pass; the persistent set is reconciled (damage that no longer
        reproduces is resolved)."""
        found: list[ScrubFinding] = []
        # per-kind completed scopes: a scan that THREW mid-volume proved
        # nothing — reconciling its scope would silently resolve (and
        # stop advertising) genuine damage the repair hasn't healed yet
        scanned: dict[str, set[int]] = {
            "corrupt_needle": set(), "corrupt_shard": set(),
            "parity_mismatch": set(),
        }
        held: int | None = None
        try:
            for loc in self.store.locations:
                for v in list(loc.volumes.values()):
                    if volume_id is not None and v.id != volume_id:
                        continue
                    held = self._hold(v.id, held)
                    try:
                        found.extend(self._scrub_needles(v))
                        scanned["corrupt_needle"].add(v.id)
                    except Exception:
                        pass  # an unloadable volume must not sink the pass
                    w = getattr(v, "online_ec", None)
                    if w is not None and w.active and not w.sealed:
                        try:
                            found.extend(self._scrub_online_parity(v, w))
                            scanned["parity_mismatch"].add(v.id)
                        except Exception:
                            pass
                for ev in list(loc.ec_volumes.values()):
                    if volume_id is not None and ev.volume_id != volume_id:
                        continue
                    held = self._hold(ev.volume_id, held)
                    try:
                        found.extend(self._scrub_sealed_ec(ev))
                        scanned["corrupt_shard"].add(ev.volume_id)
                    except Exception:
                        pass
                if volume_id is None:
                    try:
                        found.extend(self._gc_tmp_litter(loc.directory))
                    except Exception:
                        pass
        finally:
            held = self._hold(None, held)
        # reconcile: a prior finding whose scope COMPLETED this pass
        # without reproducing it was healed (or was transient)
        fresh_keys = {f.key for f in found}
        with self._lock:
            for key in list(self._findings):
                f = self._findings[key]
                if f.volume_id in scanned.get(f.kind, ()) \
                        and key not in fresh_keys:
                    del self._findings[key]
                    self.stats["resolved"] += 1
        for f in found:
            self._record(f)
        self.stats["passes"] += 1
        self.stats["last_pass_at"] = time.time()
        return found

    # --- needle scrub ---------------------------------------------------------
    @staticmethod
    def _confirm_corrupt(v, needle_id: int) -> bool:
        """Re-verify a suspected needle through the seqlock-disciplined
        direct read path before alarming: the bulk scan reads (nm, dat)
        lock-free, so a vacuum commit swapping both mid-scan can pair
        the old map's offset with the new file and fabricate damage.
        Real corruption fails here too (deliberately NOT read_needle —
        its degraded ladder would reconstruct from parity and hide the
        on-disk rot this pass exists to surface)."""
        for _ in range(3):
            gen = v._compact_gen
            if gen & 1:  # swap in flight: wait it out
                time.sleep(0.001)
                continue
            try:
                v._read_needle_once(needle_id, None)
                return False  # reads clean: a transient race, not rot
            except Exception as e:
                from seaweedfs_tpu.storage.volume import NotFound

                if isinstance(e, NotFound) and v._compact_gen == gen:
                    return False  # deleted/compacted away meanwhile
                if v._compact_gen == gen:
                    return True  # stable generation, still failing
        # the generation kept moving (a slow vacuum commit outlasted the
        # retries): UNPROVEN, not corrupt — the next pass re-checks.
        # Returning True here would fabricate bitrot out of a slow swap.
        return False

    def _scrub_needles(self, v) -> list[ScrubFinding]:
        """CRC-verify every live needle, reading in batch_bytes slices
        and verifying equal-length data in bulk through crc32c_batch."""
        from seaweedfs_tpu.storage.needle import get_actual_size

        findings: list[ScrubFinding] = []
        version = v.version()
        batch: list[tuple[int, bytes, int]] = []  # (needle_id, data, crc)
        batch_bytes = 0

        def suspect(nid: int, detail: str) -> None:
            if self._confirm_corrupt(v, nid):
                findings.append(ScrubFinding(
                    "corrupt_needle", v.id, node=self.node_id,
                    collection=v.collection, needle=nid, detail=detail,
                ))

        def flush() -> None:
            nonlocal batch, batch_bytes
            if not batch:
                return
            by_len: dict[int, list[int]] = {}
            for i, (_nid, data, _crc) in enumerate(batch):
                by_len.setdefault(len(data), []).append(i)
            for _length, idxs in by_len.items():
                datas = [batch[i][1] for i in idxs]
                stored = [batch[i][2] for i in idxs]
                nbytes = sum(len(d) for d in datas)
                t0 = time.perf_counter()
                ok, kernel = _crc_batch_ok(datas, stored, self.use_batch)
                self._observe(kernel, nbytes, time.perf_counter() - t0)
                for flag, i in zip(ok, idxs):
                    if not flag:
                        suspect(batch[i][0], "data CRC32C mismatch")
            self.stats["needles_checked"] += len(batch)
            batch, batch_bytes = [], 0

        for key, offset, size in list(v.nm.ascending_visit()):
            total = get_actual_size(size, version)
            self._pay(total)
            try:
                blob = v._dat.read_at(total, offset)
                if len(blob) < total:
                    raise ValueError(f"short read {len(blob)} < {total}")
                data, stored = _light_parse(blob, size)
            except Exception as e:
                suspect(key, f"unreadable record: {str(e)[:80]}")
                continue
            batch.append((key, data, stored))
            batch_bytes += len(data)
            if batch_bytes >= self.batch_bytes:
                flush()
        flush()
        return findings

    # --- online-EC parity scrub -----------------------------------------------
    def _scrub_online_parity(self, v, w) -> list[ScrubFinding]:
        """Recompute-and-compare sampled stripe rows of a LIVE online-EC
        volume (OnlineEcWriter.scrub_sample holds the writer lock while
        it reads/encodes, so the token bucket is paid AFTER the call —
        the debt carries into the next wait, and a sleep never stalls
        the append path under the writer lock)."""
        t0 = time.perf_counter()
        checked, mismatches = w.scrub_sample(
            max_rows=self.sample_rows, sample_bytes=self.sample_bytes,
        )
        if checked:
            self._observe("gf", checked, time.perf_counter() - t0)
            self._pay(checked)
            self.stats["stripes_checked"] += self.sample_rows
        return [
            ScrubFinding(
                "parity_mismatch", v.id, node=self.node_id,
                collection=v.collection,
                detail=f"stripe row {row}: recomputed parity disagrees",
            )
            for row in mismatches
        ]

    # --- sealed EC scrub --------------------------------------------------------
    def _scrub_sealed_ec(self, ev) -> list[ScrubFinding]:
        """Length-check every local shard; when ALL 14 are local (the
        encode-in-place window, before spread), recompute-and-compare a
        sampled column per stripe and LOCATE the corrupt shard via the
        code's own redundancy. With a partial local set the deep check
        is skipped — parity spans nodes there, and scrub never moves
        shard data over the wire (the repair machinery does)."""
        from seaweedfs_tpu.storage.erasure_coding.geometry import (
            DATA_SHARDS_COUNT,
            TOTAL_SHARDS_COUNT,
            to_ext,
        )

        findings: list[ScrubFinding] = []
        shard_size = ev.shard_size
        local: dict[int, int] = dict(ev.shards)
        for sid, fd in sorted(local.items()):
            try:
                size = os.fstat(fd).st_size
            except OSError:
                size = -1
            if size < shard_size:
                findings.append(ScrubFinding(
                    "corrupt_shard", ev.volume_id, node=self.node_id,
                    collection=ev.collection, shard=sid,
                    detail=f"shard file {size} < {shard_size} bytes",
                ))
        if getattr(ev, "_closed", False):
            # an atomic remount swapped this instance out mid-scan and
            # closed its fds — everything read above is EBADF noise, not
            # damage; the replacement instance scans on the next pass
            return []
        if len(local) < TOTAL_SHARDS_COUNT or shard_size <= 0 or findings:
            return findings
        # sampled columns: a slice at the head, middle and tail of the
        # shard length — GF is byte-wise, so slices verify independently
        width = min(self.sample_bytes, shard_size)
        offsets = sorted({
            0, max(0, shard_size // 2 - width // 2), shard_size - width,
        })
        for off in offsets:
            self._pay(width * TOTAL_SHARDS_COUNT)
            cols: dict[int, np.ndarray] = {}
            for sid, fd in local.items():
                try:
                    data = os.pread(fd, width, off)
                except OSError:
                    data = b""  # remount race (closed fd) or real loss:
                    # both resolve below (closed-check / short finding)
                if len(data) != width:
                    if getattr(ev, "_closed", False):
                        return []  # swapped out mid-scan: EBADF noise
                    findings.append(ScrubFinding(
                        "corrupt_shard", ev.volume_id, node=self.node_id,
                        collection=ev.collection, shard=sid,
                        detail=f"short pread at {off}",
                    ))
                    return findings
                cols[sid] = np.frombuffer(data, dtype=np.uint8)
            t0 = time.perf_counter()
            suspect = self._verify_columns(cols, ev.codec,
                                           DATA_SHARDS_COUNT)
            self._observe(
                "gf", width * TOTAL_SHARDS_COUNT, time.perf_counter() - t0
            )
            self.stats["stripes_checked"] += 1
            if suspect is None:
                continue
            if suspect < 0:
                # full-width escalation failed to localize (multi-shard
                # damage): report without a shard — operators decide
                findings.append(ScrubFinding(
                    "corrupt_shard", ev.volume_id, node=self.node_id,
                    collection=ev.collection,
                    detail=f"parity mismatch at {off}, not localizable",
                ))
            else:
                findings.append(ScrubFinding(
                    "corrupt_shard", ev.volume_id, node=self.node_id,
                    collection=ev.collection, shard=suspect,
                    detail=f"located via parity recompute at {off}",
                ))
            return findings  # one located finding per volume per pass
        return findings

    @staticmethod
    def _verify_columns(cols: dict[int, np.ndarray], codec,
                        data_shards: int) -> int | None:
        """None = consistent; >= 0 = the located corrupt shard; -1 =
        inconsistent but not localizable (multi-shard damage)."""
        total = len(cols)

        def verifies(full: dict[int, np.ndarray]) -> bool:
            expect = codec.encode(
                np.stack([full[c] for c in range(data_shards)])
            )
            return all(
                np.array_equal(expect[p - data_shards], full[p])
                for p in range(data_shards, total)
            )

        if verifies(cols):
            return None
        for suspect in sorted(cols):
            present = {c: b for c, b in cols.items() if c != suspect}
            try:
                rec = codec.reconstruct(present, targets=[suspect])
            except Exception:
                continue
            cand = dict(cols)
            cand[suspect] = rec[suspect]
            if verifies(cand):
                return suspect
        return -1

    # --- tmp litter GC ----------------------------------------------------------
    def _gc_tmp_litter(self, directory: str) -> list[ScrubFinding]:
        """Sweep abandoned `.ecNN.tmp` files (aborted/replaced pipelined
        rebuilds, crashed seals). Age-gated AND excluded when an
        in-flight rebuild still owns the path — a live _ShardWriters must
        never lose its tmp under it."""
        findings: list[ScrubFinding] = []
        try:
            names = os.listdir(directory)
        except OSError:
            return findings
        active = {os.path.abspath(p) for p in self._active_tmp_paths()}
        now = time.time()
        for name in names:
            if not _TMP_RE.search(name):
                continue
            path = os.path.join(directory, name)
            if os.path.abspath(path) in active:
                continue
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age < self.tmp_max_age:
                continue
            try:
                os.unlink(path)
            except OSError:
                continue
            self.stats["tmp_removed"] += 1
            # reported (metric + journal) but auto-repaired in place —
            # never routed to the master (there is nothing left to heal)
            self._m_findings.labels("tmp_litter").inc()
            self._m_repairs.labels("tmp_litter").inc()
            from seaweedfs_tpu.stats import events as events_mod

            events_mod.emit("scrub_finding", node=self.node_id or None,
                            kind="tmp_litter", path=name,
                            age_s=round(age, 1))
        return findings

    def status(self) -> dict:
        return {
            "node": self.node_id,
            "rate_bytes_per_sec": self.bucket.rate,
            "stats": dict(self.stats),
            "unresolved": self.unresolved(),
        }


# --- master side: detector ----------------------------------------------------
def detect(master) -> list:
    """The `scrub` maintenance detector: fold each node's
    heartbeat-reported findings into per-volume repair tasks, and run
    the anti-entropy digest comparison across replica holders (pure
    metadata — no data moves until the executor repairs)."""
    from .detectors import _task

    by_vol: dict[int, list[dict]] = {}
    node_of: dict[int, str] = {}
    for node in master.topo.all_nodes():
        for fd in getattr(node, "scrub_findings", ()):
            kind = fd.get("kind")
            if kind not in SCRUB_FINDING_KINDS or kind == "tmp_litter":
                continue
            vid = int(fd.get("volume_id", 0))
            by_vol.setdefault(vid, []).append(dict(fd))
            node_of.setdefault(vid, fd.get("node") or node.id)

    # replica divergence off heartbeat digests: holders of one replicated
    # volume disagreeing means a replica silently missed a write or a
    # delete. Source = the digest-majority holder; ties break toward the
    # LARGEST reported size (append-only volumes grow on every operation
    # — writes and tombstones alike — so the longest replica has seen the
    # most history).
    holders: dict[int, list[tuple]] = {}
    online = master.topo.ec_online_volumes()
    for node in master.topo.all_nodes():
        for vid, info in node.volumes.items():
            digest = getattr(info, "needle_digest", "")
            if not digest or vid in online or info.ec_online:
                continue
            holders.setdefault(vid, []).append((node, info, digest))
    for vid, hs in sorted(holders.items()):
        if len(hs) < 2:
            continue
        digests = {d for _, _, d in hs}
        if len(digests) <= 1:
            continue
        counts: dict[str, int] = {}
        for _, _, d in hs:
            counts[d] = counts.get(d, 0) + 1
        # the empty-set digest can never win the majority: an append-only
        # replica with no history is simply BEHIND any populated peer,
        # however many empty holders agree (two fresh disk replacements
        # must not out-vote the one surviving replica — and tasking the
        # survivor to sync from an empty source is a heal scrub_sync
        # rightly refuses)
        candidates = {d: c for d, c in counts.items()
                      if d != EMPTY_NEEDLE_DIGEST}
        if not candidates:
            continue  # all empty -> they agree; unreachable past the
            # len(digests) check, kept as a guard
        majority = max(
            candidates.items(),
            key=lambda kv: (kv[1], max(
                info.size for _, info, d in hs if d == kv[0]
            )),
        )[0]
        source = max(
            (h for h in hs if h[2] == majority),
            key=lambda h: h[1].size,
        )[0]
        for node, info, d in hs:
            if d == majority:
                continue
            by_vol.setdefault(vid, []).append(ScrubFinding(
                "replica_divergence", vid, node=node.id,
                collection=info.collection, source_node=source.id,
                detail=f"digest {d} != majority {majority}",
            ).to_dict())
            node_of.setdefault(vid, node.id)

    tasks = []
    for vid, fs in sorted(by_vol.items()):
        kinds = sorted({f["kind"] for f in fs})
        tasks.append(_task(
            "scrub", volume_id=vid,
            collection=fs[0].get("collection", ""),
            node=node_of.get(vid, ""),
            reason=f"{len(fs)} scrub finding(s): {', '.join(kinds)}",
            params={"findings": fs},
        ))
    return tasks


# --- repair routing: plan/apply shared by the executor and volume.scrub ------
def plan_scrub_repairs(env, findings: list[dict]) -> list[dict]:
    """Route each finding to its heal. Shared between the maintenance
    `scrub` executor and the `volume.scrub -apply` verb, so humans and
    the daemon repair identically."""
    servers = env.servers()
    by_id = {sv.id: sv for sv in servers}
    actions: list[dict] = []
    for fd in findings:
        f = ScrubFinding.from_dict(fd) if isinstance(fd, dict) else fd
        holder = by_id.get(f.node)
        base = {"kind": f.kind, "volume": f.volume_id, "node": f.node,
                "collection": f.collection}
        if holder is None:
            actions.append({**base, "skip": "holder no longer in topology"})
            continue
        base["node_url"] = holder.http
        if f.kind == "corrupt_needle":
            others = [sv for sv in servers
                      if f.volume_id in sv.volumes and sv.id != f.node]
            actions.append({
                **base, "needle": f.needle,
                "source": others[0].id if others else None,
                "source_url": others[0].http if others else None,
                # every other holder is a candidate — apply walks them
                # in order and falls back to local EC reconstruction,
                # so one unreachable/rotten source doesn't fail the heal
                "sources": [{"id": sv.id, "url": sv.http}
                            for sv in others],
            })
        elif f.kind == "corrupt_shard":
            if f.shard is None:
                actions.append(
                    {**base, "skip": "corrupt shard not localized"})
            else:
                actions.append({**base, "shard": f.shard})
        elif f.kind == "parity_mismatch":
            actions.append(base)
        elif f.kind == "replica_divergence":
            src = by_id.get(f.source_node)
            if src is None:
                actions.append(
                    {**base, "skip": "majority holder gone"})
            else:
                actions.append({**base, "source": src.id,
                                "source_url": src.http})
        else:  # tmp_litter never reaches the master; belt and braces
            actions.append({**base, "skip": "locally repaired"})
    return actions


def describe_scrub_repairs(actions: list[dict]) -> list[str]:
    """Display lines — the ONE rendering the verb's dry-run output and
    /debug/maintenance history share."""
    out = []
    for a in actions:
        head = f"volume {a['volume']} [{a['kind']}] on {a['node']}"
        if a.get("skip"):
            out.append(f"{head}: SKIP ({a['skip']})")
        elif a["kind"] == "corrupt_needle":
            src = a.get("source")
            out.append(
                f"{head}: re-copy needle {a['needle']:x} from "
                + (src if src else "local EC reconstruction")
            )
        elif a["kind"] == "corrupt_shard":
            out.append(
                f"{head}: delete corrupt shard {a['shard']} ->"
                f" ec_rebuild re-derives it"
            )
        elif a["kind"] == "parity_mismatch":
            out.append(f"{head}: re-arm online striper, re-encode parity"
                       f" from the durable .dat")
        elif a["kind"] == "replica_divergence":
            out.append(f"{head}: re-sync needles from digest-majority"
                       f" holder {a['source']}")
    return out


def _resolve(env, action: dict) -> None:
    """Tell the holder's scrubber its finding was just repaired, so the
    heartbeat stops re-advertising it (the repair_needle/sync endpoints
    resolve server-side; the shard/parity heals go through generic admin
    endpoints that don't know about the scrubber). Best-effort: the next
    scheduled pass re-verifies regardless."""
    try:
        env.post(
            f"{action['node_url']}/admin/scrub/resolve",
            {"kind": action["kind"], "volume": action["volume"]},
            timeout=30,
        )
    except Exception:
        pass


def _repair_needle(env, a: dict) -> str:
    """Try every candidate source in order, then local EC
    reconstruction — one unreachable holder or a source whose own copy
    turns out rotten (scrub_needle verifies before serving) must not
    fail the heal while a clean copy exists elsewhere."""
    errors: list[str] = []
    for s in a.get("sources") or []:
        try:
            env.post(
                f"{a['node_url']}/admin/scrub/repair_needle",
                {"volume": a["volume"], "needle": a["needle"],
                 "source": s["url"]},
                timeout=120,
            )
            return (f"volume {a['volume']}: needle {a['needle']:x}"
                    f" re-written from {s['id']}")
        except Exception as e:
            errors.append(f"{s['id']}: {str(e)[:60]}")
    try:
        env.post(
            f"{a['node_url']}/admin/scrub/repair_needle",
            {"volume": a["volume"], "needle": a["needle"]},
            timeout=120,
        )
        return (f"volume {a['volume']}: needle {a['needle']:x}"
                f" re-written from local reconstruction")
    except Exception as e:
        errors.append(f"local reconstruction: {str(e)[:60]}")
    raise RuntimeError("; ".join(errors))


def apply_scrub_repairs(env, actions: list[dict]) -> list[str]:
    """Apply every routed repair, isolating failures per action — one
    unrepairable finding (no verified copy anywhere) must not abandon
    the rest of the batch. Raises only when NOTHING succeeded, so the
    scheduler's backoff dampens a wholly-stuck task while partial
    progress still completes (the unresolved findings re-advertise on
    the next heartbeat and re-queue on the next scan)."""
    _, _, _, m_repairs = ensure_metrics()
    applied: list[str] = []
    failures: list[str] = []
    for a in actions:
        if a.get("skip"):
            continue
        try:
            applied.append(_apply_one(env, a))
            m_repairs.labels(a["kind"]).inc()
        except Exception as e:
            failures.append(
                f"volume {a['volume']} [{a['kind']}] on {a['node']}:"
                f" FAILED ({str(e)[:140]})")
    if failures and not applied:
        raise RuntimeError("; ".join(failures))
    return applied + failures


def _apply_one(env, a: dict) -> str:
    kind = a["kind"]
    if kind == "corrupt_needle":
        return _repair_needle(env, a)
    elif kind == "corrupt_shard":
        # silent corruption becomes visible loss: the missing-shard
        # detector queues the (pipelined) ec_rebuild on the next scan
        env.post(
            f"{a['node_url']}/admin/ec/delete_shards",
            {"volume": a["volume"], "shards": [a["shard"]],
             "collection": a.get("collection", "")},
            timeout=60,
        )
        _resolve(env, a)
        return (f"volume {a['volume']}: corrupt shard {a['shard']} deleted"
                f" on {a['node']} (ec_rebuild will re-derive it)")
    elif kind == "parity_mismatch":
        out = env.post(
            f"{a['node_url']}/admin/ec/online/rebuild",
            {"volume": a["volume"]}, timeout=3600,
        )
        _resolve(env, a)
        return (f"volume {a['volume']}: parity re-encoded to watermark"
                f" {out.get('watermark')} on {a['node']}")
    elif kind == "replica_divergence":
        out = env.post(
            f"{a['node_url']}/admin/scrub/sync",
            {"volume": a["volume"], "source": a["source_url"]},
            timeout=3600,
        )
        return (f"volume {a['volume']}: re-synced from {a['source']}"
                f" (+{out.get('copied', 0)} needles,"
                f" -{out.get('deleted', 0)} stale)")
    raise RuntimeError(f"unroutable finding kind {kind!r}")
