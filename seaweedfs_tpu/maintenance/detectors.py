"""Maintenance detectors: periodic scans of the master's live topology
that emit typed RepairTasks — the "detect" leg of detect → plan → heal.

Each detector reads the same state PRs 2–4 taught the master to export
(`volume_layout.under_replicated()`, `topology.ec_missing_shards()`,
heartbeat ages, per-volume deleted-byte counters) and turns a fault into
a `RepairTask` the scheduler can dedup, prioritize and throttle. The
reference runs the same scans inside the master
(`topology_vacuum.go:216`, `command_volume_fix_replication.go`,
`command_ec_rebuild.go`) but as operator verbs; RapidRAID
(arXiv:1207.6744) and the online-EC study (arXiv:1709.05365) both show
scheduling — not codec speed — dominates degraded-mode tails, so
detection here only *emits* tasks; pacing lives in scheduler.py.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from seaweedfs_tpu.storage.erasure_coding import geometry


@dataclass(frozen=True)
class TaskSpec:
    """One registered maintenance task type. Names ride into the
    `task` label of every SeaweedFS_maintenance_* metric, so
    tools/check_metric_names.py lints them (unique snake_case)."""

    name: str
    priority: int  # default priority, lower = more urgent
    concurrency: int  # per-type in-flight cap
    description: str


# the registry: detectors/executors key on these names
TASK_TYPES: dict[str, TaskSpec] = {
    spec.name: spec
    for spec in (
        TaskSpec("fix_replication", 0, 2,
                 "copy a replica of an under-replicated volume"),
        TaskSpec("ec_rebuild", 1, 1,
                 "rebuild missing RS(10,4) shards on the Pallas path"),
        TaskSpec("evacuate", 2, 1,
                 "pre-copy replicas off a stale-heartbeat node"),
        TaskSpec("scrub", 2, 1,
                 "repair silent damage a scrub pass or digest"
                 " divergence proved (route per finding kind)"),
        TaskSpec("vacuum", 3, 1,
                 "compact a volume whose deleted-bytes crossed the"
                 " threshold"),
        TaskSpec("balance", 4, 1,
                 "even out volume counts across nodes"),
    )
}


@dataclass(frozen=True)
class RepairTask:
    """One unit of planned repair work. `key` is the dedup identity: the
    scheduler refuses a task whose key is already queued or in flight."""

    type: str
    volume_id: int | None = None
    collection: str = ""
    node: str = ""  # node id the repair primarily loads (per-node limits)
    priority: int = 10
    reason: str = ""
    params: dict = field(default_factory=dict, compare=False, hash=False)

    def __post_init__(self):
        if self.type not in TASK_TYPES:
            raise ValueError(f"unknown maintenance task type {self.type!r}")

    @property
    def key(self) -> tuple:
        # volume-scoped repairs dedup on the volume alone: the holder
        # node recorded for per-node limits follows topology iteration
        # order, and keying on it would let the SAME fault enqueue twice
        # when holders reorder between scans (double-replicating it).
        # Node-scoped tasks (evacuate, balance) dedup on the node.
        if self.volume_id is not None:
            return (self.type, self.volume_id)
        return (self.type, self.node)

    def to_dict(self) -> dict:
        return {
            "type": self.type, "volume_id": self.volume_id,
            "collection": self.collection, "node": self.node,
            "priority": self.priority, "reason": self.reason,
            "params": dict(self.params),
        }


def _task(type_: str, **kw) -> RepairTask:
    kw.setdefault("priority", TASK_TYPES[type_].priority)
    return RepairTask(type=type_, **kw)


def detect_under_replicated(master) -> list[RepairTask]:
    """volume_layout.under_replicated(), the source feeding the
    `SeaweedFS_master_volumes_underreplicated` gauge. Healthy online-EC
    volumes are parity-only BY DESIGN (the layout already excludes them;
    the explicit filter keeps a heartbeat-ordering race from queueing a
    copy of a volume whose redundancy is its parity shards — only a
    volume that FELL BACK to replication becomes a repair)."""
    online = master.topo.ec_online_volumes()
    tasks = []
    for coll, vid, have, want in master.topo.under_replicated_volumes():
        if vid in online:
            continue
        holders = master.topo.lookup(vid, coll)
        if not holders:
            continue  # nothing left to copy from
        tasks.append(_task(
            "fix_replication", volume_id=vid, collection=coll,
            node=holders[0].id,
            reason=f"{have}/{want} replicas",
            params={"have": have, "want": want},
        ))
    return tasks


def detect_ec_missing_shards(master) -> list[RepairTask]:
    """topology.ec_missing_shards(), the `SeaweedFS_master_ec_missing_shards`
    source. Only recoverable volumes (>= 10 shards survive) become tasks.
    Also scans LIVE online-EC volumes whose holder audits its parity as
    damaged (lost/torn shard vs the durable watermark): those were
    previously skipped as "healthy" because the layout treats
    holder+parity as fully replicated — the executor's online branch
    re-arms the striper and re-encodes from the .dat instead of waiting
    for seal + classic rebuild (the ROADMAP online-rebuild follow-up)."""
    total = geometry.TOTAL_SHARDS_COUNT
    data = geometry.DATA_SHARDS_COUNT
    tasks = []
    for node in master.topo.all_nodes():
        for vid, info in sorted(node.volumes.items()):
            if not info.ec_online or info.ec_online_parity_damaged <= 0:
                continue
            tasks.append(_task(
                "ec_rebuild", volume_id=vid, collection=info.collection,
                node=node.id,
                reason=(f"{info.ec_online_parity_damaged} damaged parity"
                        f" shard(s) on a live online-EC volume"),
                params={"online": True,
                        "damaged": info.ec_online_parity_damaged},
            ))
    for vid, missing in sorted(master.topo.ec_missing_shards().items()):
        present = total - missing
        if present < data:
            continue  # unrecoverable: rebuilding needs 10 of 14
        shard_map = master.topo.lookup_ec_shards(vid) or {}
        holders = sorted({n.id for nodes in shard_map.values() for n in nodes})
        if not holders:
            continue
        # the concrete missing shard ids: the scheduler's lazy-batching
        # fold widens a queued task's target set with these, so co-stripe
        # losses detected across scans coalesce into ONE chain pass
        present_ids = {
            int(s) for s, nodes in shard_map.items() if nodes
        }
        targets = sorted(set(range(total)) - present_ids)
        tasks.append(_task(
            "ec_rebuild", volume_id=vid,
            collection=master.topo.ec_collections.get(vid, ""),
            node=holders[0],
            reason=f"{missing} shard(s) without a live holder",
            params={"missing": missing, "present": present,
                    "targets": targets},
        ))
    return tasks


def detect_vacuum_candidates(master) -> list[RepairTask]:
    """Deleted-bytes share over the master's garbage threshold — the same
    scan the legacy auto-vacuum ran, now emitting schedulable tasks."""
    tasks = []
    seen: set[int] = set()
    threshold = master.garbage_threshold
    for node, vid, ratio in master.topo.vacuum_candidates(threshold):
        if vid in seen:  # one task per volume; the executor hits every holder
            continue
        seen.add(vid)
        tasks.append(_task(
            "vacuum", volume_id=vid, node=node.id,
            reason=f"garbage {ratio:.1%} > {threshold:.0%}",
            params={"garbage_ratio": round(ratio, 4)},
        ))
    return tasks


def detect_imbalance(master, slack: int = 2) -> list[RepairTask]:
    """Volume-count spread across nodes beyond `slack` emits one
    cluster-wide balance task (the executor plans the full move list)."""
    nodes = master.topo.all_nodes()
    if len(nodes) < 2:
        return []
    counts = {n.id: len(n.volumes) for n in nodes}
    lo, hi = min(counts.values()), max(counts.values())
    if hi - lo <= slack:
        return []
    busiest = max(counts, key=lambda k: counts[k])
    return [_task(
        "balance", node=busiest,
        reason=f"volume counts spread {lo}..{hi} (> {slack})",
        params={"min": lo, "max": hi},
    )]


def detect_stale_nodes(master) -> list[RepairTask]:
    """Nodes whose heartbeat is stale (3x pulse — the PR-4 heartbeat_stale
    alert threshold) but not yet expired (5x pulse) are evacuation
    candidates: pre-copy their replicas from surviving holders before the
    master forgets the node entirely."""
    now = time.time()
    stale_after = 3 * max(master.topo.pulse_seconds, 1)
    tasks = []
    for node in master.topo.all_nodes():
        age = now - node.last_seen
        if age <= stale_after:
            continue
        tasks.append(_task(
            "evacuate", node=node.id,
            reason=f"heartbeat {age:.1f}s stale",
            params={"age": round(age, 1)},
        ))
    return tasks


def detect_scrub_findings(master) -> list[RepairTask]:
    """Heartbeat-reported scrub findings + anti-entropy digest
    divergence -> scrub tasks (the integrity loop's detect leg; the
    scanning itself runs on the volume servers — see scrub.py)."""
    from . import scrub as scrub_mod

    return scrub_mod.detect(master)


# task type -> detector; the daemon iterates this to scan
DETECTORS = {
    "fix_replication": detect_under_replicated,
    "ec_rebuild": detect_ec_missing_shards,
    "scrub": detect_scrub_findings,
    "vacuum": detect_vacuum_candidates,
    "balance": detect_imbalance,
    "evacuate": detect_stale_nodes,
}


_warned_detectors: set[str] = set()


def scan(master, types=None) -> list[RepairTask]:
    """Run the selected detectors (all by default) against the master's
    live topology. A broken detector must not sink the scan, but a
    silently dead repair class is worse — the first failure per detector
    is logged (the alerts push-loop convention)."""
    from seaweedfs_tpu.util import glog

    tasks: list[RepairTask] = []
    for name, fn in DETECTORS.items():
        if types is not None and name not in types:
            continue
        try:
            tasks.extend(fn(master))
            _warned_detectors.discard(name)
        except Exception as e:
            if name not in _warned_detectors:
                _warned_detectors.add(name)
                glog.warning(
                    "maintenance detector %s failing (repair class idle"
                    " until it recovers): %s", name, e,
                )
    return tasks
