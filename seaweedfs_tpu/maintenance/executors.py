"""Maintenance executors: the "heal" leg of detect → plan → heal.

Each executor drives the SAME plan/apply helpers the admin-shell repair
verbs use (`volume.fix.replication`, `ec.rebuild`, `volume.vacuum`,
`volume.balance` — shell/commands_volume.py + commands_ec.py), so humans
and the daemon repair through one code path and one -dryRun/-apply
convention. An executor returns {"planned": [...]} in dry-run mode and
{"planned": [...], "applied": [...]} after a real repair; raising marks
the task failed (the scheduler arms backoff).
"""

from __future__ import annotations

from seaweedfs_tpu.shell.commands_ec import run_rebuild
from seaweedfs_tpu.shell.commands_volume import (
    apply_balance,
    apply_fix_replication,
    apply_vacuum,
    describe_balance,
    describe_fix_replication,
    describe_vacuum,
    plan_balance,
    plan_fix_replication,
    plan_vacuum,
)

from .detectors import RepairTask


def _exec_fix_replication(task: RepairTask, env, dry_run: bool) -> dict:
    actions = plan_fix_replication(env, task.volume_id)
    planned = describe_fix_replication(actions)
    if dry_run:
        return {"planned": planned}
    if actions and all(a.get("target") is None for a in actions):
        raise RuntimeError(
            f"volume {task.volume_id}: no candidate server for a new replica"
        )
    return {"planned": planned,
            "applied": apply_fix_replication(env, actions)}


def _exec_ec_rebuild(task: RepairTask, env, dry_run: bool,
                     scheduler=None, rebuild_mode: str = "auto") -> dict:
    """Rebuild missing shards, choosing pipelined partial-sum chains vs
    classic whole-shard pulls per task: an explicit task/daemon mode
    wins, else `auto` decides from the surviving-holder count and the
    scheduler's live pressure (token bucket + in-flight caps). The whole
    choose + apply + typed-fallback path is run_rebuild — shared with
    the ec.rebuild verb so both entry points repair identically and
    feed the same fallbacks/restarts metric series."""
    if task.params.get("online"):
        return _exec_ec_rebuild_online(task, env, dry_run)
    pressure = None
    if scheduler is not None:
        pressure = scheduler.pressure()
        # discount THIS task: the scheduler already counted it in flight
        # (and against its node's limit) when it dispatched us, so the
        # raw reading would report a busy node/cluster even when this
        # repair is the only thing running — making the 2-hop
        # idle-cluster -> classic branch unreachable
        pressure["in_flight"] = max(0, pressure["in_flight"] - 1)
        if task.node and pressure["node_inflight"].get(task.node, 0) > 0:
            pressure["node_inflight"][task.node] -= 1
    mode = task.params.get("mode") or rebuild_mode or "auto"
    out = run_rebuild(
        env, task.volume_id, task.collection, mode=mode,
        pressure=pressure, dry_run=dry_run,
    )
    if out.get("healed"):  # healed between detection and dispatch
        return {"planned": out["planned"], "applied": []}
    if out.get("dry_run"):
        return {"planned": out["planned"]}
    stats = out.get("stats")
    if stats is not None:
        applied = (
            f"rebuilt shards {out['rebuilt']} on {out['rebuilder']}"
            f" (pipelined, {stats['hops']} hops,"
            f" {stats['bytes_on_wire_rebuilder']} B at rebuilder,"
            f" {stats['restarts']} chain restart(s))"
        )
    else:
        applied = (
            f"rebuilt shards {out['rebuilt']} on {out['rebuilder']}"
            f" (classic)"
        )
    return {"planned": out["planned"], "applied": [applied]}


def _exec_ec_rebuild_online(task: RepairTask, env, dry_run: bool) -> dict:
    """A LIVE online-EC volume lost/tore a parity shard: the holder
    re-arms its striper and re-encodes from the durable .dat
    (/admin/ec/online/rebuild) — no shard pulls, the .dat IS the source."""
    vid = task.volume_id
    holder = next(
        (sv for sv in env.servers() if vid in sv.volumes), None
    )
    if holder is None:  # holder gone entirely: classic repair owns it now
        return {"planned": [], "applied": []}
    planned = [
        f"volume {vid}: re-arm online striper on {holder.id},"
        f" re-encode parity from the durable .dat"
    ]
    if dry_run:
        return {"planned": planned}
    out = env.post(
        f"{holder.http}/admin/ec/online/rebuild", {"volume": vid},
        timeout=3600,
    )
    return {"planned": planned,
            "applied": [f"volume {vid}: parity re-encoded to watermark"
                        f" {out.get('watermark')} on {holder.id}"]}


def _exec_scrub(task: RepairTask, env, dry_run: bool) -> dict:
    """Route a volume's scrub findings to their heals through the shared
    plan/apply helpers (scrub.py): corrupt needle -> re-copy from a
    verified-good holder, corrupt shard -> delete (the missing-shard
    detector's ec_rebuild re-derives it, pipelined per PR 11), online
    parity mismatch -> striper re-arm, replica divergence -> needle-level
    re-sync from the digest-majority holder."""
    from . import scrub as scrub_mod

    actions = scrub_mod.plan_scrub_repairs(
        env, task.params.get("findings", [])
    )
    planned = scrub_mod.describe_scrub_repairs(actions)
    if dry_run:
        return {"planned": planned}
    return {"planned": planned,
            "applied": scrub_mod.apply_scrub_repairs(env, actions)}


def _exec_vacuum(task: RepairTask, env, dry_run: bool) -> dict:
    actions = plan_vacuum(env, volume_id=task.volume_id)
    planned = describe_vacuum(actions)
    if dry_run:
        return {"planned": planned}
    return {"planned": planned, "applied": apply_vacuum(env, actions)}


def _exec_balance(task: RepairTask, env, dry_run: bool) -> dict:
    actions = plan_balance(env)
    planned = describe_balance(actions)
    if dry_run:
        return {"planned": planned}
    return {"planned": planned, "applied": apply_balance(env, actions)}


def _plan_evacuate(env, node_id: str) -> list[dict]:
    """Copy actions moving the stale node's replicas onto healthy nodes,
    sourcing from SURVIVING holders (the stale node is presumed
    unreachable — `command_volume_server_evacuate.go`, degraded variant).
    Volumes with no other holder are reported, not silently skipped.

    EC shards get a pre-copy plan too (the PR-5 gap): a shard has no
    second holder to source from, so the plan pulls from the DRAINING
    node itself — stale-heartbeat nodes are often alive-but-slow, and a
    successful pull beats waiting for expiry + a full ec_rebuild. If the
    node is truly dead the copy fails, the task backs off, and the
    missing-shard detector takes over after expiry."""
    servers = env.servers()
    stale = next((sv for sv in servers if sv.id == node_id), None)
    if stale is None:
        return []  # already expired: fix_replication owns it now
    healthy = [sv for sv in servers if sv.id != node_id]
    actions = []
    for vid in sorted(stale.volumes):
        others = [sv for sv in healthy if vid in sv.volumes]
        if others:
            src = others[0]
        elif stale.volumes[vid].get("ec_online"):
            # a LIVE online-EC volume is single-holder BY DESIGN (its
            # redundancy is the streamed parity, which cannot be copied
            # usefully) — pull the .dat/.idx/.vif from the draining node
            # itself, exactly like the EC-shard pre-copy below: stale
            # nodes are often alive-but-slow, and the receiver's
            # /admin/volume/copy re-arms the striper + re-encodes parity
            # from byte 0 on arrival. A truly dead source fails the
            # copy into backoff; nothing is lost by trying.
            src = stale
        else:
            actions.append({"volume": vid, "source": None, "target": None})
            continue
        ranked = sorted(
            (sv for sv in healthy
             if vid not in sv.volumes and sv.free_slots() > 0),
            key=lambda sv: -sv.free_slots(),
        )
        if not ranked:
            actions.append({"volume": vid, "source": src.id,
                            "target": None})
            continue
        dst = ranked[0]
        actions.append({"volume": vid, "source": src.id,
                        "source_url": src.http,
                        "target": dst.id, "target_url": dst.http})
        dst.volumes[vid] = stale.volumes[vid]  # keep the local view fresh
    for vid in sorted(stale.ec_shards):
        shards = sorted(stale.ec_shards[vid])
        # shards another node ALREADY holds need no copy (balance moves
        # in flight); only this node's unique shards are at risk
        elsewhere = {
            s for sv in healthy for s in sv.ec_shards.get(vid, [])
        }
        at_risk = [s for s in shards if s not in elsewhere]
        if not at_risk:
            continue
        # ANTI-AFFINITY: spread the at-risk shards across targets —
        # piling 5 shards of one volume onto a single node would turn
        # the NEXT single-node loss into >4 missing shards (RS(10,4)
        # unrecoverable). Per shard, prefer the node holding the fewest
        # of this volume's shards, then the most free slots.
        per_target: dict[str, list[int]] = {}
        for s in at_risk:
            ranked = sorted(
                (sv for sv in healthy if sv.free_slots() > 0),
                key=lambda sv: (len(sv.ec_shards.get(vid, [])),
                                -sv.free_slots()),
            )
            if not ranked:
                actions.append({"ec_volume": vid, "shards": [s],
                                "source": stale.id, "target": None})
                continue
            dst = ranked[0]
            per_target.setdefault(dst.id, []).append(s)
            dst.ec_shards.setdefault(vid, []).append(s)
        for dst_id, batch in per_target.items():
            dst = next(sv for sv in healthy if sv.id == dst_id)
            actions.append({
                "ec_volume": vid, "shards": batch,
                "collection": stale.ec_collections.get(vid, ""),
                "source": stale.id, "source_url": stale.http,
                "target": dst.id, "target_url": dst.http,
            })
    return actions


def _exec_evacuate(task: RepairTask, env, dry_run: bool) -> dict:
    actions = _plan_evacuate(env, task.node)
    planned = []
    for a in actions:
        if a.get("ec_volume") is not None:
            if a.get("target") is None:
                planned.append(
                    f"ec volume {a['ec_volume']} shards {a['shards']}:"
                    f" no candidate target"
                )
            else:
                planned.append(
                    f"ec volume {a['ec_volume']}: copy shards"
                    f" {a['shards']} {a['source']} -> {a['target']}"
                )
        elif a.get("target") is None:
            planned.append(
                f"volume {a['volume']}: "
                + ("no surviving replica to copy from"
                   if a.get("source") is None else "no candidate target")
            )
        else:
            planned.append(
                f"volume {a['volume']}: copy {a['source']} -> {a['target']}"
            )
    if dry_run:
        return {"planned": planned}
    applied = []
    for a in actions:
        if a.get("target") is None or a.get("source") is None:
            continue
        # explicit deadline budgets (the bare-call-site audit): shard and
        # volume pulls can be multi-GB (the receiver's ranged GETs retry
        # under the unified RetryPolicy), mounts are quick metadata ops
        if a.get("ec_volume") is not None:
            vid = a["ec_volume"]
            env.post(
                f"{a['target_url']}/admin/ec/copy",
                {"volume": vid, "collection": a.get("collection", ""),
                 "shards": a["shards"], "source": a["source_url"]},
                timeout=3600,
            )
            env.post(
                f"{a['target_url']}/admin/ec/mount",
                {"volume": vid, "collection": a.get("collection", "")},
                timeout=60,
            )
            applied.append(
                f"ec volume {vid}: copied shards {a['shards']}"
                f" {a['source']} -> {a['target']}"
            )
            continue
        env.post(
            f"{a['target_url']}/admin/volume/copy",
            {"volume": a["volume"], "source": a["source_url"]},
            timeout=3600,
        )
        applied.append(
            f"volume {a['volume']}: copied {a['source']} -> {a['target']}"
        )
    return {"planned": planned, "applied": applied}


EXECUTORS = {
    "fix_replication": _exec_fix_replication,
    "ec_rebuild": _exec_ec_rebuild,
    "scrub": _exec_scrub,
    "vacuum": _exec_vacuum,
    "balance": _exec_balance,
    "evacuate": _exec_evacuate,
}


def execute(task: RepairTask, env, dry_run: bool = False,
            scheduler=None, rebuild_mode: str = "auto") -> dict:
    """Run one task's executor; every repair is traced as a
    `maintenance.<type>` span so /debug/traces and cluster.trace show
    healing next to the foreground traffic it must not starve.
    `scheduler`/`rebuild_mode` feed the ec_rebuild mode choice (live
    dispatch pressure + the daemon's configured default)."""
    from seaweedfs_tpu.stats import trace

    fn = EXECUTORS[task.type]
    kwargs = {}
    if task.type == "ec_rebuild":
        kwargs = {"scheduler": scheduler, "rebuild_mode": rebuild_mode}
    with trace.span(
        f"maintenance.{task.type}", role="master",
        volume=task.volume_id, node=task.node, dry_run=dry_run,
    ):
        return fn(task, env, dry_run, **kwargs)
