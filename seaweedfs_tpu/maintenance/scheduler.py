"""Repair scheduler: the "plan" leg of detect → plan → heal.

A bounded priority queue with the pacing machinery arXiv:1207.6744
(RapidRAID) and arXiv:1709.05365 argue matters more than codec speed:

  * per-task-type concurrency caps (TASK_TYPES[..].concurrency) — one
    runaway class of repair cannot monopolize the workers;
  * per-node in-flight limits — a node already copying a replica is not
    also handed an EC rebuild (degraded reads on that node would pay).
    The limit binds the task's PRIMARY node (the source holder /
    rebuilder / vacuum holder recorded in its key); copy TARGETS are
    picked at plan time inside the executor and are not reserved here,
    so two concurrent repairs may still land copies on one target;
  * dedup by task key — a fault detected on every scan enqueues once;
  * exponential backoff with jitter on failed repairs — a node that
    refuses a copy is retried at 2s, 4s, 8s... (+-50% jitter so a
    thundering herd of failed tasks does not re-arrive in lockstep);
  * a global token-bucket repair throttle (repair_rate/s, burst) — the
    aggregate healing rate is bounded so foreground traffic never
    starves behind a repair storm.

Everything takes an optional `now` so tests drive time deterministically.
"""

from __future__ import annotations

import heapq
import random
import threading
import time

from seaweedfs_tpu.stats import events as events_mod

from .detectors import TASK_TYPES, RepairTask

# lazy-batching window (PR-11 follow-up: amortize co-stripe losses): task
# types whose single-target tasks may be briefly deferred so a second
# lost shard of the SAME stripe folds into one multi-target chain pass.
LAZY_TYPES = ("ec_rebuild",)
# outcome label of SeaweedFS_maintenance_lazy_batch_total (linted):
#   deferred — a dispatch-eligible task held back inside its window
#   folded   — an offer widened a queued task's target set (the payoff)
#   batched  — a multi-target task dispatched (one pass, all targets)
#   bypassed — an urgent (alert/operator-driven) task skipped the window
#   expired  — a single-target task waited out the full window alone
LAZY_OUTCOMES = ("deferred", "folded", "batched", "bypassed", "expired")

_lazy_counter_cache = None


def lazy_batch_counter():
    """Idempotently register the lazy-batching counter family."""
    global _lazy_counter_cache
    if _lazy_counter_cache is None:
        from seaweedfs_tpu.stats import default_registry

        _lazy_counter_cache = default_registry().counter(
            "SeaweedFS_maintenance_lazy_batch_total",
            "lazy-batching window decisions for amortizable repairs",
            ("outcome",),
        )
    return _lazy_counter_cache


def task_key_str(task: RepairTask) -> str:
    """The flight recorder's `task` correlation key: the scheduler's
    dedup identity, flattened ("ec_rebuild:7", "evacuate:127.0.0.1:81")
    so cluster.why can follow one repair queued→dispatched→done."""
    return ":".join(str(p) for p in task.key)


def _coll_attr(task: RepairTask) -> dict:
    """The `collection` correlation key for task lifecycle events, so
    `cluster.why <collection>` can assemble a per-tenant repair timeline.
    Volume-scoped tasks in the unnamed collection report "default";
    node-scoped tasks (no volume) carry no collection at all — claiming
    the default tenant for an evacuate would lie."""
    if task.volume_id is None:
        return {}
    return {"collection": task.collection or "default"}


class RepairScheduler:
    def __init__(
        self,
        max_queue: int = 256,
        per_node_limit: int = 1,
        global_limit: int = 4,
        type_caps: dict[str, int] | None = None,
        repair_rate: float = 2.0,
        repair_burst: float = 4.0,
        backoff_base: float = 2.0,
        backoff_max: float = 120.0,
        rng: random.Random | None = None,
        lazy_window: float = 0.0,
    ) -> None:
        self.max_queue = max_queue
        self.per_node_limit = per_node_limit
        self.global_limit = global_limit
        self.type_caps = {
            name: spec.concurrency for name, spec in TASK_TYPES.items()
        }
        if type_caps:
            self.type_caps.update(type_caps)
        self.repair_rate = repair_rate
        self.repair_burst = repair_burst
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._heap: list[tuple[int, int, RepairTask]] = []
        self._seq = 0
        self._queued: dict[tuple, RepairTask] = {}
        self._in_flight: dict[tuple, RepairTask] = {}
        self._node_inflight: dict[str, int] = {}
        self._type_inflight: dict[str, int] = {}
        # key -> {"failures": n, "not_before": ts}
        self._backoff: dict[tuple, dict] = {}
        self._tokens = repair_burst
        self._tokens_ts: float | None = None
        # lazy-batching window: 0.0 = dispatch immediately (the pre-PR-15
        # behavior). Positive: single-target LAZY_TYPES tasks sit queued
        # up to this many seconds so a co-stripe loss detected by a later
        # scan folds into one multi-target chain pass. Urgent offers
        # (alert-driven scans — degraded reads are paying for the missing
        # shard RIGHT NOW — and operator -now scans) bypass the window.
        self.lazy_window = float(lazy_window)
        self._queued_at: dict[tuple, float] = {}
        self._urgent: set[tuple] = set()
        self._lazy_deferred: set[tuple] = set()  # count "deferred" once
        self.stats = {
            "offered": 0, "deduped": 0, "backed_off": 0, "queue_full": 0,
            "dispatched": 0, "completed": 0, "failed": 0, "folded": 0,
            "max_node_inflight": 0, "max_inflight": 0,
        }

    # --- intake ---------------------------------------------------------------
    def offer(self, task: RepairTask, now: float | None = None,
              urgent: bool = False) -> bool:
        """Admit a detected task. False when it is already queued/in
        flight, still backing off from a failure, or the queue is full.

        The dedup key is effectively widened to the TARGET SET for lazy
        types: re-offering a queued ec_rebuild whose `targets` grew (a
        second shard of the same stripe died inside the lazy window)
        FOLDS the queued task — its target set widens in place and one
        multi-target chain pass repairs everything — instead of being
        dropped as a duplicate. `urgent` (alert-driven or operator -now
        scans) lifts the lazy hold on a new or already-queued task."""
        now = time.time() if now is None else now
        folded = False
        with self._lock:
            self.stats["offered"] += 1
            key = task.key
            if key in self._queued or key in self._in_flight:
                queued = self._queued.get(key)
                if queued is not None and task.type in LAZY_TYPES:
                    new_t = set(task.params.get("targets") or ())
                    old_t = set(queued.params.get("targets") or ())
                    if new_t - old_t:
                        merged = sorted(old_t | new_t)
                        params = dict(queued.params)
                        params["targets"] = merged
                        params["missing"] = len(merged)
                        wider = RepairTask(
                            type=queued.type, volume_id=queued.volume_id,
                            collection=queued.collection, node=queued.node,
                            priority=queued.priority,
                            reason=f"{len(merged)} shard(s) without a"
                                   f" live holder (folded)",
                            params=params,
                        )
                        self._queued[key] = wider
                        self._seq += 1
                        heapq.heappush(
                            self._heap,
                            (wider.priority, self._seq, wider))
                        self.stats["folded"] += 1
                        folded = True
                if queued is not None and urgent:
                    self._urgent.add(key)
                if not folded:
                    self.stats["deduped"] += 1
                    return False
            else:
                bo = self._backoff.get(key)
                if bo is not None and bo["not_before"] > now:
                    self.stats["backed_off"] += 1
                    return False
                if len(self._queued) >= self.max_queue:
                    self.stats["queue_full"] += 1
                    return False
                self._seq += 1
                heapq.heappush(self._heap, (task.priority, self._seq, task))
                self._queued[key] = task
                self._queued_at[key] = now
                if urgent:
                    self._urgent.add(key)
        if folded:
            lazy_batch_counter().labels("folded").inc()
            events_mod.emit("task_queued", task=task_key_str(task),
                            volume=task.volume_id, node=task.node,
                            type=task.type, reason="folded into queued task",
                            **_coll_attr(task))
            return True
        events_mod.emit("task_queued", task=task_key_str(task),
                        volume=task.volume_id, node=task.node,
                        type=task.type, reason=task.reason,
                        **_coll_attr(task))
        return True

    # --- dispatch -------------------------------------------------------------
    def _refill(self, now: float) -> None:
        if self._tokens_ts is None:
            self._tokens_ts = now
        self._tokens = min(
            self.repair_burst,
            self._tokens + (now - self._tokens_ts) * self.repair_rate,
        )
        self._tokens_ts = now

    def next_task(self, now: float | None = None) -> RepairTask | None:
        """Pop the most urgent runnable task, honoring every cap. Tasks
        blocked by a cap stay queued for the next call."""
        now = time.time() if now is None else now
        lazy_outcome = None
        with self._lock:
            self._refill(now)
            if self._tokens < 1.0:
                return None
            if len(self._in_flight) >= self.global_limit:
                return None
            deferred = []
            picked = None
            while self._heap:
                prio, seq, task = heapq.heappop(self._heap)
                # the queued map is authoritative: a fold may have widened
                # the task since this heap entry was pushed (stale narrow
                # entries are skipped once the key leaves the map)
                cur = self._queued.get(task.key)
                if cur is None:  # stale heap entry
                    continue
                task = cur
                if (
                    self._type_inflight.get(task.type, 0)
                    >= self.type_caps.get(task.type, 1)
                    or (task.node and self._node_inflight.get(task.node, 0)
                        >= self.per_node_limit)
                ):
                    deferred.append((prio, seq, task))
                    continue
                outcome = self._lazy_gate(task, now)
                if outcome == "deferred":
                    deferred.append((prio, seq, task))
                    continue
                picked = task
                lazy_outcome = outcome
                break
            for entry in deferred:
                heapq.heappush(self._heap, entry)
            if picked is None:
                return None
            self._tokens -= 1.0
            del self._queued[picked.key]
            self._queued_at.pop(picked.key, None)
            self._urgent.discard(picked.key)
            self._lazy_deferred.discard(picked.key)
            self._in_flight[picked.key] = picked
            self._type_inflight[picked.type] = (
                self._type_inflight.get(picked.type, 0) + 1
            )
            if picked.node:
                n = self._node_inflight.get(picked.node, 0) + 1
                self._node_inflight[picked.node] = n
                self.stats["max_node_inflight"] = max(
                    self.stats["max_node_inflight"], n
                )
            self.stats["dispatched"] += 1
            self.stats["max_inflight"] = max(
                self.stats["max_inflight"], len(self._in_flight)
            )
        if lazy_outcome is not None:
            lazy_batch_counter().labels(lazy_outcome).inc()
        events_mod.emit("task_dispatched", task=task_key_str(picked),
                        volume=picked.volume_id, node=picked.node,
                        type=picked.type, **_coll_attr(picked))
        return picked

    def _lazy_gate(self, task: RepairTask, now: float) -> str | None:
        """Lazy-batching decision for one dispatch-eligible task (caller
        holds the lock). Returns "deferred" to hold the task, a terminal
        LAZY_OUTCOMES value to dispatch-and-count, or None when the
        window does not apply (disabled, non-lazy type, online rebuild).
        The task is NEVER delayed past queued_at + lazy_window."""
        if self.lazy_window <= 0 or task.type not in LAZY_TYPES \
                or task.params.get("online"):
            return None
        key = task.key
        targets = task.params.get("targets") or ()
        if len(targets) >= 2 or task.params.get("missing", 0) >= 2:
            return "batched"  # already multi-target: one pass, go now
        if key in self._urgent:
            return "bypassed"  # degraded reads / operator: pressure wins
        queued_at = self._queued_at.get(key, now)
        if now - queued_at < self.lazy_window:
            if key not in self._lazy_deferred:
                self._lazy_deferred.add(key)
                lazy_batch_counter().labels("deferred").inc()
            return "deferred"
        return "expired"  # waited the full window alone: repair anyway

    def complete(
        self, task: RepairTask, ok: bool, now: float | None = None
    ) -> float:
        """Mark a dispatched task finished. On failure, arm exponential
        backoff with jitter and return the retry delay (0.0 on success)."""
        now = time.time() if now is None else now
        with self._lock:
            key = task.key
            self._in_flight.pop(key, None)
            t = self._type_inflight.get(task.type, 0)
            self._type_inflight[task.type] = max(0, t - 1)
            if task.node:
                n = self._node_inflight.get(task.node, 0)
                self._node_inflight[task.node] = max(0, n - 1)
            if ok:
                self.stats["completed"] += 1
                self._backoff.pop(key, None)
                return 0.0
            self.stats["failed"] += 1
            bo = self._backoff.setdefault(
                key, {"failures": 0, "not_before": 0.0}
            )
            bo["failures"] += 1
            delay = min(
                self.backoff_max,
                self.backoff_base * 2 ** (bo["failures"] - 1),
            ) * (0.5 + self._rng.random())  # +-50% jitter
            bo["not_before"] = now + delay
            failures = bo["failures"]
        events_mod.emit("task_backoff", task=task_key_str(task),
                        volume=task.volume_id, node=task.node,
                        type=task.type, retry_in=round(delay, 2),
                        failures=failures, **_coll_attr(task))
        return delay

    def next_lazy_deadline(self, now: float | None = None) -> float | None:
        """Seconds until the soonest lazy-held task's window expires, or
        None when nothing is held — the daemon shortens its wait so a
        task is never delayed past queued_at + lazy_window. Entries
        whose window ALREADY expired are excluded: they need no
        precision wakeup anymore (the next ordinary tick dispatches
        them), and returning 0.0 for a task some OTHER cap is blocking
        would spin the daemon at the 0.05s floor — a 20 Hz full-scan
        busy loop for as long as the cap holds."""
        if self.lazy_window <= 0:
            return None
        now = time.time() if now is None else now
        with self._lock:
            deadlines = [
                d for d in (
                    self._queued_at.get(k, now) + self.lazy_window - now
                    for k, t in self._queued.items()
                    if t.type in LAZY_TYPES and k not in self._urgent
                    and not t.params.get("online")
                    and len(t.params.get("targets") or ()) < 2
                ) if d > 0.0
            ]
        if not deadlines:
            return None
        return min(deadlines)

    # --- views ----------------------------------------------------------------
    def pressure(self, now: float | None = None) -> dict:
        """Live dispatch pressure for per-task policy decisions — the
        ec_rebuild executor picks pipelined vs classic partly off this
        (a drained token bucket / saturated in-flight caps mean repairs
        are contending, so spreading one rebuild's GF math and wire load
        across the chain beats concentrating it on one node)."""
        now = time.time() if now is None else now
        with self._lock:
            self._refill(now)
            return {
                "tokens": self._tokens,
                "in_flight": len(self._in_flight),
                "global_limit": self.global_limit,
                "per_node_limit": self.per_node_limit,
                "node_inflight": dict(self._node_inflight),
                "queued": len(self._queued),
                "lazy_window": self.lazy_window,
                "lazy_held": sum(
                    1 for k, t in self._queued.items()
                    if self.lazy_window > 0 and t.type in LAZY_TYPES
                    and k not in self._urgent
                    and not t.params.get("online")
                    and len(t.params.get("targets") or ()) < 2
                    and now - self._queued_at.get(k, now) < self.lazy_window
                ),
            }

    def queue_depths(self) -> dict[str, dict[str, int]]:
        """{task_type: {queued, in_flight}} for the metrics collector."""
        with self._lock:
            out: dict[str, dict[str, int]] = {}
            for t in self._queued.values():
                out.setdefault(t.type, {"queued": 0, "in_flight": 0})
                out[t.type]["queued"] += 1
            for t in self._in_flight.values():
                out.setdefault(t.type, {"queued": 0, "in_flight": 0})
                out[t.type]["in_flight"] += 1
            return out

    def _queued_dict(self, t: RepairTask, now: float) -> dict:
        """to_dict + the lazy-window view /debug/maintenance renders:
        how much longer this task may wait for co-stripe company."""
        d = t.to_dict()
        if self.lazy_window > 0 and t.type in LAZY_TYPES:
            held = (
                t.key not in self._urgent
                and not t.params.get("online")
                and len(t.params.get("targets") or ()) < 2
            )
            remaining = max(
                0.0,
                self._queued_at.get(t.key, now) + self.lazy_window - now,
            )
            d["lazy"] = {
                "held": bool(held and remaining > 0),
                "dispatch_in": round(remaining if held else 0.0, 2),
                "urgent": t.key in self._urgent,
            }
        return d

    def snapshot(self, now: float | None = None) -> dict:
        now = time.time() if now is None else now
        with self._lock:
            seen: set[tuple] = set()
            queued = []
            for _, _, t in sorted(self._heap):
                cur = self._queued.get(t.key)
                if cur is None or cur.key in seen:
                    continue  # stale (pre-fold) or duplicate heap entry
                seen.add(cur.key)
                queued.append(self._queued_dict(cur, now))
            return {
                "queued": queued,
                "in_flight": [t.to_dict() for t in self._in_flight.values()],
                "backoff": [
                    {"type": k[0], "target": k[1],
                     "failures": v["failures"],
                     "retry_in": max(0.0, round(v["not_before"] - now, 2))}
                    for k, v in self._backoff.items()
                ],
                "stats": dict(self.stats),
                "limits": {
                    "max_queue": self.max_queue,
                    "per_node_limit": self.per_node_limit,
                    "global_limit": self.global_limit,
                    "type_caps": dict(self.type_caps),
                    "repair_rate": self.repair_rate,
                    "repair_burst": self.repair_burst,
                    "lazy_window": self.lazy_window,
                },
            }
