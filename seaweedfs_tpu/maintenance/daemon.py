"""MaintenanceDaemon: the loop that closes detect → plan → heal.

Runs inside the master (leader-only) behind the `-maintenance` flag, off
by default. Every scan interval it runs the detectors over the live
topology, offers the resulting RepairTasks to the RepairScheduler, and
drains whatever the scheduler's caps/throttle admit onto a small worker
pool that executes repairs through the shared shell plan/apply helpers.
`-maintenance.dryRun` runs the identical pipeline but executors only
plan — zero mutations — so an operator can watch /debug/maintenance and
see exactly what the daemon *would* heal.

Besides polling, the daemon subscribes to the PR-4 AlertEngine's
`on_fire` hook: a rising disk_near_cap alert triggers an immediate
vacuum+balance scan, a rising heartbeat_stale alert an evacuate scan —
reaction, not just periodic discovery.

Every repair is traced (`maintenance.<type>` spans) and timed into
`SeaweedFS_maintenance_{tasks_total,task_seconds,queue_depth,
failures_total}` so cluster.check/cluster.top-style tooling sees healing
load next to the foreground traffic it is throttled to never starve.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from seaweedfs_tpu.stats import default_registry

from . import detectors as detectors_mod
from . import executors as executors_mod
from .detectors import TASK_TYPES, RepairTask
from .scheduler import RepairScheduler

# collector-backed family (scrape-time view of the scheduler's queues)
MAINTENANCE_FAMILIES = ("SeaweedFS_maintenance_queue_depth",)

# alert name -> detector subset to scan immediately on a rising edge.
# NOTE: the AlertEngine evaluates the DAEMON's process-local metrics
# history, so volume-server-side series (disk gauges, degraded-read
# counters) only drive these hooks in single-process deployments and
# test clusters; in a multi-process cluster the periodic detector scan
# (which reads heartbeat-fed topology state, not metrics) is the heal
# path and these hooks are an accelerator where visible.
ALERT_SCANS = {
    "disk_near_cap": ("vacuum", "balance"),
    "heartbeat_stale": ("evacuate",),
    # reads surviving only through reconstruction: something is lost or
    # torn RIGHT NOW — race the repair scan instead of waiting a tick
    "degraded_reads": ("ec_rebuild", "fix_replication"),
    # a scrub pass proved silent damage: route the findings immediately
    "scrub_findings": ("scrub",),
}


def ensure_metrics(registry=None):
    """Register (idempotently) the maintenance metric families on the
    process registry; returns (tasks_total, task_seconds, failures_total)."""
    reg = registry if registry is not None else default_registry()
    return (
        reg.counter(
            "SeaweedFS_maintenance_tasks_total",
            "maintenance tasks by terminal state"
            " (completed|failed|planned)",
            ("task", "state"),
        ),
        reg.histogram(
            "SeaweedFS_maintenance_task_seconds",
            "wall time per executed maintenance task",
            ("task",),
        ),
        reg.counter(
            "SeaweedFS_maintenance_failures_total",
            "failed maintenance task executions (each arms backoff)",
            ("task",),
        ),
    )


class MaintenanceDaemon:
    def __init__(
        self,
        master,
        interval: float | None = None,
        dry_run: bool = False,
        scheduler: RepairScheduler | None = None,
        history_size: int = 128,
        registry=None,
        rebuild_mode: str = "auto",
        lazy_window: float = 0.0,
    ) -> None:
        self.master = master
        self.interval = (
            interval if interval is not None
            else float(max(master.topo.pulse_seconds, 1))
        )
        self.dry_run = bool(dry_run)
        # ec_rebuild default mode: auto (per-task choice off holder count
        # + scheduler pressure) | pipelined | classic. Runtime-settable
        # via POST /maintenance/enable {"rebuildMode": ...}.
        self.rebuild_mode = rebuild_mode
        self.enabled = True
        # -repair.lazyWindow: single-shard ec_rebuild tasks may sit
        # queued up to this many seconds so co-stripe losses coalesce
        # into one multi-target chain pass (0 = dispatch immediately).
        # Runtime-settable via POST /maintenance/enable {"lazyWindow"}.
        self.scheduler = scheduler or RepairScheduler(
            lazy_window=lazy_window)
        self.registry = registry if registry is not None else default_registry()
        self._m_tasks, self._m_seconds, self._m_failures = ensure_metrics(
            self.registry
        )
        self._collector = None
        self._lock = threading.Lock()
        self._history: deque[dict] = deque(maxlen=history_size)
        self._counts: dict[tuple[str, str], int] = {}
        self._pending_types: set[str] = set()  # requested subset scans
        self._pending_full = False  # an explicit full-scan request
        self._wake = threading.Event()
        self._stopping = False
        self._thread: threading.Thread | None = None
        self._pool: ThreadPoolExecutor | None = None
        self._env = None
        self._lease_mutex = threading.Lock()
        self._lease_count = 0
        self._renew_thread: threading.Thread | None = None
        self._alert_engine = None
        self.scans = 0
        self.started_at: float | None = None

    # --- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self.started_at = time.time()
        self._collector = self.registry.register_collector(
            self._queue_depth_lines, names=MAINTENANCE_FAMILIES
        )
        try:  # react to firing alerts, not just the polling scan
            from seaweedfs_tpu.stats import alerts as alerts_mod

            self._alert_engine = alerts_mod.engine()
            self._alert_engine.add_on_fire(self._on_alert)
        except Exception:
            self._alert_engine = None
        self._pool = ThreadPoolExecutor(
            max_workers=self.scheduler.global_limit,
            thread_name_prefix="sw-maint",
        )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sw-maint-scan"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopping = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self._alert_engine is not None:
            self._alert_engine.remove_on_fire(self._on_alert)
            self._alert_engine = None
        if self._collector is not None:
            self.registry.unregister_collector(self._collector)
            self._collector = None

    def _command_env(self):
        if self._env is None:
            from seaweedfs_tpu.shell.env import CommandEnv

            self._env = CommandEnv(self.master.url, holder="maintenance")
        return self._env

    def _acquire_lease(self, env) -> None:
        """Refcounted admin lease shared by the worker pool: every worker
        uses the one 'maintenance' holder (the master's lock is
        re-entrant per holder), so the lease is taken when the first
        concurrent repair starts and dropped when the last one ends —
        an operator's `lock` cannot slip in between two daemon tasks.
        A renewal thread re-acquires every 10s while any repair runs: a
        single long rebuild must not outlive the lease's 30s ttl and
        silently lose the mutual exclusion mid-copy. Lease POSTs carry a
        short timeout: they run under _lease_mutex, and a hung 300s call
        here would freeze every worker's task start AND finish."""
        with self._lease_mutex:
            # re-acquire also refreshes the 30s ttl
            env.acquire_lock(timeout=10)
            self._lease_count += 1
            if self._renew_thread is None or not self._renew_thread.is_alive():
                self._renew_thread = threading.Thread(
                    target=self._renew_lease_loop, args=(env,),
                    daemon=True, name="sw-maint-lease",
                )
                self._renew_thread.start()

    def _release_lease(self, env) -> None:
        with self._lease_mutex:
            self._lease_count -= 1
            if self._lease_count <= 0:
                try:
                    env.release_lock(timeout=10)
                except Exception:
                    pass  # expired lease: nothing to release

    def _renew_lease_loop(self, env) -> None:
        while not self._stopping:
            time.sleep(10.0)  # well inside the 30s lease ttl
            with self._lease_mutex:
                if self._lease_count <= 0:
                    return
                try:
                    env.acquire_lock(timeout=10)
                except Exception:
                    pass  # lost race after expiry: next task 409s+backs off

    # --- scanning -------------------------------------------------------------
    def _on_alert(self, name: str, info: dict) -> None:
        types = ALERT_SCANS.get(name)
        if types is None:
            return
        self.request_scan(types)

    def request_scan(self, types=None) -> None:
        """Ask the loop for an immediate scan (subset or full)."""
        with self._lock:
            if types is None:
                self._pending_full = True
            else:
                self._pending_types.update(types)
        self._wake.set()

    def scan_now(self, types=None) -> list[dict]:
        """Synchronous scan + enqueue (the `cluster.maintenance -now` verb);
        returns what was offered. Dispatch still rides the loop/caps. An
        operator-forced scan is urgent: it bypasses the lazy window."""
        offered = self._scan_and_enqueue(types, urgent=True)
        self._wake.set()
        return [t.to_dict() for t in offered]

    def _scan_and_enqueue(self, types=None,
                          urgent: bool | None = None) -> list[RepairTask]:
        # subset scans are reactions (a firing alert — degraded reads are
        # paying for the fault right now — or an operator's -now): they
        # bypass the lazy-batching window; periodic full scans do not
        if urgent is None:
            urgent = types is not None
        self.scans += 1
        now = time.time()
        offered = []
        for task in detectors_mod.scan(self.master, types):
            if self.scheduler.offer(task, now, urgent=urgent):
                offered.append(task)
        return offered

    # --- the loop -------------------------------------------------------------
    def _loop(self) -> None:
        next_scan = 0.0  # monotonic deadline for the periodic full scan
        while True:
            timeout = self.interval
            # a lazy-held task must dispatch the moment its window
            # expires, not a full interval later: shorten the wait to
            # the soonest lazy deadline
            lazy_in = self.scheduler.next_lazy_deadline()
            if lazy_in is not None:
                timeout = max(0.05, min(timeout, lazy_in))
            woke = self._wake.wait(timeout=timeout)
            if self._stopping:
                return
            with self._lock:
                # a timeout tick — or an overdue scan deadline — is a full
                # scan; an explicit wake scans the requested subset, or
                # skips straight to dispatch when a completed task only
                # woke us to drain the queue. The deadline matters: while
                # a long backlog drains, completion wakes arrive faster
                # than the interval and would otherwise postpone detection
                # of NEW faults indefinitely.
                full = (
                    (not woke) or self._pending_full
                    or time.monotonic() >= next_scan
                )
                types = None if full else (set(self._pending_types) or None)
                dispatch_only = woke and not full and types is None
                self._pending_full = False
                self._pending_types.clear()
                self._wake.clear()
            if not self.enabled or not self.master._is_leader():
                next_scan = 0.0  # scan immediately on re-enable/election
                continue
            if not dispatch_only:
                try:
                    self._scan_and_enqueue(types)
                except Exception:
                    pass
                if full:
                    next_scan = time.monotonic() + self.interval
            self._dispatch()

    def _dispatch(self) -> None:
        while not self._stopping:
            task = self.scheduler.next_task()
            if task is None:
                return
            pool = self._pool
            if pool is None:
                self.scheduler.complete(task, ok=True)
                return
            pool.submit(self._run_task, task)

    def _run_task(self, task: RepairTask) -> None:
        started = time.time()
        state, detail, error = "completed", {}, None
        env = self._command_env()
        try:
            if not self.dry_run:
                # the same exclusive admin lease the shell's repair verbs
                # demand: while an operator holds `lock`, acquisition 409s,
                # the task fails into backoff and retries after the human
                # is done — never interleaving with a manual repair.
                # Dry-run only plans (read-only): no lease needed.
                self._acquire_lease(env)
            try:
                detail = executors_mod.execute(
                    task, env, dry_run=self.dry_run,
                    scheduler=self.scheduler,
                    rebuild_mode=self.rebuild_mode,
                )
            finally:
                if not self.dry_run:
                    self._release_lease(env)
            if self.dry_run:
                state = "planned"
        except Exception as e:
            state, error = "failed", str(e)[:300]
        duration = time.time() - started
        ok = state != "failed"
        retry_in = self.scheduler.complete(task, ok=ok)
        from seaweedfs_tpu.stats import events as events_mod
        from .scheduler import _coll_attr, task_key_str

        events_mod.emit(
            "task_done" if ok else "task_failed",
            task=task_key_str(task), volume=task.volume_id,
            node=task.node, type=task.type, state=state,
            duration_ms=round(duration * 1000.0, 2),
            **({"error": error} if error is not None else {}),
            **_coll_attr(task),
        )
        # a finished task frees a cap/throttle slot: wake the loop so the
        # next queued task dispatches now, not a full scan interval later
        if not self._stopping:
            self._wake.set()
        self._m_tasks.labels(task.type, state).inc()
        if state != "planned":  # planning costs nothing worth histogramming
            self._m_seconds.labels(task.type).observe(duration)
        if not ok:
            self._m_failures.labels(task.type).inc()
        entry = {
            "task": task.to_dict(), "state": state,
            "started": round(started, 3),
            "duration_ms": round(duration * 1000.0, 2),
        }
        if detail.get("planned") is not None:
            entry["planned"] = detail["planned"]
        if detail.get("applied") is not None:
            entry["applied"] = detail["applied"]
        if error is not None:
            entry["error"] = error
            entry["retry_in"] = round(retry_in, 2)
        with self._lock:
            self._history.append(entry)
            k = (task.type, state)
            self._counts[k] = self._counts.get(k, 0) + 1

    # --- views ----------------------------------------------------------------
    def _queue_depth_lines(self) -> list[str]:
        from seaweedfs_tpu.stats.metrics import _fmt_labels

        lines = ["# TYPE SeaweedFS_maintenance_queue_depth gauge"]
        depths = self.scheduler.queue_depths()
        for task_type in sorted(TASK_TYPES):
            d = depths.get(task_type, {"queued": 0, "in_flight": 0})
            for st in ("queued", "in_flight"):
                lines.append(
                    "SeaweedFS_maintenance_queue_depth"
                    + _fmt_labels(("task", "state"), (task_type, st))
                    + f" {d[st]}"
                )
        return lines

    def status(self, history_limit: int = 50) -> dict:
        with self._lock:
            history = list(self._history)[-history_limit:]
            counts: dict[str, dict[str, int]] = {}
            for (task_type, state), n in sorted(self._counts.items()):
                counts.setdefault(task_type, {})[state] = n
        return {
            "enabled": self.enabled,
            "dry_run": self.dry_run,
            "interval": self.interval,
            "scans": self.scans,
            "started_at": self.started_at,
            # the live dispatch view cluster.maintenance renders: why a
            # repair is running (or deferred) RIGHT NOW — token-bucket
            # level, in-flight vs caps, and the lazy-batching hold
            "pressure": self.scheduler.pressure(),
            "task_types": {
                name: {"priority": spec.priority,
                       "concurrency": spec.concurrency,
                       "description": spec.description}
                for name, spec in TASK_TYPES.items()
            },
            "scheduler": self.scheduler.snapshot(),
            "counts": counts,
            "history": history,
        }
