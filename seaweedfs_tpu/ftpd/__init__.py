"""FTP gateway over the filer.

The reference ships only an unwired ftpserverlib skeleton
(`weed/ftpd/ftp_server.go`, 81 LoC). This build wires a working minimal
FTP server (passive mode, binary type) straight onto the filer: USER/PASS
(accept-all or fixed credentials), PWD/CWD/CDUP, PASV, LIST/NLST, RETR,
STOR, DELE, MKD/RMD, SIZE, QUIT — enough for stock clients (tested with
stdlib ftplib).
"""

from __future__ import annotations

import socket
import socketserver
import threading
import time

from seaweedfs_tpu.filer.filer_client import FilerClient


class FtpServer:
    def __init__(self, filer_url: str, host: str = "127.0.0.1",
                 port: int = 2121, user: str = "", password: str = "",
                 anonymous: bool = False) -> None:
        """With no user/password configured the gateway REFUSES logins unless
        `anonymous=True` is passed explicitly — an unconfigured server must
        not silently expose the whole filer namespace read-write (advisor r1
        finding #5)."""
        self.filer_url = filer_url
        self.host = host
        self.user = user
        self.password = password
        self.anonymous = anonymous
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                outer._session(self)

        self._server = socketserver.ThreadingTCPServer(
            (host, port), Handler, bind_and_activate=False
        )
        self._server.allow_reuse_address = True
        self._server.daemon_threads = True
        self._server.server_bind()
        self._server.server_activate()
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # --- one control session ---------------------------------------------------
    def _session(self, h: socketserver.StreamRequestHandler) -> None:
        fc = FilerClient(self.filer_url)
        cwd = "/"
        authed_user = ""
        logged_in = False
        data_listener: socket.socket | None = None

        def send(line: str) -> None:
            h.wfile.write((line + "\r\n").encode())

        def resolve(arg: str) -> str:
            """Absolute/relative resolution with '.'/'..' canonicalization so
            no un-normalized dot segments ever reach the filer."""
            if not arg or arg == ".":
                return cwd
            if arg.startswith("/"):
                path = arg
            else:
                path = cwd.rstrip("/") + "/" + arg
            parts: list[str] = []
            for seg in path.split("/"):
                if seg in ("", "."):
                    continue
                if seg == "..":
                    if parts:
                        parts.pop()
                    continue
                parts.append(seg)
            return "/" + "/".join(parts) if parts else "/"

        def open_data() -> socket.socket | None:
            nonlocal data_listener
            if data_listener is None:
                return None
            conn, _ = data_listener.accept()
            data_listener.close()
            data_listener = None
            return conn

        send("220 seaweedfs-tpu FTP ready")
        while True:
            raw = h.rfile.readline()
            if not raw:
                break
            line = raw.decode("utf-8", "replace").strip()
            cmd, _, arg = line.partition(" ")
            cmd = cmd.upper()
            try:
                if cmd == "USER":
                    authed_user = arg
                    send("331 password please")
                elif cmd == "PASS":
                    if self.user:
                        ok = authed_user == self.user and arg == self.password
                    else:
                        ok = self.anonymous  # accept-all needs explicit opt-in
                    if ok:
                        logged_in = True
                        send("230 logged in")
                    else:
                        send("530 login incorrect")
                elif cmd in ("SYST",):
                    send("215 UNIX Type: L8")
                elif cmd == "FEAT":
                    send("211-Features:")
                    send(" SIZE")
                    send(" PASV")
                    send("211 End")
                elif cmd == "TYPE":
                    send("200 type set")
                elif cmd == "NOOP":
                    send("200 ok")
                elif not logged_in:
                    # every filesystem verb demands a completed login
                    send("530 please login with USER and PASS")
                elif cmd == "PWD":
                    send(f'257 "{cwd}"')
                elif cmd == "CWD":
                    target = resolve(arg)
                    e = fc.get_entry(target) if target != "/" else {
                        "is_directory": True}
                    if e and e.get("is_directory"):
                        cwd = target
                        send("250 cwd ok")
                    else:
                        send("550 no such directory")
                elif cmd == "CDUP":
                    cwd = cwd.rsplit("/", 1)[0] or "/"
                    send("250 cwd ok")
                elif cmd == "PASV":
                    if data_listener is not None:
                        data_listener.close()
                    data_listener = socket.socket()
                    data_listener.bind((self.host, 0))
                    data_listener.listen(1)
                    p = data_listener.getsockname()[1]
                    hbytes = self.host.split(".")
                    send(
                        "227 Entering Passive Mode "
                        f"({','.join(hbytes)},{p >> 8},{p & 255})"
                    )
                elif cmd in ("LIST", "NLST"):
                    conn = open_data()
                    if conn is None:
                        send("425 use PASV first")
                        continue
                    send("150 here comes the directory listing")
                    target = resolve(arg) if arg and not arg.startswith("-") \
                        else cwd
                    listing = fc.list(target, limit=10000)
                    lines = []
                    for e in listing.get("Entries") or []:
                        name = e["FullPath"].rsplit("/", 1)[-1]
                        if cmd == "NLST":
                            lines.append(name)
                            continue
                        kind = "d" if e["IsDirectory"] else "-"
                        size = e.get("FileSize", 0)
                        mtime = time.strftime(
                            "%b %d %H:%M", time.localtime(e.get("Mtime", 0))
                        )
                        lines.append(
                            f"{kind}rw-r--r-- 1 weed weed {size:>12} "
                            f"{mtime} {name}"
                        )
                    conn.sendall(("\r\n".join(lines) + "\r\n").encode())
                    conn.close()
                    send("226 directory send ok")
                elif cmd == "SIZE":
                    e = fc.get_entry(resolve(arg))
                    if e is None or e.get("is_directory"):
                        send("550 no such file")
                    else:
                        send(f"213 {(e.get('attributes') or {}).get('file_size', 0)}")
                elif cmd == "RETR":
                    conn = open_data()
                    if conn is None:
                        send("425 use PASV first")
                        continue
                    try:
                        data = fc.read(resolve(arg))
                    except OSError:
                        conn.close()
                        send("550 no such file")
                        continue
                    send("150 opening data connection")
                    conn.sendall(data)
                    conn.close()
                    send("226 transfer complete")
                elif cmd == "STOR":
                    conn = open_data()
                    if conn is None:
                        send("425 use PASV first")
                        continue
                    send("150 ok to send data")
                    buf = bytearray()
                    while True:
                        piece = conn.recv(1 << 16)
                        if not piece:
                            break
                        buf.extend(piece)
                    conn.close()
                    fc.put(resolve(arg), bytes(buf))
                    send("226 transfer complete")
                elif cmd == "DELE":
                    if fc.delete(resolve(arg)):
                        send("250 deleted")
                    else:
                        send("550 delete failed")
                elif cmd == "MKD":
                    fc.mkdir(resolve(arg))
                    send(f'257 "{resolve(arg)}" created')
                elif cmd == "RMD":
                    if fc.delete(resolve(arg), recursive=True):
                        send("250 removed")
                    else:
                        send("550 remove failed")
                elif cmd == "QUIT":
                    send("221 bye")
                    break
                else:
                    send(f"502 {cmd} not implemented")
            except Exception as e:  # keep the session alive on errors
                try:
                    send(f"451 error: {e}")
                except Exception:
                    break
