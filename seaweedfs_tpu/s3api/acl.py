"""S3 ACL grant model: canned ACLs, grant headers, AccessControlPolicy
XML, for buckets and objects.

Reference: `weed/s3api/s3api_acl_helper.go:33-93` (grant-header and canned
parsing/validation, grantee types id/uri/emailAddress, group URIs) and
`s3api_bucket_handlers.go` / `s3api_object_handlers_acl.go` surface. ACPs
persist as extended attributes on the bucket/object entries — the same
place the reference keeps them (entry.Extended). Access ENFORCEMENT in
this rebuild rides the identity/policy engine (auth.py + policy.py); the
ACL model is the stored, validated, served representation S3 clients
expect."""

from __future__ import annotations

import json
import re
from xml.sax.saxutils import escape

from .auth import err

GROUP_ALL_USERS = "http://acs.amazonaws.com/groups/global/AllUsers"
GROUP_AUTH_USERS = "http://acs.amazonaws.com/groups/global/AuthenticatedUsers"
GROUP_LOG_DELIVERY = "http://acs.amazonaws.com/groups/s3/LogDelivery"
_GROUPS = {GROUP_ALL_USERS, GROUP_AUTH_USERS, GROUP_LOG_DELIVERY}

PERMISSIONS = ("READ", "WRITE", "READ_ACP", "WRITE_ACP", "FULL_CONTROL")

# header -> permission (`s3api_acl_helper.go` Grant* header walk)
GRANT_HEADERS = {
    "x-amz-grant-read": "READ",
    "x-amz-grant-write": "WRITE",
    "x-amz-grant-read-acp": "READ_ACP",
    "x-amz-grant-write-acp": "WRITE_ACP",
    "x-amz-grant-full-control": "FULL_CONTROL",
}

CANNED_ACLS = {
    "private", "public-read", "public-read-write", "authenticated-read",
    "bucket-owner-read", "bucket-owner-full-control", "log-delivery-write",
    "aws-exec-read",
}

_GRANTEE_KV = re.compile(r'\s*(id|uri|emailAddress)\s*=\s*"([^"]*)"\s*$')
_EMAIL = re.compile(r"^[^@\s]+@[^@\s]+\.[^@\s]+$")


def _grant(gtype: str, value: str, perm: str) -> dict:
    return {"type": gtype, "value": value, "perm": perm}


def parse_grantee(token: str) -> tuple[str, str]:
    """One grantee from a grant header: id="...", uri="..." or
    emailAddress="..." — anything else is InvalidArgument, as is an
    unknown group URI or a malformed email."""
    m = _GRANTEE_KV.match(token)
    if m is None:
        raise err("InvalidArgument", f"invalid grantee {token!r}")
    kind, value = m.group(1), m.group(2)
    if not value:
        raise err("InvalidArgument", f"empty grantee in {token!r}")
    if kind == "uri":
        if value not in _GROUPS:
            raise err("InvalidArgument", f"unknown grantee group {value!r}")
        return "Group", value
    if kind == "emailAddress":
        if not _EMAIL.match(value):
            raise err("InvalidArgument", f"invalid email grantee {value!r}")
        return "AmazonCustomerByEmail", value
    return "CanonicalUser", value


def grants_from_headers(headers: dict) -> list[dict]:
    """Parse every x-amz-grant-* header (comma-separated grantee lists)."""
    grants: list[dict] = []
    for header, perm in GRANT_HEADERS.items():
        raw = headers.get(header, "")
        if not raw:
            continue
        for token in raw.split(","):
            if not token.strip():
                raise err("InvalidArgument",
                          f"empty grantee in {header}: {raw!r}")
            gtype, value = parse_grantee(token)
            grants.append(_grant(gtype, value, perm))
    return grants


def grants_from_canned(acl: str, owner_id: str,
                       bucket_owner_id: str = "") -> list[dict]:
    """Expand a canned x-amz-acl into explicit grants
    (`s3api_acl_helper.go` canned table)."""
    if acl not in CANNED_ACLS:
        raise err("InvalidArgument", f"invalid canned acl {acl!r}")
    grants = [_grant("CanonicalUser", owner_id, "FULL_CONTROL")]
    if acl == "public-read":
        grants.append(_grant("Group", GROUP_ALL_USERS, "READ"))
    elif acl == "public-read-write":
        grants.append(_grant("Group", GROUP_ALL_USERS, "READ"))
        grants.append(_grant("Group", GROUP_ALL_USERS, "WRITE"))
    elif acl == "authenticated-read":
        grants.append(_grant("Group", GROUP_AUTH_USERS, "READ"))
    elif acl == "aws-exec-read":
        pass  # EC2 service grantee has no analog here; owner-only
    elif acl == "bucket-owner-read" and bucket_owner_id:
        grants.append(_grant("CanonicalUser", bucket_owner_id, "READ"))
    elif acl == "bucket-owner-full-control" and bucket_owner_id:
        grants.append(
            _grant("CanonicalUser", bucket_owner_id, "FULL_CONTROL"))
    elif acl == "log-delivery-write":
        grants.append(_grant("Group", GROUP_LOG_DELIVERY, "WRITE"))
        grants.append(_grant("Group", GROUP_LOG_DELIVERY, "READ_ACP"))
    return grants


def extract_acl(headers: dict, owner_id: str,
                bucket_owner_id: str = "") -> list[dict] | None:
    """The request's ACL intent from headers, or None when no ACL headers
    are present. Canned + explicit grant headers together are rejected,
    as on AWS (InvalidRequest)."""
    canned = headers.get("x-amz-acl", "")
    grant_present = any(headers.get(h) for h in GRANT_HEADERS)
    if canned and grant_present:
        raise err("InvalidRequest",
                  "Specifying both Canned ACLs and Header Grants is"
                  " not allowed")
    if canned:
        return grants_from_canned(canned, owner_id, bucket_owner_id)
    if grant_present:
        return grants_from_headers(headers)
    return None


def acp_to_xml_inner(owner_id: str, grants: list[dict]) -> str:
    parts = [f"<Owner><ID>{escape(owner_id)}</ID></Owner>",
             "<AccessControlList>"]
    for g in grants:
        if g["type"] == "Group":
            grantee = (f'<Grantee xmlns:xsi="http://www.w3.org/2001/'
                       f'XMLSchema-instance" xsi:type="Group">'
                       f"<URI>{escape(g['value'])}</URI></Grantee>")
        elif g["type"] == "AmazonCustomerByEmail":
            grantee = (f'<Grantee xmlns:xsi="http://www.w3.org/2001/'
                       f'XMLSchema-instance" xsi:type="AmazonCustomerByEmail">'
                       f"<EmailAddress>{escape(g['value'])}</EmailAddress>"
                       f"</Grantee>")
        else:
            grantee = (f'<Grantee xmlns:xsi="http://www.w3.org/2001/'
                       f'XMLSchema-instance" xsi:type="CanonicalUser">'
                       f"<ID>{escape(g['value'])}</ID></Grantee>")
        parts.append(f"<Grant>{grantee}"
                     f"<Permission>{g['perm']}</Permission></Grant>")
    parts.append("</AccessControlList>")
    return "".join(parts)


def acp_from_xml(body: bytes) -> tuple[str, list[dict]]:
    """Parse a PUT ?acl AccessControlPolicy body -> (owner_id, grants)."""
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        raise err("MalformedACLError", str(e))
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[: root.tag.index("}") + 1]
    owner_id = root.findtext(f"{ns}Owner/{ns}ID", "")
    grants: list[dict] = []
    for g in root.findall(f"{ns}AccessControlList/{ns}Grant"):
        perm = g.findtext(f"{ns}Permission", "")
        if perm not in PERMISSIONS:
            raise err("MalformedACLError", f"bad permission {perm!r}")
        grantee = g.find(f"{ns}Grantee")
        if grantee is None:
            raise err("MalformedACLError", "grant without grantee")
        uri = grantee.findtext(f"{ns}URI")
        gid = grantee.findtext(f"{ns}ID")
        email = grantee.findtext(f"{ns}EmailAddress")
        if uri:
            if uri not in _GROUPS:
                raise err("InvalidArgument", f"unknown group {uri!r}")
            grants.append(_grant("Group", uri, perm))
        elif gid:
            grants.append(_grant("CanonicalUser", gid, perm))
        elif email:
            if not _EMAIL.match(email):
                raise err("InvalidArgument", f"invalid email {email!r}")
            grants.append(_grant("AmazonCustomerByEmail", email, perm))
        else:
            raise err("MalformedACLError", "grantee without ID/URI/Email")
    return owner_id, grants


def dumps(owner_id: str, grants: list[dict]) -> str:
    return json.dumps({"owner": owner_id, "grants": grants})


def loads(raw: str) -> tuple[str, list[dict]]:
    d = json.loads(raw)
    return d.get("owner", ""), d.get("grants", [])
