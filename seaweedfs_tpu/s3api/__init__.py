"""S3-compatible gateway over the filer.

Reference: `weed/s3api/` (~14k LoC): REST router, AWS SigV4 auth, bucket and
object handlers, multipart assembly via filer chunk concatenation, tagging,
identity/action authorization, circuit breaker.
"""

from .auth import Identity, IdentityAccessManagement, S3ApiError
from .s3_server import S3Server
from .sigv4_client import S3Client

__all__ = [
    "Identity",
    "IdentityAccessManagement",
    "S3ApiError",
    "S3Server",
    "S3Client",
]
