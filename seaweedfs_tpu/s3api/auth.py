"""AWS Signature V4 verification + identity/action authorization.

Reference: `weed/s3api/auth_credentials.go` (identities and actions),
`auth_signature_v4.go` (canonical request / string-to-sign / signing key),
`s3_constants/` (action names). Identities come from a JSON config
(`s3.json` style) or the filer's `/etc/iam/identity.json`, hot-reloaded via
the metadata subscription (`auth_credentials_subscribe.go`).
"""

from __future__ import annotations

import calendar
import hashlib
import hmac
import json
import time
import urllib.parse

ACTION_ADMIN = "Admin"
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"

UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"
STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"


class S3ApiError(Exception):
    """Maps to an S3 XML error response."""

    def __init__(self, code: str, message: str, status: int) -> None:
        super().__init__(message)
        self.code = code
        self.message = message
        self.status = status


ERRORS = {
    "AccessDenied": 403,
    "InvalidAccessKeyId": 403,
    "SignatureDoesNotMatch": 403,
    "AuthorizationHeaderMalformed": 400,
    "RequestTimeTooSkewed": 403,
    "NoSuchBucket": 404,
    "NoSuchKey": 404,
    "NoSuchUpload": 404,
    "NoSuchTagSet": 404,
    "NoSuchBucketPolicy": 404,
    "NoSuchCORSConfiguration": 404,
    "NoSuchLifecycleConfiguration": 404,
    "MalformedPolicy": 400,
    "MalformedPOSTRequest": 400,
    "BucketAlreadyExists": 409,
    "BucketNotEmpty": 409,
    "InvalidBucketName": 400,
    "MalformedXML": 400,
    "InvalidPart": 400,
    "InvalidPartOrder": 400,
    "EntityTooSmall": 400,
    "InvalidArgument": 400,
    "InvalidRange": 416,
    "SlowDown": 503,
    "NotImplemented": 501,
    "InternalError": 500,
}


def err(code: str, message: str = "") -> S3ApiError:
    return S3ApiError(code, message or code, ERRORS.get(code, 400))


class Identity:
    def __init__(
        self,
        name: str,
        credentials: list[tuple[str, str]],
        actions: list[str],
        account_id: str = "",
    ) -> None:
        self.name = name
        self.credentials = credentials  # [(access_key, secret_key)]
        self.actions = actions  # e.g. ["Admin"] or ["Read:bucket", "Write:bucket"]
        self.account_id = account_id or name

    def is_anonymous(self) -> bool:
        return self.name == "anonymous"

    def can_do(self, action: str, bucket: str = "", object_key: str = "") -> bool:
        """Action match per the reference's Identity.canDo
        (`auth_credentials.go:350`): "Admin" grants all; "<Action>" grants
        the action on every bucket; "<Action>:bucket" and
        "<Action>:bucket/prefix*" scope it."""
        if ACTION_ADMIN in self.actions:
            return True
        if action in self.actions:
            return True
        if not bucket:
            return False
        target = f"{action}:{bucket}"
        limited = f"{target}/{object_key.lstrip('/')}"
        for granted in self.actions:
            if granted == target:
                return True
            if granted.endswith("*") and limited.startswith(granted[:-1]):
                return True
        return False

    @staticmethod
    def from_dict(d: dict) -> "Identity":
        return Identity(
            name=d.get("name", ""),
            credentials=[
                (c["accessKey"], c["secretKey"])
                for c in d.get("credentials", [])
            ],
            actions=list(d.get("actions", [])),
            account_id=d.get("account_id", ""),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "credentials": [
                {"accessKey": a, "secretKey": s} for a, s in self.credentials
            ],
            "actions": self.actions,
            "account_id": self.account_id,
        }


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(s, safe=safe)


def canonical_query(query_pairs: list[tuple[str, str]]) -> str:
    pairs = sorted(
        (uri_encode(k), uri_encode(v)) for k, v in query_pairs
        if k != "X-Amz-Signature"
    )
    return "&".join(f"{k}={v}" for k, v in pairs)


def canonical_request(
    method: str,
    path: str,
    query_pairs: list[tuple[str, str]],
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
) -> str:
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join(
        [
            method,
            uri_encode(path, encode_slash=False),
            canonical_query(query_pairs),
            canon_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )


def string_to_sign(amz_date: str, scope: str, canon_req: str) -> str:
    return "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canon_req.encode()).hexdigest(),
        ]
    )


class IdentityAccessManagement:
    """Identity registry + request authentication."""

    def __init__(self, identities: list[Identity] | None = None,
                 domain: str = "", allow_anonymous_when_empty: bool = True) -> None:
        self.identities: list[Identity] = identities or []
        self.domain = domain
        self.allow_anonymous_when_empty = allow_anonymous_when_empty
        self._by_access_key: dict[str, tuple[Identity, str]] = {}
        self._reindex()

    def _reindex(self) -> None:
        self._by_access_key = {
            ak: (ident, sk)
            for ident in self.identities
            for ak, sk in ident.credentials
        }

    def load_config(self, config: dict) -> None:
        self.identities = [
            Identity.from_dict(d) for d in config.get("identities", [])
        ]
        self._reindex()

    def load_json(self, payload: bytes) -> None:
        self.load_config(json.loads(payload))

    def is_enabled(self) -> bool:
        return bool(self.identities)

    def lookup(self, access_key: str) -> tuple[Identity, str]:
        found = self._by_access_key.get(access_key)
        if found is None:
            raise err("InvalidAccessKeyId", f"unknown access key {access_key}")
        return found

    def anonymous_identity(self) -> Identity:
        for ident in self.identities:
            if ident.name == "anonymous":
                return ident
        if not self.identities and self.allow_anonymous_when_empty:
            return Identity("anonymous", [], [ACTION_ADMIN])
        raise err("AccessDenied", "anonymous access disabled")

    # --- request authentication -------------------------------------------------
    def authenticate(
        self,
        method: str,
        path: str,
        query_pairs: list[tuple[str, str]],
        headers: dict[str, str],
        body: bytes,
    ) -> Identity:
        """Verify SigV4 (header or presigned) and return the caller identity."""
        headers = {k.lower(): v for k, v in headers.items()}
        auth = headers.get("authorization", "")
        q = dict(query_pairs)
        if auth.startswith("AWS4-HMAC-SHA256"):
            return self._auth_header(method, path, query_pairs, headers, auth, body)
        if q.get("X-Amz-Algorithm") == "AWS4-HMAC-SHA256":
            return self._auth_presigned(method, path, query_pairs, headers)
        if auth.startswith("AWS "):
            if ":" not in auth:  # truncated V2 header must not fall through
                raise err("AuthorizationHeaderMalformed", auth)
            return self._auth_v2_header(method, path, query_pairs, headers,
                                        auth)
        if "Signature" in q and "AWSAccessKeyId" in q and "Expires" in q:
            return self._auth_v2_presigned(method, path, query_pairs, headers)
        return self.anonymous_identity()

    # --- Signature V2 (`weed/s3api/auth_signature_v2.go:64`) ------------------
    # StringToSign = Method \n Content-MD5 \n Content-Type \n Date \n
    #                CanonicalizedAmzHeaders CanonicalizedResource
    # signature = base64(HMAC-SHA1(secret, StringToSign)); header form
    # "AWS <akid>:<sig>", presigned form ?AWSAccessKeyId&Expires&Signature
    # (Expires replaces Date in the string to sign).

    # subresources included in the canonicalized resource, per the V2 spec
    _V2_SUBRESOURCES = (
        "acl", "delete", "lifecycle", "location", "logging", "notification",
        "partNumber", "policy", "requestPayment", "response-cache-control",
        "response-content-disposition", "response-content-encoding",
        "response-content-language", "response-content-type",
        "response-expires", "tagging", "torrent", "uploadId", "uploads",
        "versionId", "versioning", "versions", "website", "cors",
    )

    @classmethod
    def _v2_canonical_resource(cls, path: str,
                               query_pairs: list[tuple[str, str]]) -> str:
        sub = []
        for k, v in query_pairs:
            if k in cls._V2_SUBRESOURCES:
                sub.append(f"{k}={v}" if v else k)
        out = path or "/"
        if sub:
            out += "?" + "&".join(sorted(sub))
        return out

    @staticmethod
    def _v2_canonical_amz_headers(headers: dict) -> str:
        amz = {}
        for k, v in headers.items():
            lk = k.lower()
            if lk.startswith("x-amz-"):
                amz[lk] = " ".join(v.split())
        return "".join(f"{k}:{amz[k]}\n" for k in sorted(amz))

    def _v2_string_to_sign(self, method: str, path: str,
                           query_pairs: list[tuple[str, str]],
                           headers: dict, date_slot: str) -> str:
        return (
            f"{method}\n{headers.get('content-md5', '')}\n"
            f"{headers.get('content-type', '')}\n{date_slot}\n"
            f"{self._v2_canonical_amz_headers(headers)}"
            f"{self._v2_canonical_resource(path, query_pairs)}"
        )

    @staticmethod
    def _v2_sign(secret: str, string_to_sign: str) -> str:
        import base64

        return base64.b64encode(
            hmac.new(secret.encode(), string_to_sign.encode(),
                     hashlib.sha1).digest()
        ).decode()

    def _auth_v2_header(self, method, path, query_pairs, headers,
                        auth) -> Identity:
        akid, _, given = auth[4:].partition(":")
        if not akid or not given:
            raise err("AuthorizationHeaderMalformed", auth)
        ident, secret = self.lookup(akid)
        # with x-amz-date present the Date slot is EMPTY (the header is
        # already covered by the canonicalized amz headers)
        date_slot = "" if "x-amz-date" in headers else headers.get("date", "")
        sts = self._v2_string_to_sign(method, path, query_pairs, headers,
                                      date_slot)
        if not hmac.compare_digest(self._v2_sign(secret, sts), given):
            raise err("SignatureDoesNotMatch", "v2 signature mismatch")
        return ident

    def _auth_v2_presigned(self, method, path, query_pairs,
                           headers) -> Identity:
        q = dict(query_pairs)
        akid = q["AWSAccessKeyId"]
        expires = q["Expires"]
        try:
            if time.time() > int(expires):
                raise err("AccessDenied", "Request has expired")
        except ValueError:
            raise err("AccessDenied", f"invalid Expires {expires!r}")
        ident, secret = self.lookup(akid)
        sts = self._v2_string_to_sign(method, path, query_pairs, headers,
                                      expires)
        if not hmac.compare_digest(self._v2_sign(secret, sts),
                                   q["Signature"]):
            raise err("SignatureDoesNotMatch", "v2 presigned mismatch")
        return ident

    def _parse_credential(self, cred: str) -> tuple[str, str, str, str]:
        # <access-key>/<yyyymmdd>/<region>/<service>/aws4_request
        parts = cred.split("/")
        if len(parts) != 5 or parts[4] != "aws4_request":
            raise err("AuthorizationHeaderMalformed", f"bad credential {cred}")
        return parts[0], parts[1], parts[2], parts[3]

    def _auth_header(
        self, method, path, query_pairs, headers, auth, body
    ) -> Identity:
        fields = {}
        for item in auth[len("AWS4-HMAC-SHA256"):].split(","):
            k, _, v = item.strip().partition("=")
            fields[k] = v
        try:
            access_key, date, region, service = self._parse_credential(
                fields["Credential"]
            )
            signed = fields["SignedHeaders"].split(";")
            given_sig = fields["Signature"]
        except KeyError as e:
            raise err("AuthorizationHeaderMalformed", f"missing {e}")
        ident, secret = self.lookup(access_key)
        payload_hash = headers.get("x-amz-content-sha256", "")
        if not payload_hash:
            payload_hash = hashlib.sha256(body or b"").hexdigest()
        elif payload_hash not in (UNSIGNED_PAYLOAD,) and not payload_hash.startswith(
            "STREAMING-"
        ):
            want = hashlib.sha256(body or b"").hexdigest()
            if body is not None and payload_hash != want:
                raise err("SignatureDoesNotMatch", "content sha256 mismatch")
        amz_date = headers.get("x-amz-date", "")
        canon = canonical_request(
            method, path, query_pairs, headers, signed, payload_hash
        )
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = string_to_sign(amz_date, scope, canon)
        key = signing_key(secret, date, region, service)
        want_sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want_sig, given_sig):
            raise err("SignatureDoesNotMatch", "signature mismatch")
        return ident

    def _auth_presigned(self, method, path, query_pairs, headers) -> Identity:
        q = dict(query_pairs)
        try:
            access_key, date, region, service = self._parse_credential(
                q["X-Amz-Credential"]
            )
            signed = q["X-Amz-SignedHeaders"].split(";")
            given_sig = q["X-Amz-Signature"]
            amz_date = q["X-Amz-Date"]
        except KeyError as e:
            raise err("AuthorizationHeaderMalformed", f"missing {e}")
        expires = int(q.get("X-Amz-Expires", "604800"))
        t0 = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
        if time.time() > t0 + expires:
            raise err("AccessDenied", "request expired")
        ident, secret = self.lookup(access_key)
        canon = canonical_request(
            method, path, query_pairs, headers, signed, UNSIGNED_PAYLOAD
        )
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = string_to_sign(amz_date, scope, canon)
        key = signing_key(secret, date, region, service)
        want_sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want_sig, given_sig):
            raise err("SignatureDoesNotMatch", "signature mismatch")
        return ident


def deframe_streaming_body(body: bytes) -> bytes:
    """Strip aws-chunked framing (STREAMING-AWS4-HMAC-SHA256-PAYLOAD):
    `<hex-size>;chunk-signature=<sig>\\r\\n<data>\\r\\n...0;...` — per-chunk
    signatures are accepted without re-verification (the outer seed signature
    authenticated the request). Reference: `weed/s3api/chunked_reader_v4.go`."""
    out = bytearray()
    i = 0
    while i < len(body):
        j = body.find(b"\r\n", i)
        if j < 0:
            break
        header = body[i:j].decode("latin-1")
        size_hex = header.split(";")[0]
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise err("MalformedXML", f"bad chunk header {header!r}")
        if size == 0:
            break
        start = j + 2
        out += body[start : start + size]
        i = start + size + 2  # skip trailing \r\n
    return bytes(out)
