"""Request-concurrency circuit breaker: global and per-bucket limits on
simultaneous requests (and bytes) per action type; over-limit requests get
503 SlowDown. Reference: `weed/s3api/s3api_circuit_breaker.go`.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from .auth import err


class CircuitBreaker:
    def __init__(
        self,
        global_limits: dict[str, int] | None = None,
        bucket_limits: dict[str, dict[str, int]] | None = None,
    ) -> None:
        # limits: {"Read": max_concurrent, "Write": ...}; 0/missing = unlimited
        self.global_limits = global_limits or {}
        self.bucket_limits = bucket_limits or {}
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def _inc(self, key: str, limit: int) -> bool:
        if limit <= 0:
            return True
        with self._lock:
            cur = self._counts.get(key, 0)
            if cur >= limit:
                return False
            self._counts[key] = cur + 1
            return True

    def _dec(self, key: str) -> None:
        with self._lock:
            cur = self._counts.get(key, 0)
            if cur <= 1:
                self._counts.pop(key, None)
            else:
                self._counts[key] = cur - 1

    @contextmanager
    def limit(self, action: str, bucket: str):
        acquired: list[str] = []
        try:
            gkey = f"global:{action}"
            if not self._inc(gkey, self.global_limits.get(action, 0)):
                raise err("SlowDown", f"too many concurrent {action} requests")
            acquired.append(gkey)
            if bucket:
                bkey = f"bucket:{bucket}:{action}"
                blimit = self.bucket_limits.get(bucket, {}).get(action, 0)
                if not self._inc(bkey, blimit):
                    raise err(
                        "SlowDown", f"too many concurrent {action} on {bucket}"
                    )
                acquired.append(bkey)
            yield
        finally:
            for key in acquired:
                self._dec(key)
