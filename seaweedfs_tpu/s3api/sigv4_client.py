"""Minimal SigV4-signing S3 client.

Used by the test suite (no aws-sdk in this environment), the remote-storage
tiering backend, and the replication S3 sink — the same roles the reference
fills with aws-sdk-go (`weed/remote_storage/s3/`, `weed/replication/sink/s3sink`).
"""

from __future__ import annotations

import hashlib
import hmac
import time
import urllib.parse
import xml.etree.ElementTree as ET

from seaweedfs_tpu.server.httpd import http_request

from .auth import canonical_request, signing_key, string_to_sign


class S3Error(IOError):
    def __init__(self, status: int, code: str, message: str) -> None:
        super().__init__(f"{status} {code}: {message}")
        self.status = status
        self.code = code
        self.message = message


def _parse_error(status: int, body: bytes) -> S3Error:
    code, message = "UnknownError", ""
    try:
        root = ET.fromstring(body)
        code = root.findtext("Code") or code
        message = root.findtext("Message") or ""
    except ET.ParseError:
        pass
    return S3Error(status, code, message)


class S3Client:
    def __init__(
        self,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        service: str = "s3",
    ) -> None:
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = service

    # --- signing ----------------------------------------------------------------
    def _signed_headers(
        self, method: str, path: str, query_pairs: list[tuple[str, str]],
        body: bytes,
    ) -> dict[str, str]:
        host = urllib.parse.urlparse(self.endpoint).netloc
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        date = time.strftime("%Y%m%d", now)
        payload_hash = hashlib.sha256(body or b"").hexdigest()
        headers = {
            "host": host,
            "x-amz-date": amz_date,
            "x-amz-content-sha256": payload_hash,
        }
        if not self.access_key:
            return headers
        signed = sorted(headers)
        canon = canonical_request(
            method, path, query_pairs, headers, signed, payload_hash
        )
        scope = f"{date}/{self.region}/{self.service}/aws4_request"
        sts = string_to_sign(amz_date, scope, canon)
        key = signing_key(self.secret_key, date, self.region, self.service)
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}"
        )
        return headers

    def presign_url(
        self, method: str, bucket: str, key: str, expires: int = 3600
    ) -> str:
        """Presigned URL (query-string auth, UNSIGNED-PAYLOAD)."""
        host = urllib.parse.urlparse(self.endpoint).netloc
        now = time.gmtime()
        amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
        date = time.strftime("%Y%m%d", now)
        scope = f"{date}/{self.region}/{self.service}/aws4_request"
        path = urllib.parse.quote(f"/{bucket}/{key}", safe="/-_.~")
        pairs = [
            ("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
            ("X-Amz-Credential", f"{self.access_key}/{scope}"),
            ("X-Amz-Date", amz_date),
            ("X-Amz-Expires", str(expires)),
            ("X-Amz-SignedHeaders", "host"),
        ]
        canon = canonical_request(
            method, path, pairs, {"host": host}, ["host"], "UNSIGNED-PAYLOAD"
        )
        sts = string_to_sign(amz_date, scope, canon)
        key_bytes = signing_key(self.secret_key, date, self.region, self.service)
        sig = hmac.new(key_bytes, sts.encode(), hashlib.sha256).hexdigest()
        pairs.append(("X-Amz-Signature", sig))
        return f"{self.endpoint}{path}?{urllib.parse.urlencode(pairs)}"

    def request(
        self,
        method: str,
        path: str,
        query: dict[str, str] | list[tuple[str, str]] | None = None,
        body: bytes = b"",
        headers: dict[str, str] | None = None,
    ) -> tuple[int, dict, bytes]:
        pairs = list(query.items()) if isinstance(query, dict) else list(query or [])
        path = urllib.parse.quote(path, safe="/-_.~")
        signed = self._signed_headers(method, path, pairs, body)
        signed.update(headers or {})
        qs = urllib.parse.urlencode(pairs)
        url = f"{self.endpoint}{path}" + (f"?{qs}" if qs else "")
        return http_request(method, url, body or None, signed)

    def _ok(self, resp: tuple[int, dict, bytes]) -> tuple[int, dict, bytes]:
        status, headers, body = resp
        if status >= 400:
            raise _parse_error(status, body)
        return resp

    # --- buckets ----------------------------------------------------------------
    def create_bucket(self, bucket: str) -> None:
        self._ok(self.request("PUT", f"/{bucket}"))

    def delete_bucket(self, bucket: str) -> None:
        self._ok(self.request("DELETE", f"/{bucket}"))

    def head_bucket(self, bucket: str) -> bool:
        status, _, _ = self.request("HEAD", f"/{bucket}")
        return status < 400

    def list_buckets(self) -> list[str]:
        _, _, body = self._ok(self.request("GET", "/"))
        root = ET.fromstring(body)
        ns = _ns(root)
        return [
            el.findtext(f"{ns}Name") or ""
            for el in root.iter(f"{ns}Bucket")
        ]

    # --- objects ----------------------------------------------------------------
    def put_object(
        self, bucket: str, key: str, data: bytes,
        content_type: str = "", metadata: dict[str, str] | None = None,
    ) -> str:
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        for k, v in (metadata or {}).items():
            headers[f"x-amz-meta-{k}"] = v
        _, rh, _ = self._ok(
            self.request("PUT", f"/{bucket}/{key}", body=data, headers=headers)
        )
        return rh.get("ETag", "").strip('"')

    def get_object(
        self, bucket: str, key: str, range_header: str | None = None
    ) -> bytes:
        headers = {"Range": range_header} if range_header else {}
        _, _, body = self._ok(
            self.request("GET", f"/{bucket}/{key}", headers=headers)
        )
        return body

    def head_object(self, bucket: str, key: str) -> dict | None:
        status, headers, _ = self.request("HEAD", f"/{bucket}/{key}")
        return dict(headers) if status < 400 else None

    def delete_object(self, bucket: str, key: str) -> None:
        self._ok(self.request("DELETE", f"/{bucket}/{key}"))

    def copy_object(
        self, src_bucket: str, src_key: str, dst_bucket: str, dst_key: str
    ) -> None:
        self._ok(
            self.request(
                "PUT",
                f"/{dst_bucket}/{dst_key}",
                headers={"x-amz-copy-source": f"/{src_bucket}/{src_key}"},
            )
        )

    def delete_objects(self, bucket: str, keys: list[str]) -> list[str]:
        objs = "".join(f"<Object><Key>{k}</Key></Object>" for k in keys)
        body = f"<Delete>{objs}</Delete>".encode()
        _, _, out = self._ok(
            self.request("POST", f"/{bucket}", query={"delete": ""}, body=body)
        )
        root = ET.fromstring(out)
        ns = _ns(root)
        return [
            el.findtext(f"{ns}Key") or "" for el in root.iter(f"{ns}Deleted")
        ]

    def list_objects(
        self,
        bucket: str,
        prefix: str = "",
        delimiter: str = "",
        max_keys: int = 1000,
        continuation_token: str = "",
        v2: bool = True,
    ) -> dict:
        q: list[tuple[str, str]] = []
        if v2:
            q.append(("list-type", "2"))
            if continuation_token:
                q.append(("continuation-token", continuation_token))
        elif continuation_token:
            q.append(("marker", continuation_token))
        if prefix:
            q.append(("prefix", prefix))
        if delimiter:
            q.append(("delimiter", delimiter))
        q.append(("max-keys", str(max_keys)))
        _, _, body = self._ok(self.request("GET", f"/{bucket}", query=q))
        root = ET.fromstring(body)
        ns = _ns(root)
        return {
            "contents": [
                {
                    "key": el.findtext(f"{ns}Key") or "",
                    "size": int(el.findtext(f"{ns}Size") or 0),
                    "etag": (el.findtext(f"{ns}ETag") or "").strip('"'),
                }
                for el in root.iter(f"{ns}Contents")
            ],
            "common_prefixes": [
                el.findtext(f"{ns}Prefix") or ""
                for el in root.iter(f"{ns}CommonPrefixes")
            ],
            "is_truncated": (root.findtext(f"{ns}IsTruncated") == "true"),
            "next_token": root.findtext(f"{ns}NextContinuationToken")
            or root.findtext(f"{ns}NextMarker")
            or "",
        }

    # --- multipart --------------------------------------------------------------
    def create_multipart(self, bucket: str, key: str) -> str:
        _, _, body = self._ok(
            self.request("POST", f"/{bucket}/{key}", query={"uploads": ""})
        )
        root = ET.fromstring(body)
        return root.findtext(f"{_ns(root)}UploadId") or ""

    def upload_part(
        self, bucket: str, key: str, upload_id: str, part_number: int, data: bytes
    ) -> str:
        _, rh, _ = self._ok(
            self.request(
                "PUT",
                f"/{bucket}/{key}",
                query={"partNumber": str(part_number), "uploadId": upload_id},
                body=data,
            )
        )
        return rh.get("ETag", "").strip('"')

    def complete_multipart(
        self, bucket: str, key: str, upload_id: str,
        parts: list[tuple[int, str]],
    ) -> str:
        inner = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
            for n, e in parts
        )
        body = f"<CompleteMultipartUpload>{inner}</CompleteMultipartUpload>".encode()
        _, _, out = self._ok(
            self.request(
                "POST", f"/{bucket}/{key}", query={"uploadId": upload_id}, body=body
            )
        )
        root = ET.fromstring(out)
        return (root.findtext(f"{_ns(root)}ETag") or "").strip('"')

    def abort_multipart(self, bucket: str, key: str, upload_id: str) -> None:
        self._ok(
            self.request(
                "DELETE", f"/{bucket}/{key}", query={"uploadId": upload_id}
            )
        )

    def list_parts(self, bucket: str, key: str, upload_id: str) -> list[int]:
        _, _, body = self._ok(
            self.request("GET", f"/{bucket}/{key}", query={"uploadId": upload_id})
        )
        root = ET.fromstring(body)
        ns = _ns(root)
        return [
            int(el.findtext(f"{ns}PartNumber") or 0)
            for el in root.iter(f"{ns}Part")
        ]

    # --- tagging ----------------------------------------------------------------
    def put_object_tagging(self, bucket: str, key: str, tags: dict[str, str]) -> None:
        inner = "".join(
            f"<Tag><Key>{k}</Key><Value>{v}</Value></Tag>" for k, v in tags.items()
        )
        body = f"<Tagging><TagSet>{inner}</TagSet></Tagging>".encode()
        self._ok(
            self.request(
                "PUT", f"/{bucket}/{key}", query={"tagging": ""}, body=body
            )
        )

    def get_object_tagging(self, bucket: str, key: str) -> dict[str, str]:
        _, _, body = self._ok(
            self.request("GET", f"/{bucket}/{key}", query={"tagging": ""})
        )
        root = ET.fromstring(body)
        ns = _ns(root)
        return {
            (el.findtext(f"{ns}Key") or ""): (el.findtext(f"{ns}Value") or "")
            for el in root.iter(f"{ns}Tag")
        }

    def delete_object_tagging(self, bucket: str, key: str) -> None:
        self._ok(
            self.request("DELETE", f"/{bucket}/{key}", query={"tagging": ""})
        )


def _ns(root: ET.Element) -> str:
    """Namespace prefix of an element tree ('{uri}' or '')."""
    if root.tag.startswith("{"):
        return root.tag[: root.tag.index("}") + 1]
    return ""
