"""AWS bucket-policy evaluation for the S3 gateway.

The reference gateway stubs the bucket-policy REST handlers with 501s
(`weed/s3api/s3api_bucket_skip_handlers.go:27-39`) and scopes access purely
through per-identity IAM actions (`auth_credentials.go`). This module
implements the AWS evaluation model those APIs define so bucket owners can
grant or deny access across identities — including anonymous principals —
with standard policy documents:

* explicit Deny beats everything;
* otherwise access is allowed if EITHER the caller's IAM grants permit the
  action OR a policy statement allows it;
* statements match on Principal (name or wildcard), Action (s3:* patterns,
  case-insensitive like AWS), and Resource (arn:aws:s3:::bucket[/key]).

Condition blocks are not supported and are rejected at PutBucketPolicy time
rather than silently ignored — a policy that appears stricter than it is
would be a security hole.
"""

from __future__ import annotations

import json
import re

ALLOW = "allow"
DENY = "deny"

_ARN_PREFIX = "arn:aws:s3:::"


def _as_list(x) -> list:
    if x is None:
        return []
    return x if isinstance(x, list) else [x]


def _wild_match(pattern: str, value: str, ci: bool = False) -> bool:
    """AWS-style wildcard match: '*' any run, '?' one char; no [] classes."""
    if ci:
        pattern, value = pattern.lower(), value.lower()
    rx = "".join(
        ".*" if c == "*" else "." if c == "?" else re.escape(c)
        for c in pattern
    )
    return re.fullmatch(rx, value) is not None


def _principals(stmt: dict) -> list[str]:
    p = stmt.get("Principal")
    if p == "*":
        return ["*"]
    if isinstance(p, dict):
        return [str(a) for a in _as_list(p.get("AWS"))]
    return []


def _stmt_matches(stmt: dict, principal: str, action: str, resource: str) -> bool:
    principals = _principals(stmt)
    if not any(a == "*" or _wild_match(a, principal) for a in principals):
        return False
    if not any(
        _wild_match(a, action, ci=True) for a in _as_list(stmt.get("Action"))
    ):
        return False
    return any(
        _wild_match(r, resource) for r in _as_list(stmt.get("Resource"))
    )


def evaluate(doc: dict, principal: str, action: str, resource: str) -> str | None:
    """Returns DENY on any matching Deny statement, else ALLOW on any
    matching Allow statement, else None (no opinion — IAM decides)."""
    decision = None
    for stmt in _as_list(doc.get("Statement")):
        if not isinstance(stmt, dict):
            continue
        if not _stmt_matches(stmt, principal, action, resource):
            continue
        if stmt.get("Effect") == "Deny":
            return DENY
        if stmt.get("Effect") == "Allow":
            decision = ALLOW
    return decision


def validate(payload: bytes, bucket: str) -> dict:
    """Parse + validate a policy document for PutBucketPolicy; raises
    ValueError with a caller-facing message. Every Resource must target the
    policy's own bucket (AWS rejects cross-bucket resources the same way)."""
    try:
        doc = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        raise ValueError("policy is not valid JSON")
    if not isinstance(doc, dict):
        raise ValueError("policy must be a JSON object")
    if doc.get("Version") not in ("2012-10-17", "2008-10-17"):
        raise ValueError("unsupported policy Version")
    stmts = _as_list(doc.get("Statement"))
    if not stmts:
        raise ValueError("policy has no Statement")
    for stmt in stmts:
        if not isinstance(stmt, dict):
            raise ValueError("Statement must be an object")
        if stmt.get("Effect") not in ("Allow", "Deny"):
            raise ValueError("Statement Effect must be Allow or Deny")
        if "NotPrincipal" in stmt or "NotAction" in stmt or "NotResource" in stmt:
            raise ValueError("NotPrincipal/NotAction/NotResource unsupported")
        if "Condition" in stmt:
            raise ValueError("Condition blocks are not supported")
        if not _principals(stmt):
            raise ValueError("Statement needs Principal ('*' or {'AWS': ...})")
        actions = _as_list(stmt.get("Action"))
        if not actions or not all(
            isinstance(a, str) and a.lower().startswith("s3:") for a in actions
        ):
            raise ValueError("Action entries must be 's3:...' strings")
        resources = _as_list(stmt.get("Resource"))
        if not resources:
            raise ValueError("Statement needs Resource")
        for r in resources:
            if not isinstance(r, str) or not r.startswith(_ARN_PREFIX):
                raise ValueError(f"Resource must start with {_ARN_PREFIX}")
            target = r[len(_ARN_PREFIX):]
            if not (
                target == bucket or target.startswith(bucket + "/")
            ):
                raise ValueError(
                    f"Resource {r} does not target bucket {bucket}"
                )
    return doc


def arn(bucket: str, key: str = "") -> str:
    return f"{_ARN_PREFIX}{bucket}/{key}" if key else f"{_ARN_PREFIX}{bucket}"


# --- POST form policies (browser uploads) ----------------------------------
# Reference: `weed/s3api/policy/post-policy.go`, `postpolicyform.go`,
# `s3api_object_handlers_postpolicy.go`. The policy document is the base64
# form field the client signs; every other form field (bar the exempt set)
# must be covered by a condition, and conditions must all hold.

_POST_EXEMPT = {
    "file", "policy", "x-amz-signature", "success_action_status",
    "x-amz-algorithm", "x-amz-credential", "x-amz-date",
    # Signature V2 POST-policy auth fields (auth_signature_v2.go)
    "awsaccesskeyid", "signature",
}


def check_post_policy(doc: dict, fields: dict, file_size: int) -> None:
    """Raises ValueError when the form violates its signed policy."""
    import calendar as _calendar
    import time as _time

    exp = doc.get("expiration")
    if not exp:
        raise ValueError("policy missing expiration")
    try:
        expires = _calendar.timegm(
            _time.strptime(exp.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S")
        )
    except ValueError:
        raise ValueError(f"bad expiration {exp!r}")
    if expires < _time.time():
        raise ValueError("policy has expired")

    fields_ci = {k.lower(): v for k, v in fields.items()}
    covered: set[str] = set()

    def field_value(name: str) -> str:
        return fields_ci.get(name.lower(), "")

    for cond in _as_list(doc.get("conditions")):
        if isinstance(cond, dict):
            items = [["eq", f"${k}", v] for k, v in cond.items()]
        elif isinstance(cond, list) and len(cond) == 3:
            items = [cond]
        else:
            raise ValueError(f"bad condition {cond!r}")
        for op, name, want in items:
            op = str(op).lower()
            if op == "content-length-range":
                lo, hi = int(name), int(want)
                if not lo <= file_size <= hi:
                    raise ValueError(
                        f"file size {file_size} outside [{lo}, {hi}]"
                    )
                continue
            key = str(name).lstrip("$").lower()
            covered.add(key)
            have = field_value(key)
            if op == "eq":
                if have != str(want):
                    raise ValueError(f"condition eq ${key} failed")
            elif op == "starts-with":
                if not have.startswith(str(want)):
                    raise ValueError(f"condition starts-with ${key} failed")
            else:
                raise ValueError(f"unsupported condition op {op!r}")

    for name in fields_ci:
        if name in _POST_EXEMPT or name.startswith("x-ignore-"):
            continue
        if name not in covered:
            raise ValueError(f"form field {name!r} not covered by policy")
