"""S3 REST gateway server.

Router and handlers for bucket CRUD, object CRUD + copy, ListObjects V1/V2,
batch delete, multipart uploads (assembled by filer chunk concatenation),
object/bucket tagging, ACL/versioning/lifecycle stubs, SigV4 auth with
per-identity actions, and a concurrency circuit breaker.

Reference: `weed/s3api/s3api_server.go:110-290` (router),
`s3api_object_handlers*.go`, `s3api_bucket_handlers.go`,
`filer_multipart.go` (chunk-concatenation completion).

Objects live in the filer under `/buckets/<bucket>/<key>`; multipart parts
stage under `/buckets/<bucket>/.uploads/<uploadId>/`.
"""

from __future__ import annotations

import hashlib
import json
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape

from seaweedfs_tpu.filer.filer_client import FilerClient
from seaweedfs_tpu.server.httpd import HTTPService, Request, Response

from . import policy as bucket_policy
from .auth import (
    ACTION_ADMIN,
    ACTION_LIST,
    ACTION_READ,
    ACTION_TAGGING,
    ACTION_WRITE,
    Identity,
    IdentityAccessManagement,
    S3ApiError,
    deframe_streaming_body,
    err,
)
from .circuit_breaker import CircuitBreaker

BUCKETS_DIR = "/buckets"
UPLOADS_FOLDER = ".uploads"
VERSIONS_FOLDER = ".versions"
TAG_PREFIX = "X-Amz-Tagging-"
AMZ_META_PREFIX = "x-amz-meta-"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def xml_response(tag: str, inner: str, status: int = 200) -> Response:
    body = (
        f'<?xml version="1.0" encoding="UTF-8"?>'
        f'<{tag} xmlns="{XMLNS}">{inner}</{tag}>'
    ).encode()
    return Response(body, status, {"Content-Type": "application/xml"})


def error_response(e: S3ApiError, resource: str = "") -> Response:
    inner = (
        f"<Code>{e.code}</Code><Message>{escape(e.message)}</Message>"
        f"<Resource>{escape(resource)}</Resource>"
    )
    body = f'<?xml version="1.0" encoding="UTF-8"?><Error>{inner}</Error>'.encode()
    return Response(body, e.status, {"Content-Type": "application/xml"})


def amz_time(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class S3Server:
    def __init__(
        self,
        filer_url: str,
        host: str = "127.0.0.1",
        port: int = 8333,
        config: dict | None = None,
        circuit_breaker: CircuitBreaker | None = None,
        slow_ms: float | None = None,
        master_url: str | None = None,
        telemetry_dir: str | None = None,
        telemetry_retention_mb: float | None = None,
        qos_limits: str | None = None,
    ) -> None:
        self.fc = FilerClient(filer_url)
        # the gateway has no heartbeat/register link of its own, so an
        # optional master_url starts a TelemetryPusher (stats/aggregate):
        # without it this process's tenant sketches and 5xx never reach
        # the cluster aggregate
        self.master_url = master_url
        self._telemetry_pusher = None
        self.iam = IdentityAccessManagement()
        if config:
            self.iam.load_config(config)
        self.cb = circuit_breaker or CircuitBreaker()
        self.lifecycle_sweep_interval = 3600.0  # 0 disables the sweeper
        self._sweep_stop = None
        self.service = HTTPService(host, port)
        self.service.enable_metrics("s3", serve_route=False)
        # -telemetry.dir: durable history/event spool (stats/store.py)
        if telemetry_dir:
            from seaweedfs_tpu.stats import store as store_mod

            store_mod.enable(telemetry_dir, telemetry_retention_mb)
        if slow_ms is not None:  # -slowMs: per-role slow-span threshold
            from seaweedfs_tpu.stats import trace as trace_mod

            trace_mod.set_slow_threshold_ms(slow_ms, role="s3")
        # -qos.limits: arm admission control (qos/) + the burn actuator;
        # the bucket IS the collection on the S3 surface, so the same
        # tenant limit holds here and on the filer front door
        if qos_limits is not None:
            from seaweedfs_tpu.qos import actuator as qos_act
            from seaweedfs_tpu.qos import admission as qos_mod

            limits, default = qos_mod.parse_limits_spec(qos_limits)
            qos_mod.controller().set_limits(limits=limits, default=default)
            qos_mod.enable()
            qos_act.start(master_url=master_url)
        self._iam_subscriber = None
        self._routes()

    def _start_fastlane(self) -> None:
        """Engine front for the gateway. Beyond the proxy governor the
        filer uses, S3 FRONT MODE relays gated plain-object GET/PUT/DELETE
        (open IAM, no policy/versioning/meta/CORS in play) straight to the
        FILER's engine front door — object bytes never cross this process's
        GIL. Python keeps the full S3 surface; per-bucket native flags are
        computed here and re-validated continuously, so any state the
        translation cannot honor falls back with a typed reason."""
        from seaweedfs_tpu.storage import fastlane as fl_mod

        self.fastlane = fl_mod.front_service(
            self.service,
            guard_active=getattr(self.service, "guard", None) is not None,
            workers=1, max_backend=2,
        )
        self._fl_s3_on = False
        self._fl_native_buckets: dict[str, int] = {}
        self._fl_qos_revoked: set[str] = set()  # buckets shed off native
        self._fl_meta_dirty: set[str] = set()
        self._fl_uploads: set[tuple[str, str]] = set()
        self._fl_collector = None
        if self.fastlane is None:
            return
        import urllib.parse as _up

        from seaweedfs_tpu.util import glog

        u = _up.urlparse(self.fc.filer_url if "://" in self.fc.filer_url
                         else "http://" + self.fc.filer_url)
        if not u.hostname or not u.port:
            return
        rc = int(self.fastlane._lib.sw_fl_s3_enable(
            self.fastlane.handle, u.hostname.encode(), int(u.port)))
        if rc != 0:
            glog.warning("s3 native front disabled: %s",
                         fl_mod.error_str(self.fastlane._lib, rc))
            return
        self._fl_s3_on = True
        self._register_front_collector()

    FL_FRONT_FAMILIES = (
        "SeaweedFS_s3_fastlane_native_total",
        "SeaweedFS_s3_fastlane_fallback_total",
    )

    def _register_front_collector(self) -> None:
        from seaweedfs_tpu.stats import default_registry
        from seaweedfs_tpu.storage import fastlane as fl_mod

        def lines() -> list[str]:
            fl = self.fastlane
            if fl is None or fl.stopped:
                return []
            server = f"{self.service.host}:{fl.port}"
            return fl_mod.front_metric_lines(
                fl, "SeaweedFS_s3_fastlane", server)

        self._fl_collector = default_registry().register_collector(
            lines, names=self.FL_FRONT_FAMILIES)

    # --- s3 native-front bucket flags ---------------------------------------
    def _fl_bucket_flags(self, bucket: str, entry: dict | None = None) -> int:
        """Native permission bits for one bucket; 0 = every op falls back,
        -1 = bucket gone (forget it). Conservative by construction: any
        state the engine's translation can't honor drops the bit."""
        if self.iam.identities:
            return 0  # authenticated mode: requests need sigv4 (Python)
        if entry is None:
            entry = self.fc.get_entry(self._bucket_path(bucket))
        if entry is None or not entry.get("is_directory"):
            return -1
        ext = entry.get("extended") or {}
        if ext.get(self._EXT_POLICY) or ext.get(self._EXT_VERSIONING):
            return 0  # policy evaluation / version retirement is Python's
        flags = 0
        if (bucket not in self._fl_meta_dirty
                and not ext.get(self._EXT_META_DIRTY)):
            # an object with x-amz-meta attributes was written: native GETs
            # could not serve its metadata headers, so reads stay on Python.
            # The marker is ALSO persisted on the bucket entry — a gateway
            # restart (or a meta write through another gateway) must not
            # re-grant the read bit off an empty in-memory set; the
            # revalidation loop reads it back within one tick
            flags |= 1
        if not ext.get("s3-read-only"):
            flags |= 2
        flags |= 4  # deletes ignore the quota read-only flag (Python does)
        return flags

    def _fl_push_bucket(self, bucket: str, entry: dict | None = None) -> None:
        """(Re)install one bucket's native flags in the engine."""
        if not getattr(self, "_fl_s3_on", False) or self.fastlane is None:
            return
        try:
            flags = self._fl_bucket_flags(bucket, entry)
        except Exception:
            flags = 0
        if flags < 0:
            self.fastlane._lib.sw_fl_s3_bucket_set(
                self.fastlane.handle, bucket.encode(), -1)
            self._fl_native_buckets.pop(bucket, None)
            return
        if self._fl_native_buckets.get(bucket) != flags:
            self.fastlane._lib.sw_fl_s3_bucket_set(
                self.fastlane.handle, bucket.encode(), flags)
            self._fl_native_buckets[bucket] = flags

    def _fl_revoke_bucket(self, bucket: str) -> None:
        if not getattr(self, "_fl_s3_on", False) or self.fastlane is None:
            return
        self.fastlane._lib.sw_fl_s3_bucket_set(
            self.fastlane.handle, bucket.encode(), -1)
        self._fl_native_buckets.pop(bucket, None)

    def _fl_upload_set(self, bucket: str, upload_id: str, on: bool) -> None:
        if not getattr(self, "_fl_s3_on", False) or self.fastlane is None:
            return
        if on:
            self._fl_uploads.add((bucket, upload_id))
        else:
            self._fl_uploads.discard((bucket, upload_id))
        self.fastlane._lib.sw_fl_s3_upload_set(
            self.fastlane.handle, bucket.encode(), upload_id.encode(),
            1 if on else 0)

    def _fl_revalidate_loop(self) -> None:  # pragma: no cover - timing loop
        # out-of-band bucket state changes (quota enforcement via the
        # shell, another gateway's policy put) reach the flags within one
        # tick; same-gateway changes push synchronously from the handlers
        while not self._fl_reval_stop.wait(2.0):
            try:
                # QoS lever over the native front: a bucket in admission
                # deficit (qos/admission.py over_limit — its token bucket
                # ran dry, possibly from natively-served traffic charged
                # through the usage ABI fold) gets its native flags
                # revoked so the NEXT requests land on this dispatcher,
                # where typed 429/503s are served; flags restore within
                # one tick of the bucket recovering
                from seaweedfs_tpu.qos import admission as qos_ctl

                ctl = qos_ctl.controller()
                if ctl.armed:
                    from seaweedfs_tpu.storage import fastlane as fl_mod

                    self._qos_usage_state = fl_mod.qos_charge_usage(
                        self.fastlane,
                        getattr(self, "_qos_usage_state", {}))
                    for bucket in list(self._fl_native_buckets):
                        if ctl.over_limit(bucket):
                            self._fl_revoke_bucket(bucket)
                            self._fl_qos_revoked.add(bucket)
                    for bucket in list(self._fl_qos_revoked):
                        if not ctl.over_limit(bucket):
                            self._fl_qos_revoked.discard(bucket)
                            self._fl_push_bucket(bucket)
                elif self._fl_qos_revoked:
                    for bucket in list(self._fl_qos_revoked):
                        self._fl_push_bucket(bucket)
                    self._fl_qos_revoked.clear()
                for bucket in list(self._fl_native_buckets):
                    self._fl_push_bucket(bucket)
                # uploads completed/aborted through ANOTHER gateway leave
                # this engine's registry stale — a late native part PUT
                # would recreate the deleted staging dir as an orphan and
                # 200 an upload that no longer exists. Unregister any
                # registration whose manifest vanished; its part PUTs fall
                # back to Python, which answers NoSuchUpload.
                for bucket, uid in list(self._fl_uploads):
                    gone = self.fc.get_entry(
                        f"{self._uploads_dir(bucket, uid)}/upload.json"
                    ) is None
                    if gone:
                        self._fl_upload_set(bucket, uid, False)
            except Exception:
                pass

    def start(self) -> None:
        import threading

        self._start_fastlane()
        try:
            self.fc.mkdir(BUCKETS_DIR)
        except IOError:
            pass
        self._load_iam_from_filer()
        self._watch_iam()
        self._fl_reval_stop = threading.Event()
        if getattr(self, "_fl_s3_on", False):
            threading.Thread(target=self._fl_revalidate_loop,
                             daemon=True).start()
        if self.lifecycle_sweep_interval > 0:
            self._sweep_stop = threading.Event()

            def sweeper():  # pragma: no cover - timing loop
                while not self._sweep_stop.wait(self.lifecycle_sweep_interval):
                    try:
                        self.run_lifecycle_sweep()
                    except Exception:
                        pass

            threading.Thread(target=sweeper, daemon=True).start()
        if self.master_url:
            from seaweedfs_tpu.stats import aggregate as agg_mod

            self._telemetry_pusher = agg_mod.TelemetryPusher(
                "s3", lambda: self.url, self.master_url)
            self._telemetry_pusher.start()

    def stop(self) -> None:
        if self._telemetry_pusher is not None:
            self._telemetry_pusher.stop()
            self._telemetry_pusher = None
        if self._sweep_stop is not None:
            self._sweep_stop.set()
        if getattr(self, "_fl_reval_stop", None) is not None:
            self._fl_reval_stop.set()
        if self._iam_subscriber is not None:
            self._iam_subscriber.stop()
        if getattr(self, "_fl_collector", None) is not None:
            from seaweedfs_tpu.stats import default_registry

            default_registry().unregister_collector(self._fl_collector)
            self._fl_collector = None
        if getattr(self, "fastlane", None) is not None:
            self.fastlane.stop()
            self.fastlane = None
        self.service.stop()

    @property
    def url(self) -> str:
        if getattr(self, "fastlane", None) is not None:
            scheme = "https" if self.fastlane.tls else "http"
            return f"{scheme}://{self.service.host}:{self.fastlane.port}"
        return self.service.url

    # --- IAM config hot reload (`auth_credentials_subscribe.go`) ---------------
    IAM_CONFIG_PATH = "/etc/iam/identity.json"

    def _load_iam_from_filer(self) -> None:
        try:
            status, _, body = self.fc.get(self.IAM_CONFIG_PATH)
            if status == 200 and body:
                self.iam.load_json(body)
                # identities appearing means every request now needs
                # sigv4: drop all native flags immediately
                for bucket in list(getattr(self, "_fl_native_buckets", {})):
                    self._fl_push_bucket(bucket)
        except Exception:
            pass

    def _watch_iam(self) -> None:
        from seaweedfs_tpu.filer.meta_aggregator import MetaSubscriber

        def on_event(ev: dict) -> None:
            e = ev.get("new_entry")
            if e and e.get("full_path") == self.IAM_CONFIG_PATH:
                self._load_iam_from_filer()

        try:
            sub = MetaSubscriber(
                self.fc.filer_url, on_event, path_prefix="/etc/iam",
                since_ns=time.time_ns(),
            )
            sub.start()
            self._iam_subscriber = sub
        except Exception:
            self._iam_subscriber = None

    # --- routing ----------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service

        @svc.route("GET", r"/")
        def list_buckets(req: Request) -> Response:
            return self._dispatch(req, "", "")

        for method in ("OPTIONS",):
            # CORS preflight carries no credentials; matched against the
            # bucket's CORS config only (`s3api_server.go` cors.New wrapper)
            @svc.route(method, r"/([^/]+)")
            def bucket_preflight(req: Request) -> Response:
                return self._preflight(req, req.match.group(1))

            @svc.route(method, r"/([^/]+)/(.*)")
            def object_preflight(req: Request) -> Response:
                return self._preflight(req, req.match.group(1))

        for method in ("GET", "PUT", "POST", "DELETE", "HEAD"):
            @svc.route(method, r"/([^/]+)")
            def bucket_level(req: Request) -> Response:
                return self._dispatch(req, req.match.group(1), "")

            @svc.route(method, r"/([^/]+)/(.*)")
            def object_level(req: Request) -> Response:
                return self._dispatch(
                    req, req.match.group(1), req.match.group(2)
                )

    def _query_pairs(self, req: Request) -> list[tuple[str, str]]:
        # S3 subresources are empty-valued query keys ("?uploads"); the
        # default Request.query drops them, so re-parse keeping blanks
        parsed = urllib.parse.urlparse(req.handler.path)
        return urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)

    def _dispatch(self, req: Request, bucket: str, key: str) -> Response:
        pairs = self._query_pairs(req)
        q = dict(pairs)
        resource = f"/{bucket}/{key}" if key else f"/{bucket}"
        if bucket:
            # QoS admission (qos/admission.py) before auth or body bytes:
            # the bucket IS the collection. A shed is a typed S3 error —
            # SlowDown (429, tenant-caused) / ServiceUnavailable (503,
            # capacity) — with Retry-After + machine-readable reason.
            # The unconfigured path is one attribute check.
            from seaweedfs_tpu import qos as qos_mod

            if qos_mod.controller().armed:
                d = None
                try:
                    cls = qos_mod.classify(
                        req.method, req.headers,
                        background_hint=(req.method == "GET" and not key))
                    d = qos_mod.admit(bucket, cls)
                except Exception:
                    d = None  # admission must never fail a request untyped
                if d is not None:
                    code = ("SlowDown" if d.status == 429
                            else "ServiceUnavailable")
                    resp = error_response(
                        S3ApiError(code,
                                   f"qos {d.reason}: request shed;"
                                   f" retry after {d.retry_after:.1f}s",
                                   d.status),
                        resource)
                    resp.headers.update(d.headers())
                    self._apply_cors_headers(req, bucket, resp)
                    return resp
        if (
            req.method == "POST"
            and bucket
            and not key
            and "multipart/form-data" in req.headers.get("Content-Type", "")
        ):
            # browser POST upload, authenticated by its signed form policy
            # (`s3api_object_handlers_postpolicy.go`)
            try:
                with self.cb.limit(ACTION_WRITE, bucket):
                    resp = self._post_policy_upload(req, bucket)
            except S3ApiError as e:
                resp = error_response(e, resource)
            self._apply_cors_headers(req, bucket, resp)
            return resp
        try:
            body = req.body
            try:
                ident = self.iam.authenticate(
                    req.method,
                    urllib.parse.unquote(
                        urllib.parse.urlparse(req.handler.path).path
                    ),
                    pairs,
                    dict(req.headers),
                    body,
                )
            except S3ApiError as e:
                # unauthenticated (NOT mis-signed) requests proceed as the
                # anonymous principal: a bucket policy may Allow "*"
                if e.code != "AccessDenied":
                    raise
                ident = Identity("anonymous", [], [])
            action = self._required_action(req.method, bucket, key, q)
            # bucket-policy evaluation (s3api/policy.py): explicit Deny
            # wins; Allow unions with the identity's IAM grants
            decision = None
            if bucket:
                doc = self._bucket_policy_doc(bucket)
                if doc is not None:
                    decision = bucket_policy.evaluate(
                        doc,
                        ident.name,
                        self._s3_action_name(req.method, bucket, key, q),
                        bucket_policy.arn(bucket, urllib.parse.unquote(key)),
                    )
            if decision == bucket_policy.DENY:
                raise err(
                    "AccessDenied", f"policy denies {resource} to {ident.name}"
                )
            if decision != bucket_policy.ALLOW and not ident.can_do(
                action, bucket, key
            ):
                raise err("AccessDenied", f"{ident.name} cannot {action} {resource}")
            # CopyObject also reads the source object — authorize both sides
            copy_source = req.headers.get("x-amz-copy-source")
            if req.method == "PUT" and key and copy_source:
                src = urllib.parse.unquote(copy_source).lstrip("/")
                src_bucket, _, src_key = src.partition("/")
                if not ident.can_do(ACTION_READ, src_bucket, src_key):
                    raise err(
                        "AccessDenied", f"{ident.name} cannot Read /{src}"
                    )
            with self.cb.limit(action, bucket):
                resp = self._handle(
                    req, bucket, urllib.parse.unquote(key), q, ident
                )
        except S3ApiError as e:
            resp = error_response(e, resource)
        except Exception as e:  # any internal failure → S3 XML error surface
            resp = error_response(err("InternalError", str(e)), resource)
        if bucket:
            self._apply_cors_headers(req, bucket, resp)
            # tenant accounting (stats/usage.py): the bucket IS the
            # collection on the S3 surface. Natively-relayed buckets never
            # reach this dispatcher — the engine's per-collection counters
            # cover those, so nothing double-counts.
            try:
                from seaweedfs_tpu.stats import usage as usage_mod

                usage_mod.accountant().record(
                    bucket,
                    bytes_in=float(
                        int(req.headers.get("Content-Length") or 0)
                        if req.method in ("PUT", "POST") else 0),
                    bytes_out=float(len(resp.body)
                                    if req.method == "GET" else 0),
                    error=resp.status >= 500,
                )
            except Exception:  # accounting must never fail a request
                pass
        return resp

    @staticmethod
    def _required_action(method: str, bucket: str, key: str, q: dict) -> str:
        if "policy" in q or "cors" in q or "lifecycle" in q or (
            "versioning" in q and method == "PUT"
        ):
            return ACTION_ADMIN  # bucket-owner configuration surfaces
        if "tagging" in q:
            return ACTION_TAGGING
        if not bucket:
            return ACTION_LIST  # ListBuckets (filtered per identity)
        if not key:
            if method in ("PUT", "DELETE"):
                return ACTION_ADMIN  # create/delete bucket
            if method == "POST":
                return ACTION_WRITE  # batch delete
            return ACTION_LIST
        if method in ("GET", "HEAD"):
            return ACTION_READ
        return ACTION_WRITE

    @staticmethod
    def _s3_action_name(method: str, bucket: str, key: str, q: dict) -> str:
        """Canonical AWS action name for policy matching."""
        if "acl" in q:
            kind = "Object" if key else "Bucket"
            return {"GET": f"s3:Get{kind}Acl",
                    "PUT": f"s3:Put{kind}Acl"}.get(method, f"s3:Get{kind}Acl")
        if "policy" in q:
            return {"GET": "s3:GetBucketPolicy", "PUT": "s3:PutBucketPolicy",
                    "DELETE": "s3:DeleteBucketPolicy"}.get(method, "s3:GetBucketPolicy")
        if "cors" in q:
            return {"GET": "s3:GetBucketCors", "PUT": "s3:PutBucketCors",
                    "DELETE": "s3:DeleteBucketCors"}.get(method, "s3:GetBucketCors")
        if "lifecycle" in q:
            return {"GET": "s3:GetLifecycleConfiguration",
                    "PUT": "s3:PutLifecycleConfiguration",
                    "DELETE": "s3:PutLifecycleConfiguration"}.get(
                method, "s3:GetLifecycleConfiguration")
        if "tagging" in q:
            kind = "Object" if key else "Bucket"
            return {"GET": f"s3:Get{kind}Tagging", "PUT": f"s3:Put{kind}Tagging",
                    "DELETE": f"s3:Delete{kind}Tagging"}.get(
                method, f"s3:Get{kind}Tagging")
        if not key:
            if method == "PUT":
                return "s3:CreateBucket"
            if method == "DELETE":
                return "s3:DeleteBucket"
            if method == "POST":
                return "s3:DeleteObject"  # batch delete
            if "uploads" in q:
                return "s3:ListBucketMultipartUploads"
            return "s3:ListBucket"
        if "uploadId" in q or "uploads" in q:
            return {"DELETE": "s3:AbortMultipartUpload",
                    "GET": "s3:ListMultipartUploadParts"}.get(
                method, "s3:PutObject")
        if method in ("GET", "HEAD"):
            return "s3:GetObject"
        if method == "DELETE":
            return "s3:DeleteObject"
        return "s3:PutObject"

    def _handle(
        self, req: Request, bucket: str, key: str, q: dict, ident
    ) -> Response:
        m = req.method
        if not bucket:
            return self._list_buckets(ident)
        if not key:
            if "tagging" in q:  # before bucket CRUD — a Tagging-only identity
                path = self._bucket_path(bucket)  # must never create/delete
                if m == "GET":
                    return self._get_tagging(path)
                if m == "PUT":
                    return self._put_tagging(path, req.body)
                if m == "DELETE":
                    return self._delete_tagging(path)
            if "policy" in q:
                if m == "GET":
                    return self._get_bucket_policy(bucket)
                if m == "PUT":
                    return self._put_bucket_policy(bucket, req.body)
                if m == "DELETE":
                    return self._delete_bucket_policy(bucket)
            if "cors" in q:
                if m == "GET":
                    return self._get_bucket_cors(bucket)
                if m == "PUT":
                    return self._put_bucket_cors(bucket, req.body)
                if m == "DELETE":
                    return self._delete_bucket_ext(bucket, "cors", 204)
            if "lifecycle" in q:
                if m == "GET":
                    return self._get_bucket_lifecycle(bucket)
                if m == "PUT":
                    return self._put_bucket_lifecycle(bucket, req.body)
                if m == "DELETE":
                    return self._delete_bucket_ext(bucket, "lifecycle", 204)
            if m == "PUT":
                if "versioning" in q:
                    return self._put_bucket_versioning(bucket, req.body)
                if "acl" in q:
                    return self._put_acl(req, ident, bucket)
                grants = self._parse_request_acl(req, ident)
                resp = self._put_bucket(bucket)
                if grants is None:
                    # record the creator as owner even without ACL headers
                    # so GET ?acl reports a stable owner, not the caller
                    from . import acl as acl_mod

                    grants = acl_mod.grants_from_canned(
                        "private", ident.account_id)
                self._apply_acl(ident.account_id, bucket, None, grants)
                return resp
            if m == "DELETE":
                return self._delete_bucket(bucket)
            if m == "HEAD":
                return self._head_bucket(bucket)
            if m == "POST" and "delete" in q:
                return self._delete_objects(req, bucket)
            if m == "GET":
                if "uploads" in q:
                    return self._list_multipart_uploads(bucket)
                if "location" in q:
                    return xml_response("LocationConstraint", "")
                if "versioning" in q:
                    return self._get_bucket_versioning(bucket)
                if "versions" in q:
                    return self._list_object_versions(bucket, q)
                if "acl" in q:
                    return self._get_acl(ident, bucket)
                return self._list_objects(req, bucket, q)
        else:
            if "uploadId" in q:
                if m == "PUT":
                    return self._upload_part(req, bucket, key, q)
                if m == "POST":
                    return self._complete_multipart(req, bucket, key, q)
                if m == "DELETE":
                    return self._abort_multipart(bucket, key, q)
                if m == "GET":
                    return self._list_parts(bucket, key, q)
            if m == "POST" and "uploads" in q:
                return self._create_multipart(req, bucket, key)
            if "tagging" in q:
                path = self._object_path(bucket, key)
                if m == "GET":
                    return self._get_tagging(path)
                if m == "PUT":
                    return self._put_tagging(path, req.body)
                if m == "DELETE":
                    return self._delete_tagging(path)
            if "acl" in q:
                if m == "GET":
                    return self._get_acl(ident, bucket, key)
                if m == "PUT":
                    return self._put_acl(req, ident, bucket, key)
            if m == "PUT":
                grants = self._parse_request_acl(req, ident)
                if req.headers.get("x-amz-copy-source"):
                    resp = self._copy_object(req, bucket, key)
                else:
                    resp = self._put_object(req, bucket, key)
                self._apply_acl(ident.account_id, bucket, key, grants)
                return resp
            if m in ("GET", "HEAD"):
                if "versionId" in q:
                    return self._get_object_version(
                        req, bucket, key, q["versionId"], head=(m == "HEAD")
                    )
                return self._get_object(req, bucket, key, head=(m == "HEAD"))
            if m == "DELETE":
                if "versionId" in q:
                    return self._delete_object_version(
                        bucket, key, q["versionId"]
                    )
                return self._delete_object(bucket, key)
        raise err("NotImplemented", f"{m} {req.path}?{urllib.parse.urlencode(q)}")

    # --- path helpers -----------------------------------------------------------
    @staticmethod
    def _bucket_path(bucket: str) -> str:
        if not bucket or "/" in bucket or bucket.startswith("."):
            raise err("InvalidBucketName", bucket)
        return f"{BUCKETS_DIR}/{bucket}"

    def _object_path(self, bucket: str, key: str) -> str:
        return f"{self._bucket_path(bucket)}/{key}"

    def _require_bucket(self, bucket: str) -> dict:
        entry = self.fc.get_entry(self._bucket_path(bucket))
        if entry is None or not entry.get("is_directory"):
            raise err("NoSuchBucket", bucket)
        # discovery hook for the native front: the first Python-handled
        # request on a bucket computes + installs its engine flags, so
        # subsequent plain-object traffic serves natively
        if bucket not in getattr(self, "_fl_native_buckets", {}):
            self._fl_push_bucket(bucket, entry)
        return entry

    def _require_writable_bucket(self, bucket: str) -> dict:
        """Uploads into a read-only bucket are rejected — the state
        `s3.bucket.quota.enforce -apply` flips when usage exceeds the
        quota (`command_s3_bucket_quota_check.go` semantics)."""
        entry = self._require_bucket(bucket)
        if (entry.get("extended") or {}).get("s3-read-only"):
            raise err("AccessDenied", f"bucket {bucket} is read-only"
                                      " (quota enforcement)")
        return entry

    # --- bucket handlers --------------------------------------------------------
    def _list_buckets(self, ident) -> Response:
        listing = self.fc.list(BUCKETS_DIR, limit=10_000)
        inner = ""
        for e in listing.get("Entries", []):
            if not e.get("IsDirectory"):
                continue
            name = e["FullPath"].rsplit("/", 1)[-1]
            if name.startswith("."):
                continue
            if not (
                ident.can_do(ACTION_LIST, name) or ident.can_do(ACTION_READ, name)
            ):
                continue
            inner += (
                f"<Bucket><Name>{escape(name)}</Name>"
                f"<CreationDate>{amz_time(e.get('Mtime', 0))}</CreationDate>"
                f"</Bucket>"
            )
        owner = (
            f"<Owner><ID>{escape(ident.account_id)}</ID>"
            f"<DisplayName>{escape(ident.name)}</DisplayName></Owner>"
        )
        return xml_response(
            "ListAllMyBucketsResult", f"{owner}<Buckets>{inner}</Buckets>"
        )

    def _put_bucket(self, bucket: str) -> Response:
        path = self._bucket_path(bucket)
        if self.fc.exists(path):
            raise err("BucketAlreadyExists", bucket)
        self.fc.mkdir(path)
        self._fl_push_bucket(bucket)
        return Response(b"", 200, {"Location": f"/{bucket}"})

    def _delete_bucket(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        # revoke the native flags BEFORE the namespace delete: a racing
        # native PUT must not recreate the bucket path mid-removal
        self._fl_revoke_bucket(bucket)
        listing = self.fc.list(self._bucket_path(bucket), limit=2)
        entries = [
            e for e in listing.get("Entries", [])
            if e["FullPath"].rsplit("/", 1)[-1]
            not in (UPLOADS_FOLDER, VERSIONS_FOLDER)
        ]
        if entries:
            raise err("BucketNotEmpty", bucket)
        self.fc.delete(self._bucket_path(bucket), recursive=True)
        # a bucket recreated under the same name starts meta-clean (the
        # persistent marker died with the directory entry)
        self._fl_meta_dirty.discard(bucket)
        return Response(b"", 204)

    def _head_bucket(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        return Response(b"", 200)

    # --- bucket configuration (policy / CORS / lifecycle) -------------------
    # Stored as extended attributes of the bucket directory entry, the same
    # place the reference keeps bucket metadata (`bucket_metadata.go` reads
    # entry.Extended). Policy documents are JSON; CORS and lifecycle keep
    # their original XML.

    _EXT_POLICY = "s3-policy"
    _EXT_CORS = "s3-cors"
    _EXT_LIFECYCLE = "s3-lifecycle"
    # set once the bucket holds an x-amz-meta-carrying object: the native
    # GET relay can't serve metadata headers, so reads stay on Python.
    # Persisted (not just in-memory) so restarts and peer gateways see it.
    _EXT_META_DIRTY = "s3-meta-objects"

    def _bucket_ext_get(self, bucket: str, attr: str) -> str | None:
        entry = self._require_bucket(bucket)
        return (entry.get("extended") or {}).get(attr)

    def _bucket_ext_set(self, bucket: str, attr: str, value: str | None) -> None:
        path = self._bucket_path(bucket)
        entry = self._require_bucket(bucket)
        ext = entry.setdefault("extended", {})
        if value is None:
            ext.pop(attr, None)
        else:
            ext[attr] = value
        self.fc.put_entry(path, entry)
        # every bucket-state mutation (policy/versioning/read-only/...)
        # funnels through here: recompute the native flags synchronously so
        # the engine never serves a request the new state forbids
        self._fl_push_bucket(bucket, entry)

    def _delete_bucket_ext(self, bucket: str, kind: str, status: int) -> Response:
        attr = {"cors": self._EXT_CORS, "lifecycle": self._EXT_LIFECYCLE,
                "policy": self._EXT_POLICY}[kind]
        self._bucket_ext_set(bucket, attr, None)
        return Response(b"", status)

    def _bucket_policy_doc(self, bucket: str) -> dict | None:
        try:
            raw = self._bucket_ext_get(bucket, self._EXT_POLICY)
        except S3ApiError:
            return None  # NoSuchBucket surfaces from the handler itself
        if not raw:
            return None
        try:
            return json.loads(raw)
        except ValueError:  # pragma: no cover - validated at put time
            return None

    def _get_bucket_policy(self, bucket: str) -> Response:
        raw = self._bucket_ext_get(bucket, self._EXT_POLICY)
        if not raw:
            raise err("NoSuchBucketPolicy", bucket)
        return Response(raw.encode(), 200, {"Content-Type": "application/json"})

    def _put_bucket_policy(self, bucket: str, body: bytes) -> Response:
        self._require_bucket(bucket)
        try:
            doc = bucket_policy.validate(body, bucket)
        except ValueError as e:
            raise err("MalformedPolicy", str(e))
        self._bucket_ext_set(
            bucket, self._EXT_POLICY, json.dumps(doc, separators=(",", ":"))
        )
        return Response(b"", 204)

    def _delete_bucket_policy(self, bucket: str) -> Response:
        return self._delete_bucket_ext(bucket, "policy", 204)

    # CORS (`s3api_server.go` cors wrapper; AWS CORSConfiguration semantics)
    def _parse_cors_rules(self, xml_text: str) -> list[dict]:
        try:
            root = ET.fromstring(xml_text)
        except ET.ParseError:
            raise err("MalformedXML", "bad CORSConfiguration")
        rules = []
        for rule_el in root.iter():
            if not (rule_el.tag == "CORSRule" or rule_el.tag.endswith("}CORSRule")):
                continue
            rule: dict = {"origins": [], "methods": [], "headers": [],
                          "expose": [], "max_age": None}
            for c in rule_el:
                tag = c.tag.rsplit("}", 1)[-1]
                text = (c.text or "").strip()
                if tag == "AllowedOrigin":
                    rule["origins"].append(text)
                elif tag == "AllowedMethod":
                    rule["methods"].append(text.upper())
                elif tag == "AllowedHeader":
                    rule["headers"].append(text)
                elif tag == "ExposeHeader":
                    rule["expose"].append(text)
                elif tag == "MaxAgeSeconds":
                    rule["max_age"] = int(text or 0)
            if rule["origins"] and rule["methods"]:
                rules.append(rule)
        if not rules:
            raise err("MalformedXML", "CORSConfiguration has no valid rules")
        return rules

    def _cors_rules(self, bucket: str) -> list[dict]:
        try:
            raw = self._bucket_ext_get(bucket, self._EXT_CORS)
        except S3ApiError:
            return []
        if not raw:
            return []
        try:
            return self._parse_cors_rules(raw)
        except S3ApiError:  # pragma: no cover - validated at put time
            return []

    @staticmethod
    def _match_cors_rule(rules: list[dict], origin: str, method: str,
                         req_headers: list[str]) -> dict | None:
        from .policy import _wild_match

        for rule in rules:
            if not any(_wild_match(o, origin) for o in rule["origins"]):
                continue
            if method not in rule["methods"]:
                continue
            if req_headers and not all(
                any(_wild_match(h.lower(), want.lower())
                    for h in rule["headers"])
                for want in req_headers
            ):
                continue
            return rule
        return None

    def _get_bucket_cors(self, bucket: str) -> Response:
        raw = self._bucket_ext_get(bucket, self._EXT_CORS)
        if not raw:
            raise err("NoSuchCORSConfiguration", bucket)
        return Response(raw.encode(), 200, {"Content-Type": "application/xml"})

    def _put_bucket_cors(self, bucket: str, body: bytes) -> Response:
        self._require_bucket(bucket)
        self._parse_cors_rules(body.decode("utf-8", "replace"))  # validate
        self._bucket_ext_set(bucket, self._EXT_CORS,
                             body.decode("utf-8", "replace"))
        return Response(b"", 200)

    def _preflight(self, req: Request, bucket: str) -> Response:
        origin = req.headers.get("origin", "")
        method = req.headers.get("access-control-request-method", "")
        want_headers = [
            h.strip()
            for h in req.headers.get("access-control-request-headers", "").split(",")
            if h.strip()
        ]
        rule = self._match_cors_rule(
            self._cors_rules(bucket), origin, method, want_headers
        )
        if origin == "" or method == "" or rule is None:
            return Response(b"", 403)
        headers = {
            "Access-Control-Allow-Origin":
                "*" if rule["origins"] == ["*"] else origin,
            "Access-Control-Allow-Methods": ", ".join(rule["methods"]),
            "Vary": "Origin, Access-Control-Request-Headers",
        }
        allow_headers = want_headers or rule["headers"]
        if allow_headers:
            headers["Access-Control-Allow-Headers"] = ", ".join(allow_headers)
        if rule["expose"]:
            headers["Access-Control-Expose-Headers"] = ", ".join(rule["expose"])
        if rule["max_age"] is not None:
            headers["Access-Control-Max-Age"] = str(rule["max_age"])
        return Response(b"", 200, headers)

    def _apply_cors_headers(self, req: Request, bucket: str, resp: Response) -> None:
        origin = req.headers.get("origin", "")
        if not origin:
            return
        rule = self._match_cors_rule(
            self._cors_rules(bucket), origin, req.method, []
        )
        if rule is None:
            return
        resp.headers.setdefault(
            "Access-Control-Allow-Origin",
            "*" if rule["origins"] == ["*"] else origin,
        )
        if rule["expose"]:
            resp.headers.setdefault(
                "Access-Control-Expose-Headers", ", ".join(rule["expose"])
            )
        resp.headers.setdefault("Vary", "Origin")

    # lifecycle (`s3api_bucket_handlers.go:308-435`; expiry applied here by
    # an explicit sweep over the namespace rather than collection TTLs)
    def _get_bucket_lifecycle(self, bucket: str) -> Response:
        raw = self._bucket_ext_get(bucket, self._EXT_LIFECYCLE)
        if not raw:
            raise err("NoSuchLifecycleConfiguration", bucket)
        return Response(raw.encode(), 200, {"Content-Type": "application/xml"})

    def _parse_lifecycle_rules(self, xml_text: str) -> list[dict]:
        try:
            root = ET.fromstring(xml_text)
        except ET.ParseError:
            raise err("MalformedXML", "bad LifecycleConfiguration")
        rules = []
        for rule_el in root.iter():
            if not (rule_el.tag == "Rule" or rule_el.tag.endswith("}Rule")):
                continue
            status = ""
            prefix = ""
            days = 0
            for c in rule_el.iter():
                tag = c.tag.rsplit("}", 1)[-1]
                text = (c.text or "").strip()
                if tag == "Status":
                    status = text
                elif tag == "Prefix" and text:
                    prefix = text
                elif tag == "Days" and text:
                    days = int(text)
            if status == "Enabled" and days > 0:
                rules.append({"prefix": prefix, "days": days})
        return rules

    def _put_bucket_lifecycle(self, bucket: str, body: bytes) -> Response:
        self._require_bucket(bucket)
        text = body.decode("utf-8", "replace")
        if not self._parse_lifecycle_rules(text):
            raise err(
                "MalformedXML",
                "no Enabled rule with Expiration Days found",
            )
        self._bucket_ext_set(bucket, self._EXT_LIFECYCLE, text)
        return Response(b"", 200)

    def run_lifecycle_sweep(self, now: float | None = None) -> dict:
        """Apply every bucket's lifecycle expiry rules: delete objects whose
        mtime is older than the rule's Days (prefix-filtered). Returns
        {bucket: expired_count}. Driven by the background sweeper thread
        (lifecycle_sweep_interval) or called directly (tests, operators
        embedding the gateway)."""
        now = now or time.time()
        out: dict[str, int] = {}
        listing = self.fc.list(BUCKETS_DIR, limit=10_000)
        for e in listing.get("Entries", []):
            if not e.get("IsDirectory"):
                continue
            bucket = e["FullPath"].rsplit("/", 1)[-1]
            if bucket.startswith("."):
                continue
            raw = self._bucket_ext_get(bucket, self._EXT_LIFECYCLE)
            if not raw:
                continue
            try:
                rules = self._parse_lifecycle_rules(raw)
            except S3ApiError:
                continue
            vstate = self._versioning_state(bucket)
            expired = 0
            for rule in rules:
                cutoff = now - rule["days"] * 86400
                expired += self._expire_prefix(
                    bucket, rule["prefix"], cutoff, vstate
                )
            if expired:
                out[bucket] = expired
        return out

    def _expire_prefix(
        self, bucket: str, prefix: str, cutoff: float, vstate: str = ""
    ) -> int:
        removed = 0
        base = self._bucket_path(bucket)

        def walk(dir_path: str, rel: str) -> None:
            nonlocal removed
            listing = self.fc.list(dir_path, limit=100_000)
            for e in listing.get("Entries", []):
                name = e["FullPath"].rsplit("/", 1)[-1]
                if name in (UPLOADS_FOLDER, VERSIONS_FOLDER):
                    continue
                rel_key = f"{rel}{name}"
                if e.get("IsDirectory"):
                    walk(e["FullPath"], rel_key + "/")
                    continue
                if not rel_key.startswith(prefix):
                    continue
                if e.get("Mtime", 0) < cutoff:
                    try:
                        # expiry on a versioned bucket leaves a delete
                        # marker (AWS lifecycle semantics), not destruction
                        self._versioned_delete(bucket, rel_key, vstate)
                        removed += 1
                    except IOError:
                        pass

        walk(base, "")
        return removed

    # --- ACLs (`s3api_acl_helper.go:33-93`) -----------------------------------
    # Stored as extended attributes on the bucket/object entries, like the
    # other bucket metadata. GET serves the stored ACP (default: owner
    # FULL_CONTROL); PUT accepts canned/grant headers or an
    # AccessControlPolicy body, fully validated.

    _EXT_ACL = "s3-acl"

    def _acl_entry(self, bucket: str, key: str | None):
        if key is None:
            return self._bucket_path(bucket), self._require_bucket(bucket)
        path = self._object_path(bucket, key)
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", key)
        return path, entry

    def _acl_owner(self, bucket: str, key: str | None, ident) -> str:
        """The resource's recorded owner: its own stored ACP, else the
        BUCKET's stored ACP (objects inherit the bucket owner), else the
        requester (pre-ACL resources with no record of their creator)."""
        from . import acl as acl_mod

        _, entry = self._acl_entry(bucket, key)
        raw = (entry.get("extended") or {}).get(self._EXT_ACL)
        if not raw and key is not None:
            _, bentry = self._acl_entry(bucket, None)
            raw = (bentry.get("extended") or {}).get(self._EXT_ACL)
        if raw:
            return acl_mod.loads(raw)[0]
        return ident.account_id

    def _get_acl(self, ident, bucket: str, key: str | None = None) -> Response:
        from . import acl as acl_mod

        _, entry = self._acl_entry(bucket, key)
        raw = (entry.get("extended") or {}).get(self._EXT_ACL)
        if raw:
            owner, grants = acl_mod.loads(raw)
        else:
            owner = self._acl_owner(bucket, key, ident)
            grants = [{"type": "CanonicalUser", "value": owner,
                       "perm": "FULL_CONTROL"}]
        return xml_response("AccessControlPolicy",
                            acl_mod.acp_to_xml_inner(owner, grants))

    def _put_acl(self, req: Request, ident, bucket: str,
                 key: str | None = None) -> Response:
        from . import acl as acl_mod

        owner = self._acl_owner(bucket, key, ident)
        grants = self._parse_request_acl(req, ident)
        if grants is None:
            if not req.body:
                # bare PUT ?acl: private (owner-only), as on AWS
                grants = acl_mod.grants_from_canned("private", owner)
            else:
                owner_in, grants = acl_mod.acp_from_xml(req.body)
                # AWS rejects an ACP whose Owner differs from the
                # resource's actual owner — accepting it would let any
                # writer spoof ownership
                if owner_in and owner_in != owner:
                    raise err("AccessDenied",
                              "ACP owner does not match resource owner")
        self._apply_acl(owner, bucket, key, grants)
        return Response(b"", 200)

    def _parse_request_acl(self, req: Request, ident) -> list | None:
        """Validate x-amz-acl / x-amz-grant-* headers on PUT bucket/object
        BEFORE the write happens (bad grants must fail the request without
        side effects); returns the grants or None when absent."""
        from . import acl as acl_mod

        headers = {k.lower(): v for k, v in req.headers.items()}
        return acl_mod.extract_acl(headers, ident.account_id,
                                   bucket_owner_id=ident.account_id)

    def _apply_acl(self, owner: str, bucket: str, key: str | None,
                   grants: list | None) -> None:
        from . import acl as acl_mod

        if grants is None:
            return
        path, entry = self._acl_entry(bucket, key)
        entry.setdefault("extended", {})[self._EXT_ACL] = acl_mod.dumps(
            owner, grants)
        self.fc.put_entry(path, entry)

    def _post_policy_upload(self, req: Request, bucket: str) -> Response:
        """POST object via browser form (sigv4-HTTPPOSTConstructPolicy):
        verify the form's signature over its base64 policy, enforce every
        policy condition, then store under the form's key."""
        import base64
        import hmac as hmac_mod

        from .auth import signing_key

        self._require_writable_bucket(bucket)
        fields, file_part = req.multipart_form()
        if file_part is None:
            raise err("MalformedPOSTRequest", "form has no file part")
        filename, file_ctype, data = file_part
        fields_ci = {k.lower(): v for k, v in fields.items()}
        key = fields_ci.get("key", "")
        if not key:
            raise err("MalformedPOSTRequest", "form has no key field")
        key = key.replace("${filename}", filename)

        policy_b64 = fields_ci.get("policy", "")
        if not policy_b64:
            raise err("AccessDenied", "POST without policy is not allowed")
        if ("awsaccesskeyid" in fields_ci
                and "x-amz-algorithm" not in fields_ci):
            # POST-policy V2 (`auth_signature_v2.go` DoesPolicySignatureV2
            # Match): signature = base64(HMAC-SHA1(secret, policy_b64))
            akid = fields_ci["awsaccesskeyid"]
            ident, secret = self.iam.lookup(akid)
            if not hmac_mod.compare_digest(
                self.iam._v2_sign(secret, policy_b64),
                fields_ci.get("signature", ""),
            ):
                raise err("SignatureDoesNotMatch", "post policy v2 signature")
        else:
            if fields_ci.get("x-amz-algorithm") != "AWS4-HMAC-SHA256":
                raise err("MalformedPOSTRequest",
                          "unsupported x-amz-algorithm")
            cred = fields_ci.get("x-amz-credential", "")
            parts = cred.split("/")
            if (len(parts) != 5 or parts[3] != "s3"
                    or parts[4] != "aws4_request"):
                raise err("MalformedPOSTRequest", f"bad credential {cred!r}")
            akid, date, region = parts[0], parts[1], parts[2]
            ident, secret = self.iam.lookup(akid)
            want = hmac_mod.new(
                signing_key(secret, date, region, "s3"),
                policy_b64.encode(),
                hashlib.sha256,
            ).hexdigest()
            if not hmac_mod.compare_digest(
                want, fields_ci.get("x-amz-signature", "")
            ):
                raise err("SignatureDoesNotMatch", "post policy signature")
        try:
            doc = json.loads(base64.b64decode(policy_b64))
            bucket_policy.check_post_policy(
                doc, {**fields_ci, "bucket": bucket, "key": key}, len(data)
            )
        except ValueError as e:
            raise err("AccessDenied", f"policy check failed: {e}")
        if not ident.can_do(ACTION_WRITE, bucket, key):
            raise err("AccessDenied", f"{ident.name} cannot Write /{bucket}/{key}")

        ctype = fields_ci.get("content-type", file_ctype)
        self.fc.put(self._object_path(bucket, key), data, ctype)
        etag = hashlib.md5(data).hexdigest()
        status = int(fields_ci.get("success_action_status", "204") or 204)
        headers = {"ETag": f'"{etag}"', "Location": f"/{bucket}/{key}"}
        if status == 201:
            inner = (
                f"<Location>/{escape(bucket)}/{escape(key)}</Location>"
                f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
                f'<ETag>"{etag}"</ETag>'
            )
            resp = xml_response("PostResponse", inner, 201)
            resp.headers.update(headers)
            return resp
        if status not in (200, 204):
            status = 204
        return Response(b"", status, headers)

    # --- versioning (`s3api_object_handlers_put.go` versioning flags; real
    # version retention rather than the reference's pass-through) ------------
    _EXT_VERSIONING = "s3-versioning"
    _EXT_VID = "s3-vid"
    _EXT_DELETE_MARKER = "s3-delete-marker"

    def _versioning_state(self, bucket: str) -> str:
        try:
            return self._bucket_ext_get(bucket, self._EXT_VERSIONING) or ""
        except S3ApiError:
            return ""

    def _get_bucket_versioning(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        state = self._versioning_state(bucket)
        inner = f"<Status>{state}</Status>" if state else ""
        return xml_response("VersioningConfiguration", inner)

    def _put_bucket_versioning(self, bucket: str, body: bytes) -> Response:
        self._require_bucket(bucket)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise err("MalformedXML", "bad VersioningConfiguration")
        status = ""
        for el in root.iter():
            if el.tag.rsplit("}", 1)[-1] == "Status":
                status = (el.text or "").strip()
        if status not in ("Enabled", "Suspended"):
            raise err("MalformedXML", "Status must be Enabled or Suspended")
        self._bucket_ext_set(bucket, self._EXT_VERSIONING, status)
        return Response(b"", 200)

    @staticmethod
    def _new_version_id() -> str:
        return f"{time.time_ns():020d}.{uuid.uuid4().hex[:8]}"

    def _versions_dir(self, bucket: str, key: str) -> str:
        return f"{self._bucket_path(bucket)}/{VERSIONS_FOLDER}/{key}"

    def _entry_vid(self, entry: dict | None) -> str:
        if not entry:
            return ""
        return (entry.get("extended") or {}).get(self._EXT_VID, "null")

    def _retire_current_version(
        self, bucket: str, key: str, only_real_vid: bool = False
    ) -> None:
        """Move the current object into the versions folder under its own
        version id (chunks move with the entry — no data copy).
        only_real_vid: leave a "null"-version current in place (Suspended
        semantics: the null version is the one that gets overwritten)."""
        path = self._object_path(bucket, key)
        cur = self.fc.get_entry(path)
        if cur is None or cur.get("is_directory"):
            return
        vid = self._entry_vid(cur)
        if only_real_vid and vid == "null":
            return
        try:
            self.fc.rename(path, f"{self._versions_dir(bucket, key)}/{vid}")
        except IOError:
            pass

    def _stamp_vid(self, path: str, vid: str) -> None:
        entry = self.fc.get_entry(path)
        if entry is not None:
            entry.setdefault("extended", {})[self._EXT_VID] = vid
            self.fc.put_entry(path, entry)

    # --- object handlers --------------------------------------------------------
    def _put_object(self, req: Request, bucket: str, key: str) -> Response:
        self._require_writable_bucket(bucket)
        body = req.body
        sha_hdr = req.headers.get("x-amz-content-sha256", "")
        if sha_hdr.startswith("STREAMING-"):
            body = deframe_streaming_body(body)
        if key.endswith("/"):
            self.fc.mkdir(self._object_path(bucket, key.rstrip("/")))
            return Response(b"", 200, {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'})
        etag = hashlib.md5(body).hexdigest()
        content_type = req.headers.get("Content-Type", "")
        vstate = self._versioning_state(bucket)
        vid = ""
        if vstate == "Enabled":
            self._retire_current_version(bucket, key)
            vid = self._new_version_id()
        elif vstate == "Suspended":
            # AWS: suspension only stops MINTING ids — versions written
            # while enabled stay retained; only the "null" version is
            # overwritten in place
            self._retire_current_version(bucket, key, only_real_vid=True)
            vid = "null"
        self.fc.put(self._object_path(bucket, key), body, content_type)
        if vid:
            self._stamp_vid(self._object_path(bucket, key), vid)
        # x-amz-meta-* headers persist as extended attributes
        meta = {
            k.lower()[len(AMZ_META_PREFIX):]: v
            for k, v in req.headers.items()
            if k.lower().startswith(AMZ_META_PREFIX)
        }
        if meta:
            path = self._object_path(bucket, key)
            entry = self.fc.get_entry(path)
            if entry is not None:
                entry.setdefault("extended", {}).update(
                    {f"{AMZ_META_PREFIX}{k}": v for k, v in meta.items()}
                )
                self.fc.put_entry(path, entry)
            # the native GET relay cannot serve x-amz-meta headers; once a
            # bucket holds meta-carrying objects its reads stay on Python
            # (persisted on the bucket entry so restarts and peer gateways
            # drop the read bit too; _bucket_ext_set re-pushes the flags)
            if bucket not in self._fl_meta_dirty:
                self._fl_meta_dirty.add(bucket)
                try:
                    self._bucket_ext_set(bucket, self._EXT_META_DIRTY, "1")
                except Exception:
                    self._fl_push_bucket(bucket)
        headers = {"ETag": f'"{etag}"'}
        if vid:
            headers["x-amz-version-id"] = vid
        return Response(b"", 200, headers)

    def _copy_object(self, req: Request, bucket: str, key: str) -> Response:
        self._require_writable_bucket(bucket)
        src = urllib.parse.unquote(req.headers["x-amz-copy-source"]).lstrip("/")
        src_bucket, _, src_key = src.partition("/")
        src_entry = self.fc.get_entry(self._object_path(src_bucket, src_key))
        if src_entry is None or src_entry.get("is_directory"):
            raise err("NoSuchKey", src)
        # replicate metadata + chunk list; the blobs are shared until the
        # source is deleted and reclaimed, so materialize the data instead
        data = self.fc.read(self._object_path(src_bucket, src_key))
        self.fc.put(
            self._object_path(bucket, key),
            data,
            src_entry.get("attributes", {}).get("mime", ""),
        )
        etag = hashlib.md5(data).hexdigest()
        inner = (
            f"<ETag>\"{etag}\"</ETag>"
            f"<LastModified>{amz_time(time.time())}</LastModified>"
        )
        return xml_response("CopyObjectResult", inner)

    def _get_object(
        self, req: Request, bucket: str, key: str, head: bool,
        path_override: str | None = None,
    ) -> Response:
        self._require_bucket(bucket)
        path = path_override or self._object_path(bucket, key)
        entry = self.fc.get_entry(path)
        if entry is None or entry.get("is_directory"):
            raise err("NoSuchKey", key)
        attrs = entry.get("attributes", {})
        headers = {
            "ETag": f'"{attrs.get("md5") or ""}"',
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(attrs.get("mtime", 0))
            ),
            "Accept-Ranges": "bytes",
        }
        if attrs.get("mime"):
            headers["Content-Type"] = attrs["mime"]
        for k, v in (entry.get("extended") or {}).items():
            if k.startswith(AMZ_META_PREFIX):
                headers[k] = v
        size = attrs.get("file_size", 0) or sum(
            c["size"] for c in entry.get("chunks", [])
        )
        if entry.get("content"):
            size = len(entry["content"]) // 2  # hex-encoded
        if head:
            headers["Content-Length"] = str(size)
            return Response(b"", 200, headers)
        status, fh, body = self.fc.get(path, req.headers.get("Range"))
        if status >= 400:
            raise err("NoSuchKey", key)
        if "Content-Range" in fh:
            headers["Content-Range"] = fh["Content-Range"]
        return Response(body, status, headers)

    def _versioned_delete(self, bucket: str, key: str, vstate: str) -> dict:
        """Versioning-aware delete shared by DELETE, batch delete and the
        lifecycle sweep; returns the response headers. Enabled: retire the
        current version, leave a delete marker. Suspended: real-vid current
        versions are still retained; the null version dies and a null
        marker takes its place. Off: plain destructive delete."""
        if vstate not in ("Enabled", "Suspended"):
            self.fc.delete(self._object_path(bucket, key), recursive=True)
            return {}
        if vstate == "Enabled":
            self._retire_current_version(bucket, key)
            vid = self._new_version_id()
        else:
            self._retire_current_version(bucket, key, only_real_vid=True)
            try:
                self.fc.delete(self._object_path(bucket, key))
            except IOError:
                pass
            vid = "null"
        marker_path = f"{self._versions_dir(bucket, key)}/{vid}"
        self.fc.put(marker_path, b"", "")
        entry = self.fc.get_entry(marker_path)
        if entry is not None:
            entry.setdefault("extended", {}).update(
                {self._EXT_VID: vid, self._EXT_DELETE_MARKER: "1"}
            )
            self.fc.put_entry(marker_path, entry)
        return {"x-amz-delete-marker": "true", "x-amz-version-id": vid}

    def _delete_object(self, bucket: str, key: str) -> Response:
        self._require_bucket(bucket)
        vstate = self._versioning_state(bucket)
        if vstate in ("Enabled", "Suspended"):
            return Response(b"", 204, self._versioned_delete(bucket, key, vstate))
        self.fc.delete(self._object_path(bucket, key), recursive=True)
        return Response(b"", 204)

    def _iter_versions(self, bucket: str, key: str) -> list[dict]:
        """All retired versions of one key, newest first (version ids are
        time-ordered)."""
        try:
            listing = self.fc.list(
                self._versions_dir(bucket, key), limit=10_000
            )
        except IOError:
            return []  # key has no retained versions
        out = [
            e for e in listing.get("Entries", [])
            if not e.get("IsDirectory")
        ]
        # newest first; the "null" (pre-versioning) id is always oldest
        out.sort(
            key=lambda e: (
                "" if (n := e["FullPath"].rsplit("/", 1)[-1]) == "null" else n
            ),
            reverse=True,
        )
        return out

    def _get_object_version(
        self, req: Request, bucket: str, key: str, vid: str, head: bool
    ) -> Response:
        self._require_bucket(bucket)
        cur = self.fc.get_entry(self._object_path(bucket, key))
        if cur is not None and self._entry_vid(cur) == vid:
            return self._get_object(req, bucket, key, head=head)
        path = f"{self._versions_dir(bucket, key)}/{vid}"
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", f"{key}?versionId={vid}")
        if (entry.get("extended") or {}).get(self._EXT_DELETE_MARKER):
            return Response(
                b"", 405,
                {"x-amz-delete-marker": "true", "x-amz-version-id": vid,
                 "Allow": "DELETE"},
            )
        resp = self._get_object(
            req, bucket, key, head=head, path_override=path
        )
        resp.headers["x-amz-version-id"] = vid
        return resp

    def _delete_object_version(self, bucket: str, key: str, vid: str) -> Response:
        """Permanent removal of one version; the next-newest non-marker
        version is promoted back to the current path when the current slot
        is empty (AWS: the latest remaining version becomes current)."""
        self._require_bucket(bucket)
        cur_path = self._object_path(bucket, key)
        cur = self.fc.get_entry(cur_path)
        marker = False
        if cur is not None and self._entry_vid(cur) == vid:
            self.fc.delete(cur_path)
        else:
            path = f"{self._versions_dir(bucket, key)}/{vid}"
            entry = self.fc.get_entry(path)
            if entry is None:
                return Response(b"", 204)
            marker = bool(
                (entry.get("extended") or {}).get(self._EXT_DELETE_MARKER)
            )
            self.fc.delete(path)
        # promote: only when no live current remains and the newest
        # remaining version is a real object (not a delete marker)
        if self.fc.get_entry(cur_path) is None:
            for v in self._iter_versions(bucket, key):
                entry = self.fc.get_entry(v["FullPath"])
                vext = (entry or {}).get("extended") or {}
                if vext.get(self._EXT_DELETE_MARKER):
                    break  # a marker is the latest: stay deleted
                try:
                    self.fc.rename(v["FullPath"], cur_path)
                except IOError:
                    pass
                break
        headers = {"x-amz-version-id": vid}
        if marker:
            headers["x-amz-delete-marker"] = "true"
        return Response(b"", 204, headers)

    def _list_object_versions(self, bucket: str, q: dict) -> Response:
        """GET ?versions — Version + DeleteMarker elements, newest first per
        key, current object marked IsLatest."""
        self._require_bucket(bucket)
        prefix = q.get("prefix", "")
        key_marker = q.get("key-marker", "")
        max_keys = min(int(q.get("max-keys", "1000") or 1000), 1000)
        inner = [
            f"<Name>{escape(bucket)}</Name>",
            f"<Prefix>{escape(prefix)}</Prefix>",
            f"<KeyMarker>{escape(key_marker)}</KeyMarker>",
            f"<MaxKeys>{max_keys}</MaxKeys>",
        ]

        def emit(key: str, entry: dict, is_latest: bool) -> None:
            ext = entry.get("extended") or {}
            vid = ext.get(self._EXT_VID, "null")
            mtime = entry.get("attributes", {}).get("mtime", 0)
            if ext.get(self._EXT_DELETE_MARKER):
                inner.append(
                    f"<DeleteMarker><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{'true' if is_latest else 'false'}</IsLatest>"
                    f"<LastModified>{amz_time(mtime)}</LastModified>"
                    f"</DeleteMarker>"
                )
            else:
                size = entry.get("attributes", {}).get("file_size", 0)
                inner.append(
                    f"<Version><Key>{escape(key)}</Key>"
                    f"<VersionId>{vid}</VersionId>"
                    f"<IsLatest>{'true' if is_latest else 'false'}</IsLatest>"
                    f"<LastModified>{amz_time(mtime)}</LastModified>"
                    f"<Size>{size}</Size></Version>"
                )

        # keys with retained versions, discovered from the versions tree
        vroot = f"{self._bucket_path(bucket)}/{VERSIONS_FOLDER}"
        keys: set[str] = set()

        def walk(dir_path: str, rel: str) -> None:
            listing = self.fc.list(dir_path, limit=100_000)
            entries = listing.get("Entries", [])
            if entries and all(not e.get("IsDirectory") for e in entries):
                keys.add(rel.rstrip("/"))
                return
            for e in entries:
                name = e["FullPath"].rsplit("/", 1)[-1]
                if e.get("IsDirectory"):
                    walk(e["FullPath"], rel + name + "/")
                else:
                    keys.add(rel.rstrip("/"))

        if self.fc.exists(vroot):
            walk(vroot, "")
        # current objects too (they may have no retired versions yet)
        marker = ""
        while True:
            contents, _, truncated, marker = self._walk(
                bucket, prefix, "", marker, 1000
            )
            for item in contents:
                keys.add(item["key"])
            if not truncated or not contents:
                break
        selected = sorted(
            k for k in keys
            if k.startswith(prefix) and (not key_marker or k > key_marker)
        )
        truncated = len(selected) > max_keys
        for key in selected[:max_keys]:
            cur = self.fc.get_entry(self._object_path(bucket, key))
            emitted_latest = False
            if cur is not None and not cur.get("is_directory"):
                emit(key, cur, True)
                emitted_latest = True
            for v in self._iter_versions(bucket, key):
                entry = self.fc.get_entry(v["FullPath"])
                if entry is not None:
                    emit(key, entry, not emitted_latest)
                    emitted_latest = True
        inner.append(
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
        )
        if truncated:
            inner.append(
                f"<NextKeyMarker>{escape(selected[max_keys - 1])}"
                f"</NextKeyMarker>"
            )
        return xml_response("ListVersionsResult", "".join(inner))

    def _delete_objects(self, req: Request, bucket: str) -> Response:
        self._require_bucket(bucket)
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise err("MalformedXML", "bad Delete document")
        deleted, errors = [], []
        vstate = self._versioning_state(bucket)
        for obj in root.iter():
            if not obj.tag.endswith("Object"):
                continue
            key_el = next(
                (c for c in obj if c.tag.endswith("Key")), None
            )
            if key_el is None or not key_el.text:
                continue
            k = key_el.text
            try:
                # same semantics as single-object DELETE: a versioned
                # bucket gets markers, not destruction
                self._versioned_delete(bucket, k, vstate)
                deleted.append(k)
            except Exception as e:
                errors.append((k, str(e)))
        inner = "".join(
            f"<Deleted><Key>{escape(k)}</Key></Deleted>" for k in deleted
        ) + "".join(
            f"<Error><Key>{escape(k)}</Key><Code>InternalError</Code>"
            f"<Message>{escape(msg)}</Message></Error>"
            for k, msg in errors
        )
        return xml_response("DeleteResult", inner)

    # --- listing ----------------------------------------------------------------
    def _list_objects(self, req: Request, bucket: str, q: dict) -> Response:
        self._require_bucket(bucket)
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        try:
            max_keys = min(int(q.get("max-keys", "1000") or 1000), 1000)
        except ValueError:
            raise err("InvalidArgument", "bad max-keys")
        marker = (
            q.get("continuation-token") or q.get("start-after", "")
            if v2
            else q.get("marker", "")
        )
        contents, prefixes, truncated, next_marker = self._walk(
            bucket, prefix, delimiter, marker, max_keys
        )
        inner = (
            f"<Name>{escape(bucket)}</Name><Prefix>{escape(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
        )
        if delimiter:
            inner += f"<Delimiter>{escape(delimiter)}</Delimiter>"
        for item in contents:
            inner += (
                "<Contents>"
                f"<Key>{escape(item['key'])}</Key>"
                f"<LastModified>{amz_time(item['mtime'])}</LastModified>"
                f"<ETag>\"{item['etag']}\"</ETag>"
                f"<Size>{item['size']}</Size>"
                "<StorageClass>STANDARD</StorageClass>"
                "</Contents>"
            )
        for p in prefixes:
            inner += f"<CommonPrefixes><Prefix>{escape(p)}</Prefix></CommonPrefixes>"
        if v2:
            inner += f"<KeyCount>{len(contents) + len(prefixes)}</KeyCount>"
            if truncated:
                inner += (
                    f"<NextContinuationToken>{escape(next_marker)}"
                    "</NextContinuationToken>"
                )
            return xml_response("ListBucketResult", inner)
        if truncated:
            inner += f"<NextMarker>{escape(next_marker)}</NextMarker>"
        return xml_response("ListBucketResult", inner)

    def _iter_bucket(self, bucket: str, prefix: str, marker: str, delimiter: str):
        """Depth-first walk yielding ("key", dict) / ("prefix", str) items in
        S3 lexicographic KEY order (`s3api_object_handlers_list.go`).

        Ordering subtlety: the filer sorts a directory's children by name,
        but S3 sorts by full key — so directory "a" (whose keys start "a/")
        must sort as "a/", AFTER file "a.txt" ('.' < '/'). Each directory
        page is therefore re-sorted by effective key before descending.
        When delimiter is "/", a qualifying subtree rolls up into a single
        prefix item without being descended."""
        base = self._bucket_path(bucket)

        def walk_dir(dir_rel: str):
            dir_abs = f"{base}/{dir_rel}".rstrip("/")
            entries: list[dict] = []
            last = ""
            while True:
                page = self.fc.list(dir_abs, last_file_name=last, limit=1024).get(
                    "Entries", []
                )
                entries.extend(page)
                if len(page) < 1024:
                    break
                last = page[-1]["FullPath"].rsplit("/", 1)[-1]

            def eff_key(e: dict) -> str:
                name = e["FullPath"].rsplit("/", 1)[-1]
                return name + "/" if e.get("IsDirectory") else name

            for e in sorted(entries, key=eff_key):
                name = e["FullPath"].rsplit("/", 1)[-1]
                rel = dir_rel + name
                if not dir_rel and name in (UPLOADS_FOLDER, VERSIONS_FOLDER):
                    continue
                if e.get("IsDirectory"):
                    sub = rel + "/"
                    # prune subtrees that can't contain the prefix, or whose
                    # entire key range precedes the marker
                    if prefix and not (
                        sub.startswith(prefix) or prefix.startswith(sub)
                    ):
                        continue
                    if marker and sub < marker and not marker.startswith(sub):
                        continue
                    if (
                        delimiter == "/"
                        and sub.startswith(prefix)
                        and len(sub) > len(prefix)
                    ):
                        yield ("prefix", sub)
                        continue
                    yield from walk_dir(sub)
                else:
                    if not rel.startswith(prefix):
                        continue
                    if marker and rel <= marker:
                        continue
                    yield (
                        "key",
                        {
                            "key": rel,
                            "size": e.get("FileSize", 0),
                            "mtime": e.get("Mtime", 0),
                            "etag": e.get("Md5", "") or "",
                        },
                    )

        yield from walk_dir("")

    def _walk(
        self, bucket: str, prefix: str, delimiter: str, marker: str, max_keys: int
    ) -> tuple[list[dict], list[str], bool, str]:
        """Apply delimiter grouping + max-keys truncation over the ordered
        key stream. Arbitrary delimiters group at the first occurrence after
        the prefix; "/" additionally benefits from subtree rollup in
        _iter_bucket."""
        contents: list[dict] = []
        prefixes: list[str] = []
        last_emitted = ""
        for kind, item in self._iter_bucket(bucket, prefix, marker, delimiter):
            if kind == "key" and delimiter and delimiter != "/":
                key = item["key"]
                idx = key.find(delimiter, len(prefix))
                if idx >= 0:
                    group = key[: idx + len(delimiter)]
                    if marker and (group <= marker or marker.startswith(group)):
                        continue
                    if prefixes and prefixes[-1] == group:
                        continue  # groups are contiguous in key order
                    kind, item = "prefix", group
            if len(contents) + len(prefixes) >= max_keys:
                return contents, prefixes, True, last_emitted
            if kind == "prefix":
                prefixes.append(item)  # type: ignore[arg-type]
                last_emitted = item  # type: ignore[assignment]
            else:
                contents.append(item)  # type: ignore[arg-type]
                last_emitted = item["key"]  # type: ignore[index]
        return contents, prefixes, False, last_emitted

    # --- multipart --------------------------------------------------------------
    def _uploads_dir(self, bucket: str, upload_id: str = "") -> str:
        d = f"{self._bucket_path(bucket)}/{UPLOADS_FOLDER}"
        return f"{d}/{upload_id}" if upload_id else d

    def _create_multipart(self, req: Request, bucket: str, key: str) -> Response:
        self._require_writable_bucket(bucket)
        upload_id = uuid.uuid4().hex
        staging = self._uploads_dir(bucket, upload_id)
        self.fc.mkdir(staging)
        manifest = {
            "key": key,
            "content_type": req.headers.get("Content-Type", ""),
            "meta": {
                k.lower()[len(AMZ_META_PREFIX):]: v
                for k, v in req.headers.items()
                if k.lower().startswith(AMZ_META_PREFIX)
            },
        }
        self.fc.put(f"{staging}/upload.json", json.dumps(manifest).encode())
        # register the live upload with the engine: part PUTs under this
        # id relay natively to the filer's staging area
        self._fl_upload_set(bucket, upload_id, True)
        inner = (
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
        )
        return xml_response("InitiateMultipartUploadResult", inner)

    def _get_upload_manifest(self, bucket: str, upload_id: str) -> dict:
        staging = self._uploads_dir(bucket, upload_id)
        status, _, body = self.fc.get(f"{staging}/upload.json")
        if status != 200:
            raise err("NoSuchUpload", upload_id)
        return json.loads(body)

    def _upload_part(self, req: Request, bucket: str, key: str, q: dict) -> Response:
        # quota read-only covers in-flight uploads too, or a pre-flip
        # uploadId could keep pouring parts into a frozen bucket
        self._require_writable_bucket(bucket)
        upload_id = q["uploadId"]
        self._get_upload_manifest(bucket, upload_id)
        try:
            part_num = int(q.get("partNumber", "0"))
        except ValueError:
            raise err("InvalidArgument", "bad partNumber")
        if not 1 <= part_num <= 10_000:
            raise err("InvalidArgument", f"partNumber {part_num} out of range")
        body = req.body
        if req.headers.get("x-amz-content-sha256", "").startswith("STREAMING-"):
            body = deframe_streaming_body(body)
        etag = hashlib.md5(body).hexdigest()
        staging = self._uploads_dir(bucket, upload_id)
        self.fc.put(f"{staging}/{part_num:05d}.part", body)
        return Response(b"", 200, {"ETag": f'"{etag}"'})

    def _complete_multipart(
        self, req: Request, bucket: str, key: str, q: dict
    ) -> Response:
        self._require_writable_bucket(bucket)
        upload_id = q["uploadId"]
        manifest = self._get_upload_manifest(bucket, upload_id)
        staging = self._uploads_dir(bucket, upload_id)
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise err("MalformedXML", "bad CompleteMultipartUpload document")
        parts: list[tuple[int, str]] = []
        for p in root.iter():
            if not p.tag.endswith("Part"):
                continue
            num = next((c.text for c in p if c.tag.endswith("PartNumber")), None)
            etag = next((c.text for c in p if c.tag.endswith("ETag")), "")
            if num is None:
                raise err("MalformedXML", "Part missing PartNumber")
            parts.append((int(num), (etag or "").strip('"')))
        if parts != sorted(parts, key=lambda x: x[0]) or len(parts) != len(
            {n for n, _ in parts}
        ):
            raise err("InvalidPartOrder", "parts must be ascending and unique")
        if not parts:
            raise err("MalformedXML", "no parts")

        # collect part entries; assemble by chunk concatenation
        # (`filer_multipart.go` CompleteMultipartUpload)
        chunks: list[dict] = []
        offset = 0
        md5s = b""
        part_entries: dict[int, dict] = {}
        any_inline = False
        for num, etag in parts:
            part_path = f"{staging}/{num:05d}.part"
            entry = self.fc.get_entry(part_path)
            if entry is None:
                raise err("InvalidPart", f"part {num} not uploaded")
            part_entries[num] = entry
            md5s += bytes.fromhex(entry["attributes"].get("md5", "") or "")
            if entry.get("content"):
                any_inline = True
        if any_inline:
            # small parts were inlined by the filer — materialize the whole
            # object and store it as a regular put (tiny total by construction)
            data = b"".join(
                self.fc.read(f"{staging}/{num:05d}.part") for num, _ in parts
            )
            self.fc.put(
                self._object_path(bucket, manifest["key"]),
                data,
                manifest.get("content_type", ""),
            )
            final_size = len(data)
        else:
            for num, etag in parts:
                entry = part_entries[num]
                part_size = entry["attributes"].get("file_size", 0)
                for c in sorted(entry.get("chunks", []), key=lambda c: c["offset"]):
                    # carry every chunk field (incl. cipher_key/is_compressed)
                    # — dropping them would leave ciphered parts unreadable
                    nc = dict(c)
                    nc["offset"] = offset + c["offset"]
                    nc["modified_ts_ns"] = time.time_ns()
                    chunks.append(nc)
                offset += part_size
            final_size = offset
            final_entry = {
                "full_path": self._object_path(bucket, manifest["key"]),
                "is_directory": False,
                "attributes": {
                    "mtime": time.time(),
                    "mode": 0o644,
                    "mime": manifest.get("content_type", ""),
                    "file_size": final_size,
                    "md5": "",
                },
                "chunks": chunks,
                "extended": {
                    f"{AMZ_META_PREFIX}{k}": v
                    for k, v in manifest.get("meta", {}).items()
                },
                "content": "",
            }
            self.fc.put_entry(final_entry["full_path"], final_entry)
            # drop the part entries WITHOUT reclaiming blobs (the final entry
            # owns them now): rewrite each part to chunkless, then delete
            for num, _ in parts:
                entry = part_entries[num]
                entry["chunks"] = []
                self.fc.put_entry(f"{staging}/{num:05d}.part", entry)
        if manifest.get("meta") and bucket not in self._fl_meta_dirty:
            self._fl_meta_dirty.add(bucket)
            try:
                self._bucket_ext_set(bucket, self._EXT_META_DIRTY, "1")
            except Exception:
                self._fl_push_bucket(bucket)
        multipart_etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        self._fl_upload_set(bucket, upload_id, False)
        self.fc.delete(staging, recursive=True)
        inner = (
            f"<Location>/{escape(bucket)}/{escape(manifest['key'])}</Location>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(manifest['key'])}</Key>"
            f"<ETag>\"{multipart_etag}\"</ETag>"
        )
        return xml_response("CompleteMultipartUploadResult", inner)

    def _abort_multipart(self, bucket: str, key: str, q: dict) -> Response:
        upload_id = q["uploadId"]
        self._get_upload_manifest(bucket, upload_id)
        self._fl_upload_set(bucket, upload_id, False)
        self.fc.delete(self._uploads_dir(bucket, upload_id), recursive=True)
        return Response(b"", 204)

    def _list_parts(self, bucket: str, key: str, q: dict) -> Response:
        # ListParts is a READ: it must keep working on quota-frozen buckets
        upload_id = q["uploadId"]
        manifest = self._get_upload_manifest(bucket, upload_id)
        staging = self._uploads_dir(bucket, upload_id)
        listing = self.fc.list(staging, limit=10_001)
        inner = (
            f"<Bucket>{escape(bucket)}</Bucket>"
            f"<Key>{escape(manifest['key'])}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
        )
        for e in listing.get("Entries", []):
            name = e["FullPath"].rsplit("/", 1)[-1]
            if not name.endswith(".part"):
                continue
            inner += (
                "<Part>"
                f"<PartNumber>{int(name[:-5])}</PartNumber>"
                f"<LastModified>{amz_time(e.get('Mtime', 0))}</LastModified>"
                f"<ETag>\"{e.get('Md5', '')}\"</ETag>"
                f"<Size>{e.get('FileSize', 0)}</Size>"
                "</Part>"
            )
        return xml_response("ListPartsResult", inner)

    def _list_multipart_uploads(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        listing = self.fc.list(self._uploads_dir(bucket), limit=1000)
        inner = f"<Bucket>{escape(bucket)}</Bucket>"
        for e in listing.get("Entries", []):
            if not e.get("IsDirectory"):
                continue
            upload_id = e["FullPath"].rsplit("/", 1)[-1]
            try:
                manifest = self._get_upload_manifest(bucket, upload_id)
            except S3ApiError:
                continue
            inner += (
                "<Upload>"
                f"<Key>{escape(manifest['key'])}</Key>"
                f"<UploadId>{upload_id}</UploadId>"
                f"<Initiated>{amz_time(e.get('Mtime', 0))}</Initiated>"
                "</Upload>"
            )
        return xml_response("ListMultipartUploadsResult", inner)

    # --- tagging ----------------------------------------------------------------
    def _get_tagging(self, path: str) -> Response:
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", path)
        tags = {
            k[len(TAG_PREFIX):]: v
            for k, v in (entry.get("extended") or {}).items()
            if k.startswith(TAG_PREFIX)
        }
        inner = "<TagSet>" + "".join(
            f"<Tag><Key>{escape(k)}</Key><Value>{escape(v)}</Value></Tag>"
            for k, v in sorted(tags.items())
        ) + "</TagSet>"
        return xml_response("Tagging", inner)

    def _put_tagging(self, path: str, body: bytes) -> Response:
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", path)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise err("MalformedXML", "bad Tagging document")
        tags = {}
        for tag_el in root.iter():
            if not tag_el.tag.endswith("}Tag") and tag_el.tag != "Tag":
                continue
            k = next((c.text for c in tag_el if c.tag.endswith("Key")), None)
            v = next((c.text for c in tag_el if c.tag.endswith("Value")), "")
            if k:
                tags[k] = v or ""
        ext = entry.setdefault("extended", {})
        for k in [k for k in ext if k.startswith(TAG_PREFIX)]:
            del ext[k]
        for k, v in tags.items():
            ext[f"{TAG_PREFIX}{k}"] = v
        self.fc.put_entry(path, entry)
        return Response(b"", 200)

    def _delete_tagging(self, path: str) -> Response:
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", path)
        ext = entry.get("extended") or {}
        entry["extended"] = {
            k: v for k, v in ext.items() if not k.startswith(TAG_PREFIX)
        }
        self.fc.put_entry(path, entry)
        return Response(b"", 204)
