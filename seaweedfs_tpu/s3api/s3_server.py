"""S3 REST gateway server.

Router and handlers for bucket CRUD, object CRUD + copy, ListObjects V1/V2,
batch delete, multipart uploads (assembled by filer chunk concatenation),
object/bucket tagging, ACL/versioning/lifecycle stubs, SigV4 auth with
per-identity actions, and a concurrency circuit breaker.

Reference: `weed/s3api/s3api_server.go:110-290` (router),
`s3api_object_handlers*.go`, `s3api_bucket_handlers.go`,
`filer_multipart.go` (chunk-concatenation completion).

Objects live in the filer under `/buckets/<bucket>/<key>`; multipart parts
stage under `/buckets/<bucket>/.uploads/<uploadId>/`.
"""

from __future__ import annotations

import hashlib
import json
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from xml.sax.saxutils import escape

from seaweedfs_tpu.filer.filer_client import FilerClient
from seaweedfs_tpu.server.httpd import HTTPService, Request, Response

from .auth import (
    ACTION_ADMIN,
    ACTION_LIST,
    ACTION_READ,
    ACTION_TAGGING,
    ACTION_WRITE,
    IdentityAccessManagement,
    S3ApiError,
    deframe_streaming_body,
    err,
)
from .circuit_breaker import CircuitBreaker

BUCKETS_DIR = "/buckets"
UPLOADS_FOLDER = ".uploads"
TAG_PREFIX = "X-Amz-Tagging-"
AMZ_META_PREFIX = "x-amz-meta-"
XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"


def xml_response(tag: str, inner: str, status: int = 200) -> Response:
    body = (
        f'<?xml version="1.0" encoding="UTF-8"?>'
        f'<{tag} xmlns="{XMLNS}">{inner}</{tag}>'
    ).encode()
    return Response(body, status, {"Content-Type": "application/xml"})


def error_response(e: S3ApiError, resource: str = "") -> Response:
    inner = (
        f"<Code>{e.code}</Code><Message>{escape(e.message)}</Message>"
        f"<Resource>{escape(resource)}</Resource>"
    )
    body = f'<?xml version="1.0" encoding="UTF-8"?><Error>{inner}</Error>'.encode()
    return Response(body, e.status, {"Content-Type": "application/xml"})


def amz_time(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class S3Server:
    def __init__(
        self,
        filer_url: str,
        host: str = "127.0.0.1",
        port: int = 8333,
        config: dict | None = None,
        circuit_breaker: CircuitBreaker | None = None,
    ) -> None:
        self.fc = FilerClient(filer_url)
        self.iam = IdentityAccessManagement()
        if config:
            self.iam.load_config(config)
        self.cb = circuit_breaker or CircuitBreaker()
        self.service = HTTPService(host, port)
        self.service.enable_metrics("s3", serve_route=False)
        self._iam_subscriber = None
        self._routes()

    def start(self) -> None:
        self.service.start()
        try:
            self.fc.mkdir(BUCKETS_DIR)
        except IOError:
            pass
        self._load_iam_from_filer()
        self._watch_iam()

    def stop(self) -> None:
        if self._iam_subscriber is not None:
            self._iam_subscriber.stop()
        self.service.stop()

    @property
    def url(self) -> str:
        return self.service.url

    # --- IAM config hot reload (`auth_credentials_subscribe.go`) ---------------
    IAM_CONFIG_PATH = "/etc/iam/identity.json"

    def _load_iam_from_filer(self) -> None:
        try:
            status, _, body = self.fc.get(self.IAM_CONFIG_PATH)
            if status == 200 and body:
                self.iam.load_json(body)
        except Exception:
            pass

    def _watch_iam(self) -> None:
        from seaweedfs_tpu.filer.meta_aggregator import MetaSubscriber

        def on_event(ev: dict) -> None:
            e = ev.get("new_entry")
            if e and e.get("full_path") == self.IAM_CONFIG_PATH:
                self._load_iam_from_filer()

        try:
            sub = MetaSubscriber(
                self.fc.filer_url, on_event, path_prefix="/etc/iam",
                since_ns=time.time_ns(),
            )
            sub.start()
            self._iam_subscriber = sub
        except Exception:
            self._iam_subscriber = None

    # --- routing ----------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service

        @svc.route("GET", r"/")
        def list_buckets(req: Request) -> Response:
            return self._dispatch(req, "", "")

        for method in ("GET", "PUT", "POST", "DELETE", "HEAD"):
            @svc.route(method, r"/([^/]+)")
            def bucket_level(req: Request) -> Response:
                return self._dispatch(req, req.match.group(1), "")

            @svc.route(method, r"/([^/]+)/(.*)")
            def object_level(req: Request) -> Response:
                return self._dispatch(
                    req, req.match.group(1), req.match.group(2)
                )

    def _query_pairs(self, req: Request) -> list[tuple[str, str]]:
        # S3 subresources are empty-valued query keys ("?uploads"); the
        # default Request.query drops them, so re-parse keeping blanks
        parsed = urllib.parse.urlparse(req.handler.path)
        return urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)

    def _dispatch(self, req: Request, bucket: str, key: str) -> Response:
        pairs = self._query_pairs(req)
        q = dict(pairs)
        resource = f"/{bucket}/{key}" if key else f"/{bucket}"
        try:
            body = req.body
            ident = self.iam.authenticate(
                req.method,
                urllib.parse.unquote(urllib.parse.urlparse(req.handler.path).path),
                pairs,
                dict(req.headers),
                body,
            )
            action = self._required_action(req.method, bucket, key, q)
            if not ident.can_do(action, bucket, key):
                raise err("AccessDenied", f"{ident.name} cannot {action} {resource}")
            # CopyObject also reads the source object — authorize both sides
            copy_source = req.headers.get("x-amz-copy-source")
            if req.method == "PUT" and key and copy_source:
                src = urllib.parse.unquote(copy_source).lstrip("/")
                src_bucket, _, src_key = src.partition("/")
                if not ident.can_do(ACTION_READ, src_bucket, src_key):
                    raise err(
                        "AccessDenied", f"{ident.name} cannot Read /{src}"
                    )
            with self.cb.limit(action, bucket):
                return self._handle(req, bucket, urllib.parse.unquote(key), q, ident)
        except S3ApiError as e:
            return error_response(e, resource)
        except Exception as e:  # any internal failure → S3 XML error surface
            return error_response(err("InternalError", str(e)), resource)

    @staticmethod
    def _required_action(method: str, bucket: str, key: str, q: dict) -> str:
        if "tagging" in q:
            return ACTION_TAGGING
        if not bucket:
            return ACTION_LIST  # ListBuckets (filtered per identity)
        if not key:
            if method in ("PUT", "DELETE"):
                return ACTION_ADMIN  # create/delete bucket
            if method == "POST":
                return ACTION_WRITE  # batch delete
            return ACTION_LIST
        if method in ("GET", "HEAD"):
            return ACTION_READ
        return ACTION_WRITE

    def _handle(
        self, req: Request, bucket: str, key: str, q: dict, ident
    ) -> Response:
        m = req.method
        if not bucket:
            return self._list_buckets(ident)
        if not key:
            if "tagging" in q:  # before bucket CRUD — a Tagging-only identity
                path = self._bucket_path(bucket)  # must never create/delete
                if m == "GET":
                    return self._get_tagging(path)
                if m == "PUT":
                    return self._put_tagging(path, req.body)
                if m == "DELETE":
                    return self._delete_tagging(path)
            if m == "PUT":
                return self._put_bucket(bucket)
            if m == "DELETE":
                return self._delete_bucket(bucket)
            if m == "HEAD":
                return self._head_bucket(bucket)
            if m == "POST" and "delete" in q:
                return self._delete_objects(req, bucket)
            if m == "GET":
                if "uploads" in q:
                    return self._list_multipart_uploads(bucket)
                if "location" in q:
                    return xml_response("LocationConstraint", "")
                if "versioning" in q:
                    return xml_response("VersioningConfiguration", "")
                if "lifecycle" in q:
                    raise err("NoSuchTagSet", "no lifecycle configuration")
                if "acl" in q:
                    return self._canned_acl(ident)
                return self._list_objects(req, bucket, q)
        else:
            if "uploadId" in q:
                if m == "PUT":
                    return self._upload_part(req, bucket, key, q)
                if m == "POST":
                    return self._complete_multipart(req, bucket, key, q)
                if m == "DELETE":
                    return self._abort_multipart(bucket, key, q)
                if m == "GET":
                    return self._list_parts(bucket, key, q)
            if m == "POST" and "uploads" in q:
                return self._create_multipart(req, bucket, key)
            if "tagging" in q:
                path = self._object_path(bucket, key)
                if m == "GET":
                    return self._get_tagging(path)
                if m == "PUT":
                    return self._put_tagging(path, req.body)
                if m == "DELETE":
                    return self._delete_tagging(path)
            if m == "PUT":
                if req.headers.get("x-amz-copy-source"):
                    return self._copy_object(req, bucket, key)
                return self._put_object(req, bucket, key)
            if m in ("GET", "HEAD"):
                return self._get_object(req, bucket, key, head=(m == "HEAD"))
            if m == "DELETE":
                return self._delete_object(bucket, key)
        raise err("NotImplemented", f"{m} {req.path}?{urllib.parse.urlencode(q)}")

    # --- path helpers -----------------------------------------------------------
    @staticmethod
    def _bucket_path(bucket: str) -> str:
        if not bucket or "/" in bucket or bucket.startswith("."):
            raise err("InvalidBucketName", bucket)
        return f"{BUCKETS_DIR}/{bucket}"

    def _object_path(self, bucket: str, key: str) -> str:
        return f"{self._bucket_path(bucket)}/{key}"

    def _require_bucket(self, bucket: str) -> dict:
        entry = self.fc.get_entry(self._bucket_path(bucket))
        if entry is None or not entry.get("is_directory"):
            raise err("NoSuchBucket", bucket)
        return entry

    # --- bucket handlers --------------------------------------------------------
    def _list_buckets(self, ident) -> Response:
        listing = self.fc.list(BUCKETS_DIR, limit=10_000)
        inner = ""
        for e in listing.get("Entries", []):
            if not e.get("IsDirectory"):
                continue
            name = e["FullPath"].rsplit("/", 1)[-1]
            if name.startswith("."):
                continue
            if not (
                ident.can_do(ACTION_LIST, name) or ident.can_do(ACTION_READ, name)
            ):
                continue
            inner += (
                f"<Bucket><Name>{escape(name)}</Name>"
                f"<CreationDate>{amz_time(e.get('Mtime', 0))}</CreationDate>"
                f"</Bucket>"
            )
        owner = (
            f"<Owner><ID>{escape(ident.account_id)}</ID>"
            f"<DisplayName>{escape(ident.name)}</DisplayName></Owner>"
        )
        return xml_response(
            "ListAllMyBucketsResult", f"{owner}<Buckets>{inner}</Buckets>"
        )

    def _put_bucket(self, bucket: str) -> Response:
        path = self._bucket_path(bucket)
        if self.fc.exists(path):
            raise err("BucketAlreadyExists", bucket)
        self.fc.mkdir(path)
        return Response(b"", 200, {"Location": f"/{bucket}"})

    def _delete_bucket(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        listing = self.fc.list(self._bucket_path(bucket), limit=2)
        entries = [
            e for e in listing.get("Entries", [])
            if e["FullPath"].rsplit("/", 1)[-1] != UPLOADS_FOLDER
        ]
        if entries:
            raise err("BucketNotEmpty", bucket)
        self.fc.delete(self._bucket_path(bucket), recursive=True)
        return Response(b"", 204)

    def _head_bucket(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        return Response(b"", 200)

    def _canned_acl(self, ident) -> Response:
        owner = (
            f"<Owner><ID>{escape(ident.account_id)}</ID></Owner>"
            "<AccessControlList><Grant><Grantee "
            'xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance" '
            'xsi:type="CanonicalUser">'
            f"<ID>{escape(ident.account_id)}</ID></Grantee>"
            "<Permission>FULL_CONTROL</Permission></Grant></AccessControlList>"
        )
        return xml_response("AccessControlPolicy", owner)

    # --- object handlers --------------------------------------------------------
    def _put_object(self, req: Request, bucket: str, key: str) -> Response:
        self._require_bucket(bucket)
        body = req.body
        sha_hdr = req.headers.get("x-amz-content-sha256", "")
        if sha_hdr.startswith("STREAMING-"):
            body = deframe_streaming_body(body)
        if key.endswith("/"):
            self.fc.mkdir(self._object_path(bucket, key.rstrip("/")))
            return Response(b"", 200, {"ETag": '"d41d8cd98f00b204e9800998ecf8427e"'})
        etag = hashlib.md5(body).hexdigest()
        content_type = req.headers.get("Content-Type", "")
        self.fc.put(self._object_path(bucket, key), body, content_type)
        # x-amz-meta-* headers persist as extended attributes
        meta = {
            k.lower()[len(AMZ_META_PREFIX):]: v
            for k, v in req.headers.items()
            if k.lower().startswith(AMZ_META_PREFIX)
        }
        if meta:
            path = self._object_path(bucket, key)
            entry = self.fc.get_entry(path)
            if entry is not None:
                entry.setdefault("extended", {}).update(
                    {f"{AMZ_META_PREFIX}{k}": v for k, v in meta.items()}
                )
                self.fc.put_entry(path, entry)
        return Response(b"", 200, {"ETag": f'"{etag}"'})

    def _copy_object(self, req: Request, bucket: str, key: str) -> Response:
        self._require_bucket(bucket)
        src = urllib.parse.unquote(req.headers["x-amz-copy-source"]).lstrip("/")
        src_bucket, _, src_key = src.partition("/")
        src_entry = self.fc.get_entry(self._object_path(src_bucket, src_key))
        if src_entry is None or src_entry.get("is_directory"):
            raise err("NoSuchKey", src)
        # replicate metadata + chunk list; the blobs are shared until the
        # source is deleted and reclaimed, so materialize the data instead
        data = self.fc.read(self._object_path(src_bucket, src_key))
        self.fc.put(
            self._object_path(bucket, key),
            data,
            src_entry.get("attributes", {}).get("mime", ""),
        )
        etag = hashlib.md5(data).hexdigest()
        inner = (
            f"<ETag>\"{etag}\"</ETag>"
            f"<LastModified>{amz_time(time.time())}</LastModified>"
        )
        return xml_response("CopyObjectResult", inner)

    def _get_object(
        self, req: Request, bucket: str, key: str, head: bool
    ) -> Response:
        self._require_bucket(bucket)
        path = self._object_path(bucket, key)
        entry = self.fc.get_entry(path)
        if entry is None or entry.get("is_directory"):
            raise err("NoSuchKey", key)
        attrs = entry.get("attributes", {})
        headers = {
            "ETag": f'"{attrs.get("md5") or ""}"',
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(attrs.get("mtime", 0))
            ),
            "Accept-Ranges": "bytes",
        }
        if attrs.get("mime"):
            headers["Content-Type"] = attrs["mime"]
        for k, v in (entry.get("extended") or {}).items():
            if k.startswith(AMZ_META_PREFIX):
                headers[k] = v
        size = attrs.get("file_size", 0) or sum(
            c["size"] for c in entry.get("chunks", [])
        )
        if entry.get("content"):
            size = len(entry["content"]) // 2  # hex-encoded
        if head:
            headers["Content-Length"] = str(size)
            return Response(b"", 200, headers)
        status, fh, body = self.fc.get(path, req.headers.get("Range"))
        if status >= 400:
            raise err("NoSuchKey", key)
        if "Content-Range" in fh:
            headers["Content-Range"] = fh["Content-Range"]
        return Response(body, status, headers)

    def _delete_object(self, bucket: str, key: str) -> Response:
        self._require_bucket(bucket)
        self.fc.delete(self._object_path(bucket, key), recursive=True)
        return Response(b"", 204)

    def _delete_objects(self, req: Request, bucket: str) -> Response:
        self._require_bucket(bucket)
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise err("MalformedXML", "bad Delete document")
        deleted, errors = [], []
        for obj in root.iter():
            if not obj.tag.endswith("Object"):
                continue
            key_el = next(
                (c for c in obj if c.tag.endswith("Key")), None
            )
            if key_el is None or not key_el.text:
                continue
            k = key_el.text
            try:
                self.fc.delete(self._object_path(bucket, k), recursive=True)
                deleted.append(k)
            except Exception as e:
                errors.append((k, str(e)))
        inner = "".join(
            f"<Deleted><Key>{escape(k)}</Key></Deleted>" for k in deleted
        ) + "".join(
            f"<Error><Key>{escape(k)}</Key><Code>InternalError</Code>"
            f"<Message>{escape(msg)}</Message></Error>"
            for k, msg in errors
        )
        return xml_response("DeleteResult", inner)

    # --- listing ----------------------------------------------------------------
    def _list_objects(self, req: Request, bucket: str, q: dict) -> Response:
        self._require_bucket(bucket)
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        try:
            max_keys = min(int(q.get("max-keys", "1000") or 1000), 1000)
        except ValueError:
            raise err("InvalidArgument", "bad max-keys")
        marker = (
            q.get("continuation-token") or q.get("start-after", "")
            if v2
            else q.get("marker", "")
        )
        contents, prefixes, truncated, next_marker = self._walk(
            bucket, prefix, delimiter, marker, max_keys
        )
        inner = (
            f"<Name>{escape(bucket)}</Name><Prefix>{escape(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
        )
        if delimiter:
            inner += f"<Delimiter>{escape(delimiter)}</Delimiter>"
        for item in contents:
            inner += (
                "<Contents>"
                f"<Key>{escape(item['key'])}</Key>"
                f"<LastModified>{amz_time(item['mtime'])}</LastModified>"
                f"<ETag>\"{item['etag']}\"</ETag>"
                f"<Size>{item['size']}</Size>"
                "<StorageClass>STANDARD</StorageClass>"
                "</Contents>"
            )
        for p in prefixes:
            inner += f"<CommonPrefixes><Prefix>{escape(p)}</Prefix></CommonPrefixes>"
        if v2:
            inner += f"<KeyCount>{len(contents) + len(prefixes)}</KeyCount>"
            if truncated:
                inner += (
                    f"<NextContinuationToken>{escape(next_marker)}"
                    "</NextContinuationToken>"
                )
            return xml_response("ListBucketResult", inner)
        if truncated:
            inner += f"<NextMarker>{escape(next_marker)}</NextMarker>"
        return xml_response("ListBucketResult", inner)

    def _iter_bucket(self, bucket: str, prefix: str, marker: str, delimiter: str):
        """Depth-first walk yielding ("key", dict) / ("prefix", str) items in
        S3 lexicographic KEY order (`s3api_object_handlers_list.go`).

        Ordering subtlety: the filer sorts a directory's children by name,
        but S3 sorts by full key — so directory "a" (whose keys start "a/")
        must sort as "a/", AFTER file "a.txt" ('.' < '/'). Each directory
        page is therefore re-sorted by effective key before descending.
        When delimiter is "/", a qualifying subtree rolls up into a single
        prefix item without being descended."""
        base = self._bucket_path(bucket)

        def walk_dir(dir_rel: str):
            dir_abs = f"{base}/{dir_rel}".rstrip("/")
            entries: list[dict] = []
            last = ""
            while True:
                page = self.fc.list(dir_abs, last_file_name=last, limit=1024).get(
                    "Entries", []
                )
                entries.extend(page)
                if len(page) < 1024:
                    break
                last = page[-1]["FullPath"].rsplit("/", 1)[-1]

            def eff_key(e: dict) -> str:
                name = e["FullPath"].rsplit("/", 1)[-1]
                return name + "/" if e.get("IsDirectory") else name

            for e in sorted(entries, key=eff_key):
                name = e["FullPath"].rsplit("/", 1)[-1]
                rel = dir_rel + name
                if not dir_rel and name == UPLOADS_FOLDER:
                    continue
                if e.get("IsDirectory"):
                    sub = rel + "/"
                    # prune subtrees that can't contain the prefix, or whose
                    # entire key range precedes the marker
                    if prefix and not (
                        sub.startswith(prefix) or prefix.startswith(sub)
                    ):
                        continue
                    if marker and sub < marker and not marker.startswith(sub):
                        continue
                    if (
                        delimiter == "/"
                        and sub.startswith(prefix)
                        and len(sub) > len(prefix)
                    ):
                        yield ("prefix", sub)
                        continue
                    yield from walk_dir(sub)
                else:
                    if not rel.startswith(prefix):
                        continue
                    if marker and rel <= marker:
                        continue
                    yield (
                        "key",
                        {
                            "key": rel,
                            "size": e.get("FileSize", 0),
                            "mtime": e.get("Mtime", 0),
                            "etag": e.get("Md5", "") or "",
                        },
                    )

        yield from walk_dir("")

    def _walk(
        self, bucket: str, prefix: str, delimiter: str, marker: str, max_keys: int
    ) -> tuple[list[dict], list[str], bool, str]:
        """Apply delimiter grouping + max-keys truncation over the ordered
        key stream. Arbitrary delimiters group at the first occurrence after
        the prefix; "/" additionally benefits from subtree rollup in
        _iter_bucket."""
        contents: list[dict] = []
        prefixes: list[str] = []
        last_emitted = ""
        for kind, item in self._iter_bucket(bucket, prefix, marker, delimiter):
            if kind == "key" and delimiter and delimiter != "/":
                key = item["key"]
                idx = key.find(delimiter, len(prefix))
                if idx >= 0:
                    group = key[: idx + len(delimiter)]
                    if marker and (group <= marker or marker.startswith(group)):
                        continue
                    if prefixes and prefixes[-1] == group:
                        continue  # groups are contiguous in key order
                    kind, item = "prefix", group
            if len(contents) + len(prefixes) >= max_keys:
                return contents, prefixes, True, last_emitted
            if kind == "prefix":
                prefixes.append(item)  # type: ignore[arg-type]
                last_emitted = item  # type: ignore[assignment]
            else:
                contents.append(item)  # type: ignore[arg-type]
                last_emitted = item["key"]  # type: ignore[index]
        return contents, prefixes, False, last_emitted

    # --- multipart --------------------------------------------------------------
    def _uploads_dir(self, bucket: str, upload_id: str = "") -> str:
        d = f"{self._bucket_path(bucket)}/{UPLOADS_FOLDER}"
        return f"{d}/{upload_id}" if upload_id else d

    def _create_multipart(self, req: Request, bucket: str, key: str) -> Response:
        self._require_bucket(bucket)
        upload_id = uuid.uuid4().hex
        staging = self._uploads_dir(bucket, upload_id)
        self.fc.mkdir(staging)
        manifest = {
            "key": key,
            "content_type": req.headers.get("Content-Type", ""),
            "meta": {
                k.lower()[len(AMZ_META_PREFIX):]: v
                for k, v in req.headers.items()
                if k.lower().startswith(AMZ_META_PREFIX)
            },
        }
        self.fc.put(f"{staging}/upload.json", json.dumps(manifest).encode())
        inner = (
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
        )
        return xml_response("InitiateMultipartUploadResult", inner)

    def _get_upload_manifest(self, bucket: str, upload_id: str) -> dict:
        staging = self._uploads_dir(bucket, upload_id)
        status, _, body = self.fc.get(f"{staging}/upload.json")
        if status != 200:
            raise err("NoSuchUpload", upload_id)
        return json.loads(body)

    def _upload_part(self, req: Request, bucket: str, key: str, q: dict) -> Response:
        upload_id = q["uploadId"]
        self._get_upload_manifest(bucket, upload_id)
        try:
            part_num = int(q.get("partNumber", "0"))
        except ValueError:
            raise err("InvalidArgument", "bad partNumber")
        if not 1 <= part_num <= 10_000:
            raise err("InvalidArgument", f"partNumber {part_num} out of range")
        body = req.body
        if req.headers.get("x-amz-content-sha256", "").startswith("STREAMING-"):
            body = deframe_streaming_body(body)
        etag = hashlib.md5(body).hexdigest()
        staging = self._uploads_dir(bucket, upload_id)
        self.fc.put(f"{staging}/{part_num:05d}.part", body)
        return Response(b"", 200, {"ETag": f'"{etag}"'})

    def _complete_multipart(
        self, req: Request, bucket: str, key: str, q: dict
    ) -> Response:
        upload_id = q["uploadId"]
        manifest = self._get_upload_manifest(bucket, upload_id)
        staging = self._uploads_dir(bucket, upload_id)
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError:
            raise err("MalformedXML", "bad CompleteMultipartUpload document")
        parts: list[tuple[int, str]] = []
        for p in root.iter():
            if not p.tag.endswith("Part"):
                continue
            num = next((c.text for c in p if c.tag.endswith("PartNumber")), None)
            etag = next((c.text for c in p if c.tag.endswith("ETag")), "")
            if num is None:
                raise err("MalformedXML", "Part missing PartNumber")
            parts.append((int(num), (etag or "").strip('"')))
        if parts != sorted(parts, key=lambda x: x[0]) or len(parts) != len(
            {n for n, _ in parts}
        ):
            raise err("InvalidPartOrder", "parts must be ascending and unique")
        if not parts:
            raise err("MalformedXML", "no parts")

        # collect part entries; assemble by chunk concatenation
        # (`filer_multipart.go` CompleteMultipartUpload)
        chunks: list[dict] = []
        offset = 0
        md5s = b""
        part_entries: dict[int, dict] = {}
        any_inline = False
        for num, etag in parts:
            part_path = f"{staging}/{num:05d}.part"
            entry = self.fc.get_entry(part_path)
            if entry is None:
                raise err("InvalidPart", f"part {num} not uploaded")
            part_entries[num] = entry
            md5s += bytes.fromhex(entry["attributes"].get("md5", "") or "")
            if entry.get("content"):
                any_inline = True
        if any_inline:
            # small parts were inlined by the filer — materialize the whole
            # object and store it as a regular put (tiny total by construction)
            data = b"".join(
                self.fc.read(f"{staging}/{num:05d}.part") for num, _ in parts
            )
            self.fc.put(
                self._object_path(bucket, manifest["key"]),
                data,
                manifest.get("content_type", ""),
            )
            final_size = len(data)
        else:
            for num, etag in parts:
                entry = part_entries[num]
                part_size = entry["attributes"].get("file_size", 0)
                for c in sorted(entry.get("chunks", []), key=lambda c: c["offset"]):
                    # carry every chunk field (incl. cipher_key/is_compressed)
                    # — dropping them would leave ciphered parts unreadable
                    nc = dict(c)
                    nc["offset"] = offset + c["offset"]
                    nc["modified_ts_ns"] = time.time_ns()
                    chunks.append(nc)
                offset += part_size
            final_size = offset
            final_entry = {
                "full_path": self._object_path(bucket, manifest["key"]),
                "is_directory": False,
                "attributes": {
                    "mtime": time.time(),
                    "mode": 0o644,
                    "mime": manifest.get("content_type", ""),
                    "file_size": final_size,
                    "md5": "",
                },
                "chunks": chunks,
                "extended": {
                    f"{AMZ_META_PREFIX}{k}": v
                    for k, v in manifest.get("meta", {}).items()
                },
                "content": "",
            }
            self.fc.put_entry(final_entry["full_path"], final_entry)
            # drop the part entries WITHOUT reclaiming blobs (the final entry
            # owns them now): rewrite each part to chunkless, then delete
            for num, _ in parts:
                entry = part_entries[num]
                entry["chunks"] = []
                self.fc.put_entry(f"{staging}/{num:05d}.part", entry)
        multipart_etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        self.fc.delete(staging, recursive=True)
        inner = (
            f"<Location>/{escape(bucket)}/{escape(manifest['key'])}</Location>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(manifest['key'])}</Key>"
            f"<ETag>\"{multipart_etag}\"</ETag>"
        )
        return xml_response("CompleteMultipartUploadResult", inner)

    def _abort_multipart(self, bucket: str, key: str, q: dict) -> Response:
        upload_id = q["uploadId"]
        self._get_upload_manifest(bucket, upload_id)
        self.fc.delete(self._uploads_dir(bucket, upload_id), recursive=True)
        return Response(b"", 204)

    def _list_parts(self, bucket: str, key: str, q: dict) -> Response:
        upload_id = q["uploadId"]
        manifest = self._get_upload_manifest(bucket, upload_id)
        staging = self._uploads_dir(bucket, upload_id)
        listing = self.fc.list(staging, limit=10_001)
        inner = (
            f"<Bucket>{escape(bucket)}</Bucket>"
            f"<Key>{escape(manifest['key'])}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
        )
        for e in listing.get("Entries", []):
            name = e["FullPath"].rsplit("/", 1)[-1]
            if not name.endswith(".part"):
                continue
            inner += (
                "<Part>"
                f"<PartNumber>{int(name[:-5])}</PartNumber>"
                f"<LastModified>{amz_time(e.get('Mtime', 0))}</LastModified>"
                f"<ETag>\"{e.get('Md5', '')}\"</ETag>"
                f"<Size>{e.get('FileSize', 0)}</Size>"
                "</Part>"
            )
        return xml_response("ListPartsResult", inner)

    def _list_multipart_uploads(self, bucket: str) -> Response:
        self._require_bucket(bucket)
        listing = self.fc.list(self._uploads_dir(bucket), limit=1000)
        inner = f"<Bucket>{escape(bucket)}</Bucket>"
        for e in listing.get("Entries", []):
            if not e.get("IsDirectory"):
                continue
            upload_id = e["FullPath"].rsplit("/", 1)[-1]
            try:
                manifest = self._get_upload_manifest(bucket, upload_id)
            except S3ApiError:
                continue
            inner += (
                "<Upload>"
                f"<Key>{escape(manifest['key'])}</Key>"
                f"<UploadId>{upload_id}</UploadId>"
                f"<Initiated>{amz_time(e.get('Mtime', 0))}</Initiated>"
                "</Upload>"
            )
        return xml_response("ListMultipartUploadsResult", inner)

    # --- tagging ----------------------------------------------------------------
    def _get_tagging(self, path: str) -> Response:
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", path)
        tags = {
            k[len(TAG_PREFIX):]: v
            for k, v in (entry.get("extended") or {}).items()
            if k.startswith(TAG_PREFIX)
        }
        inner = "<TagSet>" + "".join(
            f"<Tag><Key>{escape(k)}</Key><Value>{escape(v)}</Value></Tag>"
            for k, v in sorted(tags.items())
        ) + "</TagSet>"
        return xml_response("Tagging", inner)

    def _put_tagging(self, path: str, body: bytes) -> Response:
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", path)
        try:
            root = ET.fromstring(body)
        except ET.ParseError:
            raise err("MalformedXML", "bad Tagging document")
        tags = {}
        for tag_el in root.iter():
            if not tag_el.tag.endswith("}Tag") and tag_el.tag != "Tag":
                continue
            k = next((c.text for c in tag_el if c.tag.endswith("Key")), None)
            v = next((c.text for c in tag_el if c.tag.endswith("Value")), "")
            if k:
                tags[k] = v or ""
        ext = entry.setdefault("extended", {})
        for k in [k for k in ext if k.startswith(TAG_PREFIX)]:
            del ext[k]
        for k, v in tags.items():
            ext[f"{TAG_PREFIX}{k}"] = v
        self.fc.put_entry(path, entry)
        return Response(b"", 200)

    def _delete_tagging(self, path: str) -> Response:
        entry = self.fc.get_entry(path)
        if entry is None:
            raise err("NoSuchKey", path)
        ext = entry.get("extended") or {}
        entry["extended"] = {
            k: v for k, v in ext.items() if not k.startswith(TAG_PREFIX)
        }
        self.fc.put_entry(path, entry)
        return Response(b"", 204)
