"""Cross-cluster replication: event-driven sinks + bidirectional filer.sync.

Behavioral port of `weed/replication/replicator.go:24` (+ `sink/`,
`source/`) and `weed/command/filer_sync.go:119-385`:

  - `ReplicationSink` SPI — apply create/update/delete events somewhere
  - `FilerSink` — another cluster's filer (content is re-uploaded through
    the target cluster's own assign/upload path, not fid-copied)
  - `LocalSink` — materialize the namespace into a local directory
    (`replication/sink/localsink`)
  - `Replicator` — event dispatcher (create/update/delete/rename semantics)
  - `FilerSyncer` — one direction of `weed filer.sync`: tail the source
    filer's metadata stream and replay onto the sink with the source's
    signature attached; events that already carry the target's signature
    are skipped (loop prevention for active-active pairs)
"""

from __future__ import annotations

import os
import time

from seaweedfs_tpu.filer.filer_client import FilerClient
from seaweedfs_tpu.filer.filer_notify import SYSTEM_LOG_DIR


class ReplicationSink:
    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        raise NotImplementedError

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        raise NotImplementedError

    def delete_entry(self, path: str, is_directory: bool) -> None:
        raise NotImplementedError

    @property
    def signature(self) -> int:
        """Signature attached to writes this sink performs (0 = none)."""
        return 0


class LocalSink(ReplicationSink):
    """Mirror the filer namespace into a directory (`localsink/local_sink.go`)."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, path: str) -> str:
        return os.path.join(self.root, path.lstrip("/"))

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        p = self._path(path)
        if entry.get("is_directory"):
            os.makedirs(p, exist_ok=True)
            return
        os.makedirs(os.path.dirname(p) or "/", exist_ok=True)
        with open(p, "wb") as f:
            f.write(data or b"")

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        p = self._path(path)
        try:
            if is_directory:
                import shutil

                shutil.rmtree(p, ignore_errors=True)
            else:
                os.remove(p)
        except FileNotFoundError:
            pass


class FilerSink(ReplicationSink):
    """Replicate into another cluster's filer over HTTP
    (`replication/sink/filersink/` — content flows through the target
    cluster's own volume assignment, never cross-cluster fids)."""

    def __init__(self, filer_url: str, extra_signature: int = 0) -> None:
        self.client = FilerClient(filer_url)
        self.extra_signature = extra_signature

    def _sig_query(self) -> dict:
        if not self.extra_signature:
            return {}
        return {"signatures": str(self.extra_signature)}

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        if entry.get("is_directory"):
            q = dict(self._sig_query())
            q["mkdir"] = "true"
            self.client.put(path.rstrip("/"), b"", query=q)
            return
        mime = (entry.get("attributes") or {}).get("mime", "")
        self.client.put(path, data or b"", content_type=mime,
                        query=self._sig_query())

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        q = {"recursive": "true"} if is_directory else {}
        q.update(self._sig_query())
        from seaweedfs_tpu.server.httpd import http_request

        url = self.client._u(path, q)
        http_request("DELETE", url, timeout=30)

    @property
    def signature(self) -> int:
        return self.extra_signature


class Replicator:
    """Apply one metadata event to a sink (`replicator.go:24` Replicate):
    old+new same path → update; old+new different path → delete+create
    (rename); only new → create; only old → delete."""

    def __init__(self, sink: ReplicationSink,
                 read_content=None) -> None:
        self.sink = sink
        self._read = read_content or (lambda path, entry: None)

    def replicate(self, event: dict) -> None:
        old, new = event.get("old_entry"), event.get("new_entry")
        if new is not None:
            new_path = new["full_path"]
            if new_path.startswith(SYSTEM_LOG_DIR):
                return
            # read BEFORE mutating the sink: a transient source failure
            # must leave the sink untouched (drain loops like
            # filer.replicate advance past raised events, so partial
            # application would be permanent)
            data = None
            superseded = False
            if not new.get("is_directory"):
                try:
                    data = self._read(new_path, new)
                except IOError as e:
                    # status suffix, not substring: paths may contain "404"
                    if str(e).rstrip().endswith("404"):
                        # replaying history: this create was superseded
                        # (renamed/deleted later at the source); later
                        # events converge the sink
                        superseded = True
                    else:
                        raise  # transient failure: caller retries
            if old is not None and old["full_path"] != new_path:
                # rename: the old key must go even when the new content is
                # superseded, or a replayed rename leaves it stale forever
                self.sink.delete_entry(
                    old["full_path"], bool(old.get("is_directory"))
                )
            if superseded:
                return
            if old is not None and old["full_path"] == new_path:
                self.sink.update_entry(new_path, new, data)
            else:
                self.sink.create_entry(new_path, new, data)
        elif old is not None:
            old_path = old["full_path"]
            if old_path.startswith(SYSTEM_LOG_DIR):
                return
            self.sink.delete_entry(old_path, bool(old.get("is_directory")))


class FilerSyncer:
    """One direction of `weed filer.sync` (`filer_sync.go:119-385`):
    tail source metadata, replay onto target with the source signature,
    skip events the target has already seen (its signature is in the
    event's signature list)."""

    def __init__(self, source_url: str, target_url: str) -> None:
        self.source = FilerClient(source_url)
        self.source_url = source_url
        self.target_url = target_url
        import json as _json

        from seaweedfs_tpu.server.httpd import http_request

        def info(url):
            status, _, body = http_request("GET", url + "/__meta__/info",
                                           timeout=10)
            return _json.loads(body)

        self.source_signature = info(source_url.rstrip("/"))["signature"]
        self.target_signature = info(target_url.rstrip("/"))["signature"]
        sink = FilerSink(target_url, extra_signature=self.source_signature)
        self.replicator = Replicator(sink, read_content=self._read_source)
        self.cursor_ns = time.time_ns()

    def _read_source(self, path: str, entry: dict) -> bytes:
        return self.source.read(path)

    def run_once(self, wait: float = 0.0) -> int:
        """Fetch + replay one batch; returns number of applied events."""
        import json as _json

        from seaweedfs_tpu.server.httpd import http_request

        url = (
            f"{self.source_url.rstrip('/')}/__meta__/events"
            f"?since_ns={self.cursor_ns}&wait={wait}"
        )
        status, _, body = http_request("GET", url, timeout=wait + 30)
        if status != 200:
            raise IOError(f"subscribe {self.source_url} -> {status}")
        out = _json.loads(body)
        applied = 0
        for ev in out["events"]:
            # loop prevention: this event already passed through the target
            if self.target_signature in ev.get("signatures", []):
                continue
            self.replicator.replicate(ev)
            applied += 1
        self.cursor_ns = out["next_ts_ns"]
        return applied

    def run_forever(self, poll_interval: float = 1.0, stop_event=None) -> None:
        while stop_event is None or not stop_event.is_set():
            try:
                n = self.run_once(wait=poll_interval)
                if n == 0 and poll_interval > 0:
                    time.sleep(min(poll_interval, 0.2))
            except Exception:
                time.sleep(poll_interval)


class S3Sink(ReplicationSink):
    """Replicate the namespace into any S3 endpoint — AWS or this
    framework's own gateway (`weed/replication/sink/s3sink/s3_sink.go`).
    Filer path /a/b.txt lands at s3://bucket/<prefix>/a/b.txt. Directories
    become zero-byte "dir/" marker objects, the convention S3 browsers use."""

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        access_key: str = "",
        secret_key: str = "",
        prefix: str = "",
        create_bucket: bool = True,
    ) -> None:
        from seaweedfs_tpu.s3api.sigv4_client import S3Client, S3Error

        self._S3Error = S3Error
        self.client = S3Client(endpoint, access_key, secret_key)
        self.bucket = bucket
        self.prefix = prefix.strip("/")
        if create_bucket:
            try:
                self.client.create_bucket(bucket)
            except S3Error:
                pass  # exists / owned

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.prefix}/{key}" if self.prefix else key

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        if entry.get("is_directory"):
            self.client.put_object(self.bucket, self._key(path) + "/", b"")
            return
        mime = (entry.get("attributes") or {}).get("mime", "")
        self.client.put_object(
            self.bucket, self._key(path), data or b"", content_type=mime
        )

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        if is_directory:
            # drop the subtree: marker + every object under the prefix,
            # paging until the listing is exhausted
            token = ""
            while True:
                listing = self.client.list_objects(
                    self.bucket, prefix=self._key(path) + "/",
                    continuation_token=token,
                )
                keys = [c["key"] for c in listing["contents"]]
                if keys:
                    self.client.delete_objects(self.bucket, keys)
                token = listing.get("next_token") or ""
                if not listing.get("is_truncated") or (not token and not keys):
                    break
        try:
            self.client.delete_object(self.bucket, self._key(path))
        except self._S3Error:
            pass
