"""Cloud replication sinks speaking the providers' REST protocols natively.

The reference wraps vendor SDKs (`weed/replication/sink/azuresink/azure_sink.go`,
`gcssink/gcs_sink.go`, `b2sink/b2_sink.go`); none of those SDKs exist in this
image, and none are needed — each service is an HTTP API:

  - `AzureSink`  — Azure Blob Storage REST with SharedKey request signing
    (HMAC-SHA256 over the canonicalized request, per the Storage Services
    auth spec). Files are AppendBlobs created then appended in ≤4MB blocks,
    matching `azure_sink.go:100-140`.
  - `GcsSink`    — Google Cloud Storage JSON API (`upload/storage/v1` media
    uploads, `storage/v1` deletes) with Bearer-token auth; the token comes
    from a pluggable provider, and `service_account_token_provider()`
    implements the RS256 JWT OAuth2 grant the SDK performs internally.
  - `B2Sink`     — Backblaze B2 native API: b2_authorize_account →
    b2_get_upload_url → upload with X-Bz-Content-Sha1, delete via
    file-version enumeration, with 401 re-auth, per `b2_sink.go`.

Every endpoint is overridable so contract tests drive the full client
against in-process fakes (`tests/test_cloud_sinks.py`).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse

from seaweedfs_tpu.server.httpd import http_request

from . import ReplicationSink

_APPEND_BLOCK = 4 * 1024 * 1024  # Azure AppendBlock limit per call


def _clean_key(path: str, is_directory: bool = False) -> str:
    key = path.lstrip("/")
    return key + "/" if is_directory else key


class CloudSinkError(IOError):
    def __init__(self, status: int, body: bytes) -> None:
        super().__init__(f"{status}: {body[:200]!r}")
        self.status = status


# ---------------------------------------------------------------------------
# Azure Blob Storage
# ---------------------------------------------------------------------------


def azure_sharedkey_signature(
    account: str,
    key_b64: str,
    method: str,
    headers: dict[str, str],
    path: str,
    query: dict[str, str],
) -> str:
    """SharedKey signature per the Azure Storage authentication spec:
    string-to-sign = VERB + standard headers + canonicalized x-ms-*
    headers + canonicalized resource, HMAC-SHA256 with the base64 account
    key, emitted as `SharedKey <account>:<base64 digest>`."""
    h = {k.lower(): v.strip() for k, v in headers.items()}
    # API versions >= 2015-02-21 sign a zero Content-Length as empty string
    # even though the wire carries "0"
    content_length = h.get("content-length", "")
    if content_length == "0":
        content_length = ""
    std = [
        h.get("content-encoding", ""),
        h.get("content-language", ""),
        content_length,
        h.get("content-md5", ""),
        h.get("content-type", ""),
        "",  # Date is always empty: x-ms-date is authoritative
        h.get("if-modified-since", ""),
        h.get("if-match", ""),
        h.get("if-none-match", ""),
        h.get("if-unmodified-since", ""),
        h.get("range", ""),
    ]
    canon_headers = "".join(
        f"{k}:{h[k]}\n" for k in sorted(h) if k.startswith("x-ms-")
    )
    canon_resource = f"/{account}{path}"
    for k in sorted(query):
        canon_resource += f"\n{k.lower()}:{query[k]}"
    to_sign = (
        method + "\n" + "\n".join(std) + "\n" + canon_headers + canon_resource
    )
    digest = hmac.new(
        base64.b64decode(key_b64), to_sign.encode(), hashlib.sha256
    ).digest()
    return f"SharedKey {account}:{base64.b64encode(digest).decode()}"


class AzureSink(ReplicationSink):
    """Replicate into an Azure Blob container (`azure_sink.go`). Blobs are
    AppendBlobs — created once, then appended in ≤4MB blocks — so large
    chunked files stream without buffering the whole object."""

    def __init__(
        self,
        account: str,
        account_key_b64: str,
        container: str,
        endpoint: str | None = None,
    ) -> None:
        self.account = account
        self.key = account_key_b64
        self.container = container
        self.endpoint = (
            endpoint or f"https://{account}.blob.core.windows.net"
        ).rstrip("/")

    def _request(
        self,
        method: str,
        blob: str,
        query: dict[str, str] | None = None,
        body: bytes = b"",
        extra_headers: dict[str, str] | None = None,
    ) -> tuple[int, dict, bytes]:
        from email.utils import formatdate  # RFC1123, locale-independent

        query = dict(query or {})
        path = f"/{self.container}/{urllib.parse.quote(blob)}"
        headers = {
            "x-ms-date": formatdate(usegmt=True),
            "x-ms-version": "2021-08-06",
        }
        if body or method == "PUT":
            headers["content-length"] = str(len(body))
            # explicit: urllib would otherwise inject an unsigned default
            headers["content-type"] = "application/octet-stream"
        headers.update(extra_headers or {})
        headers["Authorization"] = azure_sharedkey_signature(
            self.account, self.key, method, headers, path, query
        )
        url = self.endpoint + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        # PUT always ships a body (possibly empty) so the wire carries the
        # same content-length the signature covered
        wire_body = body if (body or method == "PUT") else None
        # data-bearing sink pushes may carry whole chunks: a longer,
        # still-finite budget (the audit rule: explicit or default,
        # never unbounded)
        return http_request(method, url, wire_body, headers, timeout=120)

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        if entry.get("is_directory"):
            return  # containers are flat; directories are implicit
        blob = _clean_key(path)
        status, _, body = self._request(
            "PUT", blob, extra_headers={"x-ms-blob-type": "AppendBlob"}
        )
        if status >= 400:
            raise CloudSinkError(status, body)
        data = data or b""
        for off in range(0, len(data), _APPEND_BLOCK):
            block = data[off : off + _APPEND_BLOCK]
            status, _, body = self._request(
                "PUT", blob, query={"comp": "appendblock"}, body=block
            )
            if status >= 400:
                raise CloudSinkError(status, body)

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        blob = _clean_key(path, is_directory)
        status, _, body = self._request(
            "DELETE", blob, extra_headers={"x-ms-delete-snapshots": "include"}
        )
        if status >= 400 and status != 404:
            raise CloudSinkError(status, body)


# ---------------------------------------------------------------------------
# Google Cloud Storage
# ---------------------------------------------------------------------------


def service_account_token_provider(
    credentials: dict, token_url: str | None = None, scope: str | None = None
):
    """Return a `() -> bearer token` callable implementing the OAuth2
    service-account JWT grant (what `option.WithCredentialsFile` does inside
    the SDK): sign an RS256 JWT with the account's private key, exchange it
    at the token endpoint, cache until expiry."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding

    priv = serialization.load_pem_private_key(
        credentials["private_key"].encode(), password=None
    )
    token_url = token_url or credentials.get(
        "token_uri", "https://oauth2.googleapis.com/token"
    )
    scope = scope or "https://www.googleapis.com/auth/devstorage.read_write"
    cache: dict = {}

    def b64u(raw: bytes) -> str:
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    def provider() -> str:
        now = int(time.time())
        if cache.get("exp", 0) - 60 > now:
            return cache["token"]
        header = b64u(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
        claims = b64u(
            json.dumps(
                {
                    "iss": credentials["client_email"],
                    "scope": scope,
                    "aud": token_url,
                    "iat": now,
                    "exp": now + 3600,
                }
            ).encode()
        )
        signing_input = f"{header}.{claims}".encode()
        sig = priv.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
        jwt = f"{header}.{claims}.{b64u(sig)}"
        body = urllib.parse.urlencode(
            {
                "grant_type": "urn:ietf:params:oauth:grant-type:jwt-bearer",
                "assertion": jwt,
            }
        ).encode()
        status, _, resp = http_request(
            "POST",
            token_url,
            body,
            {"Content-Type": "application/x-www-form-urlencoded"},
        )
        if status >= 400:
            raise CloudSinkError(status, resp)
        out = json.loads(resp)
        cache["token"] = out["access_token"]
        cache["exp"] = now + int(out.get("expires_in", 3600))
        return cache["token"]

    return provider


class GcsSink(ReplicationSink):
    """Replicate into a GCS bucket via the JSON API (`gcs_sink.go`).
    Directories become trailing-slash marker deletes only, matching the
    reference (it never creates directory objects but deletes `key/`)."""

    def __init__(
        self,
        bucket: str,
        token_provider,
        endpoint: str = "https://storage.googleapis.com",
    ) -> None:
        self.bucket = bucket
        self.token = token_provider
        self.endpoint = endpoint.rstrip("/")

    def _headers(self) -> dict[str, str]:
        return {"Authorization": f"Bearer {self.token()}"}

    def create_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        if entry.get("is_directory"):
            return
        key = _clean_key(path)
        mime = (entry.get("attributes") or {}).get(
            "mime", "application/octet-stream"
        )
        url = (
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name={urllib.parse.quote(key, safe='')}"
        )
        headers = self._headers()
        headers["Content-Type"] = mime or "application/octet-stream"
        status, _, body = http_request("POST", url, data or b"", headers,
                                       timeout=120)
        if status >= 400:
            raise CloudSinkError(status, body)

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        key = _clean_key(path, is_directory)
        url = (
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o/"
            f"{urllib.parse.quote(key, safe='')}"
        )
        status, _, body = http_request("DELETE", url, None, self._headers())
        if status >= 400 and status != 404:
            raise CloudSinkError(status, body)


# ---------------------------------------------------------------------------
# Backblaze B2
# ---------------------------------------------------------------------------


class B2Sink(ReplicationSink):
    """Replicate into a B2 bucket over the native API (`b2_sink.go`, which
    wraps kurin/blazer). Auth tokens and upload URLs are cached and
    refreshed on 401, the way the SDK's transport does."""

    def __init__(
        self,
        account_id: str,
        application_key: str,
        bucket: str,
        endpoint: str = "https://api.backblazeb2.com",
    ) -> None:
        self.account_id = account_id
        self.app_key = application_key
        self.bucket = bucket
        self.endpoint = endpoint.rstrip("/")
        self._auth: dict | None = None
        self._upload: dict | None = None
        self._bucket_id: str | None = None

    # --- session -----------------------------------------------------------
    def _authorize(self) -> dict:
        if self._auth is not None:
            return self._auth
        basic = base64.b64encode(
            f"{self.account_id}:{self.app_key}".encode()
        ).decode()
        status, _, body = http_request(
            "GET",
            f"{self.endpoint}/b2api/v2/b2_authorize_account",
            None,
            {"Authorization": f"Basic {basic}"},
        )
        if status >= 400:
            raise CloudSinkError(status, body)
        self._auth = json.loads(body)
        return self._auth

    def _api(self, call: str, payload: dict, _retry: bool = True) -> dict:
        auth = self._authorize()
        status, _, body = http_request(
            "POST",
            f"{auth['apiUrl']}/b2api/v2/{call}",
            json.dumps(payload).encode(),
            {"Authorization": auth["authorizationToken"]},
        )
        if status == 401 and _retry:  # expired token: one re-auth retry
            self._auth = None
            return self._api(call, payload, _retry=False)
        if status >= 400:
            raise CloudSinkError(status, body)
        return json.loads(body)

    def _get_bucket_id(self) -> str:
        if self._bucket_id is None:
            out = self._api(
                "b2_list_buckets",
                {
                    "accountId": self._authorize()["accountId"],
                    "bucketName": self.bucket,
                },
            )
            for b in out["buckets"]:
                if b["bucketName"] == self.bucket:
                    self._bucket_id = b["bucketId"]
            if self._bucket_id is None:
                raise CloudSinkError(404, f"bucket {self.bucket}".encode())
        return self._bucket_id

    # --- sink SPI ----------------------------------------------------------
    def create_entry(self, path: str, entry: dict, data: bytes | None,
                     _retry: bool = True) -> None:
        if entry.get("is_directory"):
            return
        data = data or b""
        if self._upload is None:
            self._upload = self._api(
                "b2_get_upload_url", {"bucketId": self._get_bucket_id()}
            )
        mime = (entry.get("attributes") or {}).get("mime") or "b2/x-auto"
        headers = {
            "Authorization": self._upload["authorizationToken"],
            "X-Bz-File-Name": urllib.parse.quote(_clean_key(path)),
            "Content-Type": mime,
            "X-Bz-Content-Sha1": hashlib.sha1(data).hexdigest(),
        }
        status, _, body = http_request(
            "POST", self._upload["uploadUrl"], data, headers, timeout=120,
        )
        if status == 401 and _retry:  # upload URLs expire on their own clock
            self._upload = None
            return self.create_entry(path, entry, data, _retry=False)
        if status >= 400:
            raise CloudSinkError(status, body)

    def update_entry(self, path: str, entry: dict, data: bytes | None) -> None:
        self.create_entry(path, entry, data)

    def delete_entry(self, path: str, is_directory: bool) -> None:
        key = _clean_key(path, is_directory)
        start_name, start_id = key, None
        while True:  # page through ALL versions of this file name
            req = {
                "bucketId": self._get_bucket_id(),
                "startFileName": start_name,
                "maxFileCount": 100,
            }
            if start_id:
                req["startFileId"] = start_id
            out = self._api("b2_list_file_versions", req)
            done = False
            for f in out.get("files", []):
                if f["fileName"] != key:
                    done = True
                    break
                self._api(
                    "b2_delete_file_version",
                    {"fileName": f["fileName"], "fileId": f["fileId"]},
                )
            start_name = out.get("nextFileName")
            start_id = out.get("nextFileId")
            if done or not start_name:
                break
