"""HTTP servers: master, volume, filer (+ S3 gateway in seaweedfs_tpu.s3).

The control plane mirrors the reference's own HTTP surface (/dir/assign,
/dir/lookup on the master — `weed/server/master_server_handlers.go:36,110` —
and GET/POST/DELETE /<vid>,<fid> on volume servers —
`weed/server/volume_server_handlers.go`), with JSON bodies where the
reference uses gRPC for admin verbs (this build's wire format; grpc/proto
tooling is not available in the image).
"""
