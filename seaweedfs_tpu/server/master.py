"""Master server: assign/lookup HTTP API + heartbeat ingest + growth + vacuum.

Reference: `weed/server/master_server.go`, `master_server_handlers.go:36,110`,
`master_grpc_server.go:62`, `topology_vacuum.go:216`. Multi-master HA rides
on the Raft layer (seaweedfs_tpu/raft): followers redirect to the leader,
and the volume-id counter + file-id sequence ceiling are replicated.
"""

from __future__ import annotations

import threading
import time

from seaweedfs_tpu.security import Guard, SecurityConfig
from seaweedfs_tpu.security.jwt import gen_write_jwt
from seaweedfs_tpu.storage.types import ReplicaPlacement, TTL
from seaweedfs_tpu.topology import Topology
from seaweedfs_tpu.topology.sequence import MemorySequencer
from seaweedfs_tpu.topology.volume_layout import NoWritableVolume
from seaweedfs_tpu.util import faults

from .httpd import HTTPService, Request, Response, post_json, peer_url

# control-plane fault seams: every client of assign/lookup must survive a
# 500 (fresh assignment, alternate holder) — the chaos suite proves it
_FP_ASSIGN = faults.register("master.assign")
_FP_LOOKUP = faults.register("master.lookup")


class MasterServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 9333,
        volume_size_limit_mb: int = 30 * 1024,
        pulse_seconds: int = 5,
        default_replication: str = "000",
        meta_dir: str | None = None,
        garbage_threshold: float = 0.3,
        security: SecurityConfig | None = None,
        peers: list[str] | None = None,
        raft_dir: str | None = None,
        slow_ms: float | None = None,
        maintenance: bool = False,
        maintenance_dry_run: bool = False,
        maintenance_interval: float | None = None,
        repair_lazy_window: float = 0.0,
        ec_online: str = "",
        ec_online_block: int | None = None,
        telemetry_dir: str | None = None,
        telemetry_retention_mb: float | None = None,
    ) -> None:
        seq = MemorySequencer(f"{meta_dir}/sequence.json" if meta_dir else None)
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * 1024 * 1024,
            pulse_seconds=pulse_seconds,
            sequencer=seq,
        )
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        # -ec.online policy: collections whose volumes stream-encode
        # RS(10,4) parity on ingest instead of replica fan-out
        # (comma-separated names; "*" = every collection incl. default)
        self.ec_online_collections = {
            c.strip() for c in ec_online.split(",") if c.strip()
        }
        self.ec_online_block = ec_online_block
        self.security = security or SecurityConfig()
        self.service = HTTPService(host, port)
        if self.security.white_list:
            self.service.guard = Guard(self.security.white_list)
        self.service.enable_metrics("master")
        # -telemetry.dir: durable spool — replay the pre-crash tail into
        # the history/event rings, then flush them to disk continuously
        if telemetry_dir:
            from seaweedfs_tpu.stats import store as store_mod

            store_mod.enable(telemetry_dir, telemetry_retention_mb)
        if slow_ms is not None:  # -slowMs: per-role slow-span threshold
            from seaweedfs_tpu.stats import trace as _trace

            _trace.set_slow_threshold_ms(slow_ms, role="master")
        self._grow_lock = threading.Lock()
        self._stop = threading.Event()
        # cluster membership (filers/brokers announce themselves) + admin lock
        self._members: dict[str, dict] = {}
        self._admin_lock: tuple[str, float] | None = None  # (holder, expiry)
        # raft HA (weed/server/raft_server.go): created at start() once the
        # listen port is known; None = single-master mode
        self.raft = None
        self.fastlane = None  # native /dir/assign front door (start())
        self._peer_config = list(peers or [])
        self._raft_dir = raft_dir
        self._seq_ceiling = 0
        # raft term the sequencer lease was last synced in: any term change
        # (i.e. any possible leadership handoff, even one this node never
        # observed via a request) forces a re-sync against the replicated
        # ceiling before ids are handed out (advisor r1 finding #1)
        self._seq_synced_term = -1
        # autonomous maintenance (seaweedfs_tpu/maintenance): off by
        # default; -maintenance starts the detect->plan->heal daemon,
        # -maintenance.dryRun plans without executing
        self.maintenance = None
        self._maintenance_flag = maintenance
        self._maintenance_dry_run = maintenance_dry_run
        self._maintenance_interval = maintenance_interval
        # -repair.lazyWindow: defer single-shard ec_rebuild dispatch up
        # to this many seconds so co-stripe losses fold into one
        # multi-target chain pass (0 = dispatch immediately)
        self._repair_lazy_window = float(repair_lazy_window)
        self._maintenance_lock = threading.Lock()
        self._routes()

    # --- lifecycle -------------------------------------------------------------
    def _start_fastlane(self) -> None:
        """Front the master with the native engine so /dir/assign is served
        without the GIL: Python installs per-query volume-set profiles with
        leased file-key ranges (do_assign), the engine mints fids from them,
        and anything else (or a spent/missing profile) proxies back here."""
        from seaweedfs_tpu.storage import fastlane as fl_mod

        # write_key counts as a bail-out: assigns mint per-fid JWTs, which
        # only the Python handler can sign. mTLS does NOT bail — the engine
        # terminates it natively (front_service's TLS branch).
        self.fastlane = fl_mod.front_service(
            self.service,
            guard_active=bool(self.security.white_list
                              or self.security.write_key),
        )

    def start(self) -> None:
        self._start_fastlane()
        self._register_metrics_collector()
        if self._peer_config:
            self.enable_raft(
                [p.rstrip("/") for p in self._peer_config
                 if p.rstrip("/") != self.url]
            )
        threading.Thread(target=self._maintenance_loop, daemon=True).start()
        if self._maintenance_flag:
            self._ensure_maintenance(dry_run=self._maintenance_dry_run)

    def _ensure_maintenance(self, dry_run: bool | None = False,
                            rebuild_mode: str | None = None,
                            lazy_window: float | None = None):
        """Create (or reconfigure) and start the maintenance daemon — the
        `-maintenance` flag at boot, or `cluster.maintenance -enable` at
        runtime. dry_run=None preserves the daemon's current mode: a bare
        re-enable must not silently flip a dry-run daemon into mutating
        mode (rebuild_mode=None and lazy_window=None likewise). Locked:
        two racing /maintenance/enable requests must not each start (and
        one leak) a daemon, and an enable racing stop() must not start a
        daemon that outlives the master."""
        with self._maintenance_lock:
            if self._stop.is_set():
                raise RuntimeError("master is stopping")
            if self.maintenance is None:
                from seaweedfs_tpu.maintenance import MaintenanceDaemon

                daemon = MaintenanceDaemon(
                    self, interval=self._maintenance_interval,
                    dry_run=bool(dry_run),
                    rebuild_mode=rebuild_mode or "auto",
                    lazy_window=(
                        self._repair_lazy_window if lazy_window is None
                        else float(lazy_window)
                    ),
                )
                daemon.start()
                self.maintenance = daemon
            else:
                if dry_run is not None:
                    self.maintenance.dry_run = bool(dry_run)
                if rebuild_mode is not None:
                    self.maintenance.rebuild_mode = rebuild_mode
                if lazy_window is not None:
                    self.maintenance.scheduler.lazy_window = \
                        float(lazy_window)
                self.maintenance.enabled = True
            return self.maintenance

    # --- topology gauges --------------------------------------------------------
    MASTER_METRIC_FAMILIES = (
        "SeaweedFS_master_volume_size_bytes",
        "SeaweedFS_master_volume_file_count",
        "SeaweedFS_master_volume_deleted_bytes",
        "SeaweedFS_master_volume_readonly",
        "SeaweedFS_master_volume_size_limit_bytes",
        "SeaweedFS_master_free_slots",
        "SeaweedFS_master_heartbeat_age_seconds",
        "SeaweedFS_master_stale_heartbeats",
        "SeaweedFS_master_ec_shard_count",
        "SeaweedFS_master_volumes_underreplicated",
        "SeaweedFS_master_ec_missing_shards",
    )

    def _register_metrics_collector(self) -> None:
        """Export the heartbeat-fed topology view as Prometheus gauges at
        scrape time (the reference's master exports the same families from
        `weed/stats/metrics.go` MasterVolumeLayout gauges). Registered as a
        scrape-time collector so /metrics always reflects the live tree —
        no per-heartbeat gauge churn, nothing stale after a node expires."""
        from seaweedfs_tpu.stats import default_registry
        from seaweedfs_tpu.stats import heat as heat_mod

        self._metrics_collector = default_registry().register_collector(
            self._metrics_lines, names=self.MASTER_METRIC_FAMILIES,
        )
        # cluster heat rollup: heartbeat-fed per-collection/per-node
        # access rates only the master can assemble (stats/heat.py)
        self.heat_rollup = heat_mod.HeatRollup()
        heat_mod.register_rollup(self.heat_rollup)
        self._heat_collector = default_registry().register_collector(
            self.heat_rollup.lines, names=heat_mod.ROLLUP_FAMILIES,
        )
        # cluster telemetry plane: frames ride heartbeats / register
        # payloads / POST /cluster/telemetry into the leader's aggregator
        # (stats/aggregate.py); one GET /debug/cluster/telemetry serves
        # the merged view cluster.top/cluster.check consume
        from seaweedfs_tpu.stats import aggregate as agg_mod

        self.telemetry = agg_mod.TelemetryAggregator()
        self._telemetry_collector = default_registry().register_collector(
            self.telemetry.lines, names=agg_mod.CLUSTER_FAMILIES,
        )
        self._telemetry_self_ts = 0.0

    def _metrics_lines(self) -> list[str]:
        from seaweedfs_tpu.stats.metrics import _fmt_labels

        lines: list[str] = []
        # disambiguates multiple masters sharing one process registry
        # (raft test clusters) — same role the volume collector's `server`
        # label plays; without it their series would collide. Advertise the
        # public port (the engine front when present, not the loopback
        # backend the Python service binds behind it)
        port = self.fastlane.port if getattr(self, "fastlane", None) \
            else self.service.port
        me = f"{self.service.host}:{port}"

        def sample(family: str, labels: dict, value) -> None:
            labels = {"master": me, **labels}
            # integers render exactly: '{:g}' would clip volume sizes to 6
            # significant digits, skewing cluster.check's capacity math
            v = str(int(value)) if float(value).is_integer() else f"{value:g}"
            lines.append(
                f"{family}"
                f"{_fmt_labels(tuple(labels), tuple(labels.values()))}"
                f" {v}"
            )

        for fam in self.MASTER_METRIC_FAMILIES:
            lines.append(f"# TYPE {fam} gauge")
        sample("SeaweedFS_master_volume_size_limit_bytes", {},
               self.topo.volume_size_limit)
        now = time.time()
        # 3x pulse: late enough that a GIL-starved heartbeat thread does
        # not flap the gauge, early enough to flag well before the 5x-pulse
        # node expiry removes the node (and its gauges) entirely
        stale_after = 3 * max(self.topo.pulse_seconds, 1)
        # flight-recorder edges: staleness is computed right here, so the
        # journal events ride the same scrape that flips the gauge (a
        # racing double-render could at worst duplicate an edge — the
        # journal tolerates that; missing one it would not)
        prev_stale = getattr(self, "_stale_nodes", None)
        if prev_stale is None:
            prev_stale = self._stale_nodes = set()
        from seaweedfs_tpu.stats import events as events_mod

        # a stale node that EXPIRED out of the topology never rejoined —
        # drop it without an edge, so the set can't leak and a later
        # fresh re-registration can't fabricate a spurious rejoin
        live_ids = {n.id for n in self.topo.all_nodes()}
        prev_stale &= live_ids
        for node in self.topo.all_nodes():
            where = {"dc": node.dc_name(), "rack": node.rack_name(),
                     "node": node.id}
            sample("SeaweedFS_master_free_slots", where, node.free_slots())
            age = max(0.0, now - node.last_seen)
            stale = age > stale_after
            if stale and node.id not in prev_stale:
                prev_stale.add(node.id)
                events_mod.emit("heartbeat_stale", node=node.id,
                                age_s=round(age, 2))
            elif not stale and node.id in prev_stale:
                prev_stale.discard(node.id)
                events_mod.emit("heartbeat_rejoin", node=node.id,
                                age_s=round(age, 2))
            sample("SeaweedFS_master_heartbeat_age_seconds", where, age)
            sample("SeaweedFS_master_stale_heartbeats", where,
                   1 if stale else 0)
            sample("SeaweedFS_master_ec_shard_count", where,
                   sum(len(s.shard_ids()) for s in node.ec_shards.values()))
            for vid, v in sorted(node.volumes.items()):
                vl = {"volume": vid, "collection": v.collection,
                      "node": node.id}
                sample("SeaweedFS_master_volume_size_bytes", vl, v.size)
                sample("SeaweedFS_master_volume_file_count", vl, v.file_count)
                sample("SeaweedFS_master_volume_deleted_bytes", vl,
                       v.deleted_byte_count)
                sample("SeaweedFS_master_volume_readonly", vl,
                       1 if v.read_only else 0)
        for coll, vid, have, want in self.topo.under_replicated_volumes():
            sample("SeaweedFS_master_volumes_underreplicated",
                   {"volume": vid, "collection": coll, "have": have,
                    "want": want}, want - have)
        for vid, missing in sorted(self.topo.ec_missing_shards().items()):
            sample("SeaweedFS_master_ec_missing_shards", {"volume": vid},
                   missing)
        return lines

    def _fl_assign_install(self, req, count: int, replication: str,
                           collection: str, ttl: str, dc: str,
                           shard: tuple[int, int] | None = None) -> None:
        """After a Python-served assign: teach the engine this exact query.
        The profile snapshot is the layout's current writable volume set;
        any heartbeat clears every profile (sync is cheap, staleness isn't).
        The profile keys on the raw query, so a gateway's `?shard=i:n`
        lease slice gets its own profile — restricted to the slice's
        vids (falling back to the full set when the slice is empty,
        mirroring VolumeLayout.pick_for_write's soft constraint)."""
        if self.fastlane is None or count != 1 or not self._is_leader():
            return
        import json as _json

        rp = ReplicaPlacement.parse(replication)
        lo = self.topo.layout(collection, rp, TTL.parse(ttl).to_u32())
        entries = []
        with lo._lock:
            writables = list(lo.writables)
            if shard is not None and shard[1] > 1:
                sliced = [v for v in writables if v % shard[1] == shard[0]]
                if sliced:
                    writables = sliced
            for vid in writables:
                nodes = lo.locations.get(vid, [])
                if not nodes:
                    continue
                if dc and all(n.dc_name() != dc for n in nodes):
                    continue
                main = nodes[0]
                tail = (
                    f'"url": {_json.dumps(main.id)}, '
                    f'"publicUrl": {_json.dumps(main.url)}, "count": 1, '
                    '"replicas": ['
                    + ", ".join(
                        f'{{"url": {_json.dumps(n.id)}, '
                        f'"publicUrl": {_json.dumps(n.url)}}}'
                        for n in nodes[1:]
                    )
                    + "]}"
                )
                entries.append((vid, tail))
        if not entries:
            return
        lease = 20000
        try:
            self._ensure_sequence_lease(lease)
        except Exception:
            return  # not leader / raft flux: stay on the Python path
        start = self.topo.sequencer.next_file_id(lease)
        self.fastlane.assign_set(req.raw_query, entries, start, start + lease)

    def _fl_assign_clear(self) -> None:
        if getattr(self, "fastlane", None) is not None:
            self.fastlane.assign_clear()

    def enable_raft(self, peer_urls: list[str]) -> None:
        from seaweedfs_tpu.raft import RaftNode

        self.raft = RaftNode(
            self.url, peer_urls, self._raft_apply, state_dir=self._raft_dir,
            snapshot_fn=self._raft_snapshot, restore_fn=self._raft_restore,
            # clear native assign profiles the instant leadership is lost —
            # waiting for the next maintenance tick would let the engine
            # keep minting fids from stale topology for up to pulse_seconds
            on_demote=self._fl_assign_clear,
        )
        self.topo.vid_allocator = lambda: self.raft.propose(
            {"type": "next_volume_id"}
        )
        self.raft.start()

    def _raft_snapshot(self) -> dict:
        """Applied master state for log compaction (`-master.resumeState`)."""
        return {
            "max_volume_id": self.topo._max_volume_id,
            "seq_ceiling": self._seq_ceiling,
        }

    def _raft_restore(self, state: dict) -> None:
        self.topo._max_volume_id = max(
            self.topo._max_volume_id, int(state.get("max_volume_id", 0))
        )
        self._seq_ceiling = max(
            self._seq_ceiling, int(state.get("seq_ceiling", 0))
        )

    def _raft_apply(self, command: dict):
        """Replicated master state machine: volume-id counter + file-id
        sequence ceiling (the two pieces the reference raft-persists)."""
        kind = command.get("type")
        if kind == "next_volume_id":
            return self.topo._next_volume_id_raw()
        if kind == "sequence_ceiling":
            self._seq_ceiling = max(self._seq_ceiling, int(command["value"]))
            return self._seq_ceiling
        return None

    def _is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader()

    def leader_url(self) -> str:
        if self.raft is None or self.raft.is_leader():
            return self.url
        return self.raft.leader() or self.url

    def _not_leader_response(self):
        return Response(
            {"error": "raft.not.leader", "leader": self.leader_url()}, 409
        )

    def _ensure_sequence_lease(self, count: int) -> None:
        """Leader-side sequence lease (`sequence raft SetMax`): ids are only
        handed out below the committed ceiling; whenever the raft term moved
        since the last sync (any election, observed or not), the counter is
        fast-forwarded to the replicated ceiling first so ids never repeat
        across failover."""
        if self.raft is None:
            return
        seq = self.topo.sequencer
        term = self.raft.term()
        if self._seq_synced_term != term:
            # Commit a no-op barrier first: committing it forces every
            # ceiling entry from prior terms to be APPLIED on this node, so
            # the set_max below sees grants the old leader made that were
            # still unapplied here (committed-but-not-applied window).
            self.raft.propose({"type": "sequence_ceiling", "value": 0})
            seq.set_max(self._seq_ceiling)
            self._seq_synced_term = term
        while seq.peek() + count >= self._seq_ceiling:
            self.raft.propose({
                "type": "sequence_ceiling",
                "value": seq.peek() + count + 10000,
            })

    def stop(self) -> None:
        self._stop.set()
        # under the same lock as _ensure_maintenance: an in-flight enable
        # must either finish first (and be stopped here) or observe _stop
        with self._maintenance_lock:
            if self.maintenance is not None:
                self.maintenance.stop()
                self.maintenance = None
        if getattr(self, "_metrics_collector", None) is not None:
            from seaweedfs_tpu.stats import default_registry

            default_registry().unregister_collector(self._metrics_collector)
            self._metrics_collector = None
        if getattr(self, "_heat_collector", None) is not None:
            from seaweedfs_tpu.stats import default_registry
            from seaweedfs_tpu.stats import heat as heat_mod

            default_registry().unregister_collector(self._heat_collector)
            self._heat_collector = None
            heat_mod.unregister_rollup(self.heat_rollup)
            self.heat_rollup = None
        if getattr(self, "_telemetry_collector", None) is not None:
            from seaweedfs_tpu.stats import default_registry

            default_registry().unregister_collector(self._telemetry_collector)
            self._telemetry_collector = None
            self.telemetry = None
        if self.raft is not None:
            self.raft.stop()
        if getattr(self, "fastlane", None) is not None:
            self.fastlane.stop()
            self.fastlane = None
        self.service.stop()

    @property
    def url(self) -> str:
        if getattr(self, "fastlane", None) is not None:
            scheme = "https" if self.fastlane.tls else "http"
            return f"{scheme}://{self.service.host}:{self.fastlane.port}"
        return self.service.url

    def _maintenance_loop(self) -> None:
        last_assigns = 0
        while not self._stop.wait(self.topo.pulse_seconds):
            if self.raft is not None and not self.raft.is_leader():
                self._fl_assign_clear()  # followers must not mint fids
            if self.fastlane is not None and self.service.metrics_role:
                # native assigns bypass the instrumented Python handler
                n = self.fastlane.stats()["native_assigns"]
                if n > last_assigns:
                    self.service._m_total.labels(
                        self.service.metrics_role, "GET", "200"
                    ).inc(n - last_assigns)
                    last_assigns = n
            self.topo.expire_dead_nodes()
            self._telemetry_self_feed()
            try:
                self._vacuum_check()
            except Exception:
                pass

    def _telemetry_self_feed(self) -> None:
        """The master is a telemetry sender too — its own frame (role
        'master') enters the aggregator on the pulse cadence, so the
        cluster view covers the control plane without a network hop.
        Rate-limited: the debug handler also calls this on demand."""
        tele = getattr(self, "telemetry", None)
        if tele is None:
            return
        now = time.time()
        interval = max(float(self.topo.pulse_seconds), 1.0)
        if now - self._telemetry_self_ts < interval:
            return
        self._telemetry_self_ts = now
        try:
            from seaweedfs_tpu.stats import aggregate as agg_mod

            port = self.fastlane.port if getattr(self, "fastlane", None) \
                else self.service.port
            tele.ingest(agg_mod.build_frame(
                "master", f"{self.service.host}:{port}", interval=interval,
            ), now=now)
        except Exception:
            pass

    # --- growth ----------------------------------------------------------------
    def _is_ec_online(self, collection: str) -> bool:
        return (
            "*" in self.ec_online_collections
            or collection in self.ec_online_collections
        )

    def _grow_volumes(
        self, collection: str, rp: ReplicaPlacement, ttl_u32: int, dc: str
    ) -> None:
        """Pick servers then instruct them to allocate (`volume_growth.go:243`)."""
        from seaweedfs_tpu.stats import trace

        with self._grow_lock, trace.span(
            "master.grow", role="master", collection=collection,
        ):
            lo = self.topo.layout(collection, rp, ttl_u32)
            if lo.active_volume_count(dc) > 0:
                return  # another request already grew (in this DC if pinned)
            ec_online = self._is_ec_online(collection)
            # parity-only durability wants ONE holder while the volume
            # streams (no replica ever receives bytes — an empty replica
            # would 404 reads), so slot-finding places a single copy. The
            # volume's superblock still records the REQUESTED placement:
            # if online mode degrades, the heartbeat drops ec_online and
            # the layout re-demands the real replica count, so
            # fix_replication can heal it.
            rp_slots = ReplicaPlacement.parse("000") if ec_online else rp
            grown = self.topo.grow(collection, rp_slots, ttl_u32, dc)
            ttl_s = str(TTL.from_u32(ttl_u32))
            for vid, nodes in grown:
                ok_nodes = []
                for node in nodes:
                    try:
                        body = {
                            "volume": vid,
                            "collection": collection,
                            "replication": str(rp),
                            "ttl": ttl_s,
                        }
                        if ec_online:
                            body["ecOnline"] = True
                            if self.ec_online_block:
                                body["ecOnlineBlock"] = self.ec_online_block
                        post_json(
                            peer_url(node.url) + "/admin/allocate_volume",
                            body,
                            timeout=10,
                        )
                        ok_nodes.append(node)
                    except Exception:
                        continue
                # registration happens via the servers' next heartbeat; to make
                # assign usable immediately, register optimistically
                from seaweedfs_tpu.topology.node import VolumeInfo

                want_nodes = 1 if ec_online else rp.copy_count()
                if len(ok_nodes) == want_nodes:
                    for node in ok_nodes:
                        info = VolumeInfo(
                            id=vid,
                            collection=collection,
                            replica_placement=rp.to_byte(),
                            ttl=ttl_u32,
                            ec_online=ec_online,
                        )
                        node.volumes[vid] = info
                        self.topo._register_volume(info, node)

    # --- vacuum ----------------------------------------------------------------
    def _vacuum_check(self) -> None:
        """Ask volume servers to compact garbage-heavy volumes
        (`topology_vacuum.go:216`)."""
        from seaweedfs_tpu.stats import trace

        if not getattr(self, "vacuum_enabled", True):
            return
        # the maintenance subsystem owns vacuum while its daemon is on
        # (including dry-run: the legacy loop mutating would break the
        # "plans with zero mutations" contract)
        if self.maintenance is not None and self.maintenance.enabled:
            return
        with trace.span("master.vacuum_check", role="master"):
            self._vacuum_round()

    def _vacuum_round(self) -> None:
        for node, vid, _ in self.topo.vacuum_candidates(self.garbage_threshold):
            try:
                post_json(
                    peer_url(node.url) + "/admin/vacuum",
                    {"volume": vid},
                    timeout=120,
                )
            except Exception:
                pass

    # --- routes ----------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service

        @svc.route("POST", r"/heartbeat")
        def heartbeat(req: Request) -> Response:
            from seaweedfs_tpu.stats import trace

            # periodic chatter: recorded only when the volume server's
            # (sampled) heartbeat span linked us into its trace
            trace.annotate(noise=True)
            if not self._is_leader():
                # volume servers re-target to the leader (KeepConnected
                # redirect semantics, `master_grpc_server.go`)
                return self._not_leader_response()
            hb = req.json()
            self.topo.sync_heartbeat(hb)
            if getattr(self, "heat_rollup", None) is not None:
                self.heat_rollup.feed(
                    f"{hb.get('ip', '')}:{hb.get('port', '')}",
                    hb.get("volumes") or (),
                )
            tele = hb.get("telemetry")
            if tele and getattr(self, "telemetry", None) is not None:
                self.telemetry.ingest(tele)
            # any topology delta may change the writable set: drop every
            # assign profile, the next Python-served assign reinstalls
            self._fl_assign_clear()
            return Response(
                {
                    "volume_size_limit": self.topo.volume_size_limit,
                    "leader": self.leader_url(),
                }
            )

        # --- raft plane (`weed/server/raft_server.go` transport) ---
        @svc.route("POST", r"/raft/request_vote")
        def raft_request_vote(req: Request) -> Response:
            if self.raft is None:
                return Response({"error": "raft disabled"}, 503)
            return Response(self.raft.handle_request_vote(req.json()))

        @svc.route("POST", r"/raft/append_entries")
        def raft_append_entries(req: Request) -> Response:
            if self.raft is None:
                return Response({"error": "raft disabled"}, 503)
            return Response(self.raft.handle_append_entries(req.json()))

        @svc.route("POST", r"/raft/install_snapshot")
        def raft_install_snapshot(req: Request) -> Response:
            if self.raft is None:
                return Response({"error": "raft disabled"}, 503)
            return Response(self.raft.handle_install_snapshot(req.json()))

        @svc.route("GET", r"/raft/status")
        def raft_status(req: Request) -> Response:
            if self.raft is None:
                return Response({"enabled": False, "leader": self.url})
            out = self.raft.status()
            out["enabled"] = True
            return Response(out)

        @svc.route("POST", r"/raft/add")
        def raft_add(req: Request) -> Response:
            # `cluster.raft.add` (command_cluster_raft_add.go): replicated
            # membership change; leader-only like every admin mutation
            if self.raft is None:
                return Response({"error": "raft not enabled"}, 400)
            if not self._is_leader():
                return self._not_leader_response()
            peer = (req.json().get("peer") or "").rstrip("/")
            if not peer:
                return Response({"error": "missing peer url"}, 400)
            out = self.raft.add_peer(peer)
            return Response(out)

        @svc.route("POST", r"/raft/remove")
        def raft_remove(req: Request) -> Response:
            if self.raft is None:
                return Response({"error": "raft not enabled"}, 400)
            if not self._is_leader():
                return self._not_leader_response()
            peer = (req.json().get("peer") or "").rstrip("/")
            if not peer:
                return Response({"error": "missing peer url"}, 400)
            out = self.raft.remove_peer(peer)
            return Response(out)

        def do_assign(req: Request) -> Response:
            _FP_ASSIGN.hit()  # injected error -> 500 via _dispatch; the
            # writer's retry/fresh-assignment path is what's under test
            if not self._is_leader():
                return self._not_leader_response()
            count = int(req.query.get("count", 1))
            replication = req.query.get("replication") or self.default_replication
            collection = req.query.get("collection", "")
            ttl = req.query.get("ttl", "")
            dc = req.query.get("dataCenter", "")
            # ?shard=i:n — gateway lease-pool vid-space sharding: prefer
            # vids where vid % n == i (soft: falls back to the whole
            # space when the slice has no writables)
            shard = None
            shard_s = req.query.get("shard", "")
            if shard_s:
                try:
                    i_s, _, n_s = shard_s.partition(":")
                    shard = (int(i_s), int(n_s))
                    if shard[1] < 1 or not 0 <= shard[0] < shard[1]:
                        raise ValueError(shard_s)
                except ValueError:
                    return Response(
                        {"error": f"bad shard {shard_s!r} (want i:n)"}, 400)
            rp = ReplicaPlacement.parse(replication)
            ttl_u32 = TTL.parse(ttl).to_u32()
            from seaweedfs_tpu.raft import NotLeader

            lo = self.topo.layout(collection, rp, ttl_u32)
            if lo.active_volume_count(dc) == 0:
                try:
                    self._grow_volumes(collection, rp, ttl_u32, dc)
                except NotLeader:
                    return self._not_leader_response()
                except Exception as e:
                    return Response({"error": f"cannot grow volumes: {e}"}, 500)
            try:
                self._ensure_sequence_lease(count)
                fid, cnt, nodes = self.topo.pick_for_write(
                    count, replication, ttl, collection, dc, shard=shard
                )
            except NotLeader:
                return self._not_leader_response()
            except NoWritableVolume:
                # raced with a full/readonly transition: grow then retry once
                try:
                    self._grow_volumes(collection, rp, ttl_u32, dc)
                    fid, cnt, nodes = self.topo.pick_for_write(
                        count, replication, ttl, collection, dc, shard=shard
                    )
                except NotLeader:
                    return self._not_leader_response()
                except (NoWritableVolume, Exception) as e:
                    return Response({"error": str(e)}, 404)
            main = nodes[0]
            out = {
                "fid": fid,
                "url": main.id,
                "publicUrl": main.url,
                "count": cnt,
                "replicas": [
                    {"url": n.id, "publicUrl": n.url} for n in nodes[1:]
                ],
            }
            if self.security.write_key:
                # per-fileId write token the volume server will demand
                # (`weed/security/jwt.go GenJwtForVolumeServer`)
                out["auth"] = gen_write_jwt(
                    self.security.write_key, fid, self.security.write_expires_sec
                )
            else:
                self._fl_assign_install(req, count, replication, collection,
                                        ttl, dc, shard=shard)
            return Response(out)

        svc.route("GET", r"/dir/assign")(do_assign)
        svc.route("POST", r"/dir/assign")(do_assign)

        def do_lookup(req: Request) -> Response:
            _FP_LOOKUP.hit()
            if not self._is_leader():
                # followers have empty topologies (heartbeats are
                # leader-only) — redirect instead of a misleading 404
                return self._not_leader_response()
            vid_s = req.query.get("volumeId", "")
            if "," in vid_s:
                vid_s = vid_s.split(",")[0]
            try:
                vid = int(vid_s)
            except ValueError:
                return Response({"error": f"unknown volumeId {vid_s}"}, 400)
            nodes = self.topo.lookup(vid, req.query.get("collection", ""))
            if not nodes:
                return Response(
                    {"volumeOrFileId": vid_s, "error": "volume id not found"}, 404
                )
            return Response(
                {
                    "volumeOrFileId": vid_s,
                    "locations": [
                        {"url": n.id, "publicUrl": n.url} for n in nodes
                    ],
                }
            )

        svc.route("GET", r"/dir/lookup")(do_lookup)
        svc.route("POST", r"/dir/lookup")(do_lookup)

        @svc.route("GET", r"/dir/ec_lookup")
        def ec_lookup(req: Request) -> Response:
            vid = int(req.query.get("volumeId", 0))
            shard_map = self.topo.lookup_ec_shards(vid)
            if shard_map is None:
                return Response({"error": "ec volume not found"}, 404)
            return Response(
                {
                    "volumeId": vid,
                    "shards": {
                        str(sid): [n.url for n in nodes]
                        for sid, nodes in shard_map.items()
                    },
                }
            )

        @svc.route("GET", r"/ui")
        def ui(req: Request) -> Response:
            # minimal HTML status page (`weed/server/master_ui/`)
            rows = []
            for node in self.topo.all_nodes():
                rows.append(
                    f"<tr><td>{node.id}</td><td>{node.dc_name()}</td>"
                    f"<td>{node.rack_name()}</td>"
                    f"<td>{len(node.volumes)}</td></tr>"
                )
            html = (
                "<html><head><title>seaweedfs-tpu master</title></head><body>"
                f"<h1>Master {self.url}</h1>"
                f"<p>leader: {self.leader_url()} | max volume id: "
                f"{self.topo._max_volume_id}</p>"
                "<table border=1><tr><th>volume server</th><th>DC</th>"
                "<th>rack</th><th>volumes</th></tr>"
                + "".join(rows) + "</table>"
                "<p><a href='/dir/status'>topology json</a> | "
                "<a href='/cluster/ps'>cluster ps</a> | "
                "<a href='/metrics'>metrics</a></p>"
                "</body></html>"
            ).encode()
            return Response(html, content_type="text/html")

        @svc.route("GET", r"/dir/status")
        def dir_status(req: Request) -> Response:
            return Response({"Topology": self.topo.to_dict(), "Version": "seaweedfs-tpu"})

        @svc.route("GET", r"/cluster/status")
        def cluster_status(req: Request) -> Response:
            return Response(
                {"IsLeader": self._is_leader(), "Leader": self.leader_url(),
                 "MaxVolumeId": self.topo._max_volume_id}
            )

        @svc.route("GET", r"/vol/status")
        def vol_status(req: Request) -> Response:
            out = {}
            for node in self.topo.all_nodes():
                out[node.id] = {
                    str(vid): {
                        "size": v.size,
                        "file_count": v.file_count,
                        "delete_count": v.delete_count,
                        "garbage": v.deleted_byte_count,
                    }
                    for vid, v in node.volumes.items()
                }
            return Response({"Volumes": out})

        @svc.route("POST", r"/cluster/register")
        def cluster_register(req: Request) -> Response:
            """Filers/brokers announce themselves (the reference rides this on
            the KeepConnected stream, `weed/cluster/cluster.go`)."""
            from seaweedfs_tpu.stats import trace

            trace.annotate(noise=True)  # periodic re-registration chatter
            p = req.json()
            prev = self._members.get(p["address"])
            self._members[p["address"]] = {
                "type": p.get("type", "filer"),
                "address": p["address"],
                "last_seen": time.time(),
                # first-seen decides group leadership (`cluster.go` — the
                # longest-lived member leads its group)
                "created_ts": prev["created_ts"] if prev else time.time(),
            }
            tele = p.get("telemetry")
            if tele and getattr(self, "telemetry", None) is not None:
                self.telemetry.ingest(tele)
            # answer with the member's position among its live peer group
            # (ordered by first-seen, like group leadership): filers use
            # ordinal/gateways to shard the fid-lease vid-space so N
            # front doors never contend on the same volume
            now = time.time()
            ptype = p.get("type", "filer")
            peers = sorted(
                (m for m in self._members.values()
                 if m["type"] == ptype and now - m["last_seen"] < 30),
                key=lambda m: (m["created_ts"], m["address"]),
            )
            addrs = [m["address"] for m in peers]
            out = {"ok": True, "leader": self.url, "gateways": len(addrs)}
            if p["address"] in addrs:
                out["ordinal"] = addrs.index(p["address"])
            return Response(out)

        @svc.route("POST", r"/cluster/telemetry")
        def cluster_telemetry_push(req: Request) -> Response:
            """Telemetry frames from roles with no other master link (S3,
            webdav, tests). Leader-only like the heartbeat — the response
            names the leader so pushers re-target."""
            from seaweedfs_tpu.stats import trace

            trace.annotate(noise=True)  # periodic push chatter
            if not self._is_leader():
                return self._not_leader_response()
            tele = getattr(self, "telemetry", None)
            if tele is None:
                return Response({"error": "telemetry not started"}, 503)
            ok = tele.ingest(req.json())
            if not ok:
                return Response(
                    {"error": "malformed or replayed frame",
                     "leader": self.leader_url()}, 400)
            return Response({"ok": True, "leader": self.leader_url()})

        @svc.route("GET", r"/debug/cluster/telemetry")
        def cluster_telemetry_get(req: Request) -> Response:
            """The one-fetch cluster state: merged tenants + error bound,
            per-role rates, cluster SLO burn, per-sender staleness."""
            tele = getattr(self, "telemetry", None)
            if tele is None:
                return Response({"error": "telemetry not started"}, 503)
            self._telemetry_self_feed()
            n = req.query.get("n")
            try:
                n = int(n) if n else None
            except ValueError:
                return Response({"error": "bad n"}, 400)
            out = tele.snapshot(n=n)
            out["leader"] = self.leader_url()
            from seaweedfs_tpu.stats import profiler as prof_mod

            out["proc"] = prof_mod.PROCESS_TOKEN
            return Response(out)

        @svc.route("GET", r"/cluster/leader")
        def cluster_leader(req: Request) -> Response:
            kind = req.query.get("type", "filer")
            now = time.time()
            live = [
                m for m in self._members.values()
                if m["type"] == kind
                and now - m["last_seen"] < 3 * max(self.topo.pulse_seconds, 5)
            ]
            if not live:
                return Response({"error": f"no live {kind} members"}, 404)
            leader = min(live, key=lambda m: (m["created_ts"], m["address"]))
            return Response({"leader": leader["address"], "type": kind})

        @svc.route("GET", r"/cluster/ps")
        def cluster_ps(req: Request) -> Response:
            now = time.time()
            members = [
                m for m in self._members.values()
                if now - m["last_seen"] < 3 * max(self.topo.pulse_seconds, 5)
            ]
            return Response(
                {
                    "masters": [{"address": self.url, "isLeader": True}],
                    "volumeServers": [
                        {"address": n.url, "dataCenter": n.dc_name(),
                         "rack": n.rack_name()}
                        for n in self.topo.all_nodes()
                    ],
                    "filers": [m for m in members if m["type"] == "filer"],
                    "brokers": [m for m in members if m["type"] == "broker"],
                }
            )

        @svc.route("POST", r"/cluster/lock")
        def cluster_lock(req: Request) -> Response:
            """Exclusive admin-shell lease (`weed/shell` lock/unlock via master
            lease). Re-entrant for the same holder; expires after ttl."""
            p = req.json()
            holder = p.get("holder", "shell")
            ttl = float(p.get("ttl", 30))
            now = time.time()
            if self._admin_lock and self._admin_lock[1] > now and \
                    self._admin_lock[0] != holder:
                return Response(
                    {"error": f"locked by {self._admin_lock[0]}"}, 409
                )
            self._admin_lock = (holder, now + ttl)
            return Response({"ok": True, "holder": holder, "ttl": ttl})

        @svc.route("POST", r"/cluster/unlock")
        def cluster_unlock(req: Request) -> Response:
            holder = req.json().get("holder", "shell")
            if self._admin_lock and self._admin_lock[0] != holder:
                return Response(
                    {"error": f"locked by {self._admin_lock[0]}"}, 409
                )
            self._admin_lock = None
            return Response({"ok": True})

        @svc.route("GET", r"/col/list")
        def col_list(req: Request) -> Response:
            cols: dict[str, int] = {}
            for node in self.topo.all_nodes():
                for v in node.volumes.values():
                    cols[v.collection] = cols.get(v.collection, 0) + 1
            return Response(
                {"collections": [
                    {"name": k, "volumeCount": c} for k, c in sorted(cols.items())
                ]}
            )

        @svc.route("POST", r"/col/delete")
        def col_delete(req: Request) -> Response:
            """Drop every volume of a collection on every server
            (`master_server_handlers_admin.go collectionDeleteHandler`)."""
            self._fl_assign_clear()
            name = req.query.get("collection", "")
            if not name:
                try:
                    name = req.json().get("collection", "")
                except ValueError:
                    pass
            if not name:
                # an empty name would match every default-collection volume —
                # refuse, like the reference's 'collection not found'
                return Response({"error": "collection name required"}, 400)
            deleted = 0
            for node in self.topo.all_nodes():
                for vid, v in list(node.volumes.items()):
                    if v.collection == name:
                        try:
                            post_json(
                                peer_url(node.url) + "/admin/delete_volume",
                                {"volume": vid}, timeout=30,
                            )
                            deleted += 1
                        except Exception:
                            pass
            return Response({"ok": True, "deleted": deleted})

        # --- autonomous maintenance plane (seaweedfs_tpu/maintenance) ---
        @svc.route("GET", r"/debug/maintenance")
        def debug_maintenance(req: Request) -> Response:
            if self.maintenance is None:
                return Response({"configured": False, "enabled": False})
            out = self.maintenance.status()
            out["configured"] = True
            return Response(out)

        @svc.route("POST", r"/maintenance/enable")
        def maintenance_enable(req: Request) -> Response:
            try:
                p = req.json()
            except ValueError:
                p = {}
            # an absent dryRun key preserves the running daemon's mode —
            # only an explicit true/false flips it (a bare re-enable must
            # not silently turn a plan-only daemon into a mutating one)
            dry = p.get("dryRun")
            mode = p.get("rebuildMode")
            if mode is not None and mode not in ("auto", "classic",
                                                 "pipelined"):
                return Response(
                    {"error": f"rebuildMode {mode!r} not"
                     f" auto|classic|pipelined"}, 400)
            lazy = p.get("lazyWindow")
            if lazy is not None:
                try:
                    lazy = float(lazy)
                except (TypeError, ValueError):
                    return Response(
                        {"error": f"lazyWindow {lazy!r} not a number"},
                        400)
                if not (0.0 <= lazy < 3600.0) or lazy != lazy:
                    return Response(
                        {"error": f"lazyWindow {lazy} not in [0, 3600)"},
                        400)
            d = self._ensure_maintenance(
                dry_run=None if dry is None else bool(dry),
                rebuild_mode=mode, lazy_window=lazy,
            )
            return Response({
                "ok": True, "enabled": True, "dry_run": d.dry_run,
                "interval": d.interval, "rebuild_mode": d.rebuild_mode,
                "lazy_window": d.scheduler.lazy_window,
            })

        @svc.route("POST", r"/maintenance/disable")
        def maintenance_disable(req: Request) -> Response:
            if self.maintenance is not None:
                self.maintenance.enabled = False
            return Response({"ok": True, "enabled": False})

        @svc.route("POST", r"/maintenance/scan")
        def maintenance_scan(req: Request) -> Response:
            """Force a scan now (`cluster.maintenance -now [task]`)."""
            if self.maintenance is None:
                return Response({"error": "maintenance not configured"}, 400)
            try:
                p = req.json()
            except ValueError:
                p = {}
            task = p.get("task")
            if task is not None:
                from seaweedfs_tpu.maintenance import TASK_TYPES

                if task not in TASK_TYPES:
                    return Response(
                        {"error": f"unknown task type {task!r}"
                         f" (known: {sorted(TASK_TYPES)})"}, 400)
            offered = self.maintenance.scan_now(
                None if task is None else (task,)
            )
            return Response({"ok": True, "offered": offered})

        @svc.route("POST", r"/vol/vacuum/disable")
        def vacuum_disable(req: Request) -> Response:
            self.vacuum_enabled = False
            return Response({"ok": True, "vacuum": "disabled"})

        @svc.route("POST", r"/vol/vacuum/enable")
        def vacuum_enable(req: Request) -> Response:
            self.vacuum_enabled = True
            return Response({"ok": True, "vacuum": "enabled"})

        @svc.route("GET", r"/vol/vacuum")
        def vol_vacuum(req: Request) -> Response:
            self._fl_assign_clear()  # volumes flip readonly during compaction
            threshold = float(req.query.get("garbageThreshold", self.garbage_threshold))
            old = self.garbage_threshold
            self.garbage_threshold = threshold
            try:
                self._vacuum_check()
            finally:
                self.garbage_threshold = old
            return Response({"ok": True})
