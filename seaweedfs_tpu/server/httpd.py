"""Minimal threaded HTTP service kit (routing + JSON + multipart).

Built on http.server.ThreadingHTTPServer — the control plane is not the
benchmark surface; the data plane stays on big bodies where Python's
overhead amortizes.
"""

from __future__ import annotations

import json
import re
import os
import socket
import threading
import urllib.parse
import urllib.request

from seaweedfs_tpu.security import tls as _tls
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, match: re.Match) -> None:
        self.handler = handler
        self.match = match
        parsed = urllib.parse.urlparse(handler.path)
        self.path = parsed.path
        self.raw_query = parsed.query  # exact bytes: fastlane profile keys
        self.query = {
            k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()
        }
        self.headers = handler.headers
        self.method = handler.command
        ca = handler.client_address
        # AF_UNIX peers have no address tuple (same-host by construction)
        self.remote_ip = ca[0] if isinstance(ca, tuple) and ca else "unix"

        self._body: bytes | None = None

    @property
    def body(self) -> bytes:
        if self._body is None:
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self.handler.rfile.read(length) if length else b""
        return self._body

    def json(self) -> dict:
        if not self.body:
            return {}
        return json.loads(self.body)

    def multipart_file(self) -> tuple[str, str, bytes] | None:
        """Parse the first file part of a multipart/form-data body ->
        (filename, content_type, data); None if not multipart."""
        ctype = self.headers.get("Content-Type", "")
        m = re.search(r'boundary="?([^";]+)"?', ctype)
        if "multipart/form-data" not in ctype or not m:
            return None
        boundary = m.group(1).encode()
        parts = self.body.split(b"--" + boundary)
        for part in parts:
            if b"\r\n\r\n" not in part:
                continue
            head, _, data = part.partition(b"\r\n\r\n")
            if data.endswith(b"\r\n"):
                data = data[:-2]
            head_s = head.decode("utf-8", "replace")
            fm = re.search(r'filename="([^"]*)"', head_s)
            if fm is None:
                continue
            cm = re.search(r"Content-Type:\s*([^\r\n]+)", head_s, re.I)
            return fm.group(1), (cm.group(1).strip() if cm else ""), data
        return None


    def multipart_form(self) -> tuple[dict, tuple[str, str, bytes] | None]:
        """Parse a multipart/form-data body -> ({field: value}, file_part)
        where file_part is (filename, content_type, data) for the part named
        "file" (or any part carrying a filename). Browser-POST uploads
        (S3 post-policy) arrive this way."""
        ctype = self.headers.get("Content-Type", "")
        m = re.search(r'boundary="?([^";]+)"?', ctype)
        fields: dict = {}
        if "multipart/form-data" not in ctype or not m:
            return fields, None
        boundary = m.group(1).encode()
        file_part = None
        for part in self.body.split(b"--" + boundary):
            if b"\r\n\r\n" not in part:
                continue
            head, _, data = part.partition(b"\r\n\r\n")
            if data.endswith(b"\r\n"):
                data = data[:-2]
            head_s = head.decode("utf-8", "replace")
            nm = re.search(r'name="([^"]*)"', head_s)
            if nm is None:
                continue
            fm = re.search(r'filename="([^"]*)"', head_s)
            if fm is not None:
                cm = re.search(r"Content-Type:\s*([^\r\n]+)", head_s, re.I)
                file_part = (
                    fm.group(1), (cm.group(1).strip() if cm else ""), data
                )
            else:
                fields[nm.group(1)] = data.decode("utf-8", "replace")
        return fields, file_part


class Response:
    def __init__(
        self,
        body: bytes | str | dict | None = None,
        status: int = 200,
        headers: dict | None = None,
        content_type: str | None = None,
    ) -> None:
        self.status = status
        self.headers = dict(headers or {})
        if isinstance(body, dict):
            self.body = json.dumps(body).encode()
            self.headers.setdefault("Content-Type", "application/json")
        elif isinstance(body, str):
            self.body = body.encode()
            self.headers.setdefault("Content-Type", "text/plain; charset=utf-8")
        else:
            self.body = body or b""
            if content_type:
                self.headers.setdefault("Content-Type", content_type)


class HTTPService:
    """Route table + server lifecycle. Routes are (method, regex) -> fn(req)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.routes: list[tuple[str, re.Pattern, Callable[[Request], Response]]] = []
        self.guard = None  # security.Guard — 403s non-whitelisted IPs when set
        self.metrics_role: str | None = None  # instrument requests when set
        self.trace_role: str | None = None  # record request spans when set
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def enable_metrics(self, role: str, serve_route: bool = True) -> None:
        """Count + time every request under this role label and (unless the
        main port has a catch-all route, like the filer) serve Prometheus
        text format on /metrics (`weed/stats/metrics.go`)."""
        from seaweedfs_tpu.stats import default_registry

        self.metrics_role = role
        reg = default_registry()
        self._m_total = reg.counter(
            "SeaweedFS_http_request_total", "requests", ("role", "method", "code")
        )
        # exemplars: each latency sample carries the active trace id, so
        # a cluster.top p99 row links straight to the trace that landed
        # in that bucket (/debug/traces?id= point lookup)
        self._m_seconds = reg.histogram(
            "SeaweedFS_http_request_seconds", "request latency",
            ("role", "method"), exemplars=True,
        )
        if serve_route:
            @self.route("GET", r"/metrics")
            def metrics(req: Request) -> Response:
                return Response(
                    reg.render().encode(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
        # process identity: start time (the history ring's restart signal)
        # and a build_info series per role — cluster.top's uptime/version
        import seaweedfs_tpu
        from seaweedfs_tpu.stats import alerts as alerts_mod
        from seaweedfs_tpu.stats import history as history_mod
        from seaweedfs_tpu.stats.metrics import PROCESS_START_TIME

        # whole seconds: an integer renders exactly in the exposition
        # (uptime math off a digit-clipped float put starts in the future)
        reg.gauge(
            "SeaweedFS_process_start_time_seconds",
            "unix time this process started (counter-reset detection)",
        ).set(int(PROCESS_START_TIME))
        reg.gauge(
            "SeaweedFS_build_info",
            "constant 1, labeled with the build version and server role",
            ("version", "role"),
        ).labels(seaweedfs_tpu.__version__, role).set(1)
        # the self-scraping history ring + alert engine + flight recorder
        # start with the first metered server in the process (library
        # imports pay nothing)
        from seaweedfs_tpu.stats import events as events_mod

        from seaweedfs_tpu.stats import heat as heat_mod
        from seaweedfs_tpu.stats import usage as usage_mod

        history_mod.default_history().start()
        alerts_mod.engine()
        events_mod.enable()
        usage_mod.enable()
        heat_mod.enable()
        self.enable_tracing(role)

    def enable_tracing(self, role: str) -> None:
        """Record a span for every request under this role (inheriting the
        caller's trace via X-Sw-Trace-Id/X-Sw-Span) and serve the shared
        ring buffer on /debug/traces + /debug/requests. Idempotent. Like
        the request histograms, spans cover the Python path only — requests
        the native engine serves never reach _dispatch."""
        if self.trace_role is not None:
            return
        self.trace_role = role
        _register_debug_routes(self)

    def serve_debug_routes(self) -> None:
        """Expose /debug/traces + /debug/requests without per-request
        spans (standalone listeners like MetricsService)."""
        _register_debug_routes(self)

    def route(self, method: str, pattern: str):
        compiled = re.compile(pattern)

        def deco(fn):
            self.routes.append((method, compiled, fn))
            return fn

        return deco

    def _dispatch(self, handler: BaseHTTPRequestHandler) -> None:
        import time as _time

        start = _time.monotonic()
        path = urllib.parse.urlparse(handler.path).path
        span = None
        if self.trace_role is not None:
            from seaweedfs_tpu.stats import trace as _trace

            span = _trace.begin_server_span(
                self.trace_role, handler.command, path, handler.headers
            )
        peer_ok = True
        # unix-socket peers are same-host-trusted by construction: neither
        # the mTLS CN gate (no TLS on AF_UNIX) nor the IP guard applies
        if getattr(self, "_tls_on", False) and not getattr(
                handler, "_unix_peer", False):
            try:
                peer_ok = _tls.peer_allowed(
                    handler.connection.getpeercert(), self._allowed_cns
                )
            except Exception:
                peer_ok = False
        if not peer_ok:
            req = None
            resp = Response({"error": "client certificate CN not allowed"}, 403)
        elif self.guard is not None and isinstance(
            handler.client_address, tuple
        ) and handler.client_address and not self.guard.is_allowed(
            handler.client_address[0]
        ):  # unix-socket peers are same-host: the IP whitelist is N/A
            req = None
            resp = Response({"error": "forbidden"}, 403)
        else:
            for method, pattern, fn in self.routes:
                if method != handler.command:
                    continue
                m = pattern.fullmatch(path)
                if m is None:
                    continue
                req = Request(handler, m)
                try:
                    resp = fn(req)
                except Exception as e:  # uniform JSON error surface
                    from seaweedfs_tpu.util.sentry import capture_exception

                    capture_exception(e, path=path, method=handler.command)
                    resp = Response({"error": str(e)}, status=500)
                break
            else:
                req = None
                resp = Response({"error": f"no route {handler.command} {path}"}, 404)
        if self.metrics_role is not None:
            # a QoS shed (X-Sw-Qos-Reason rides every one) is a
            # deliberate refusal AHEAD of service, not a service
            # failure: counting its 503 in http_request_total would
            # burn the very availability SLO the actuator watches and
            # the shed would sustain itself — locally and cluster-wide,
            # since telemetry frames ship these counters to the master.
            # SeaweedFS_qos_shed_total is the canonical record.
            if "X-Sw-Qos-Reason" not in resp.headers:
                self._m_total.labels(
                    self.metrics_role, handler.command, str(resp.status)
                ).inc()
                self._m_seconds.labels(
                    self.metrics_role, handler.command
                ).observe(_time.monotonic() - start)
        if span is not None:
            from seaweedfs_tpu.stats import trace as _trace

            resp.headers.setdefault(_trace.TRACE_HEADER, span.trace_id)
            _trace.end_server_span(span, resp.status)
        # drain an unread request body before responding — on a keep-alive
        # connection leftover body bytes would desynchronize the next request
        length = int(handler.headers.get("Content-Length") or 0)
        if length and (req is None or req._body is None):
            try:
                handler.rfile.read(length)
            except Exception:
                pass
        try:
            handler.send_response(resp.status)
            body = resp.body
            # a handler may pre-set Content-Length (HEAD responses advertise
            # the entity size while sending no body)
            if "Content-Length" not in resp.headers:
                handler.send_header("Content-Length", str(len(body)))
            for k, v in resp.headers.items():
                handler.send_header(k, v)
            handler.end_headers()
            if handler.command != "HEAD":
                handler.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    _SWITCH_INTERVAL_SET = False

    def start(self) -> None:
        # Many handler threads on few cores convoy badly on the default 5ms
        # GIL switch interval (p99 explodes, throughput collapses ~2-4x on a
        # single-core host). Request serving is IO-and-syscall heavy and the
        # compute kernels release the GIL in C, so a sub-ms interval is the
        # right trade for every server in this process. Override:
        # SEAWEEDFS_TPU_SWITCH_INTERVAL (seconds; "0" leaves the default).
        if not HTTPService._SWITCH_INTERVAL_SET:
            HTTPService._SWITCH_INTERVAL_SET = True
            import sys as _sys

            val = os.environ.get("SEAWEEDFS_TPU_SWITCH_INTERVAL", "0.0005")
            try:
                if float(val) > 0:
                    _sys.setswitchinterval(float(val))
            except ValueError:
                pass
        service = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True  # response headers+body are
            # separate writes; Nagle would stall keep-alive clients ~40ms

            def log_message(self, fmt, *args):  # silent
                pass

            def _handle(self):
                service._dispatch(self)

            do_GET = do_POST = do_PUT = do_DELETE = do_HEAD = _handle
            # WebDAV verbs (webdav_server.go surface)
            do_OPTIONS = do_PROPFIND = do_PROPPATCH = do_MKCOL = _handle
            do_MOVE = do_COPY = do_LOCK = do_UNLOCK = _handle

        # plain_backend: this listener sits BEHIND the native engine, which
        # terminates mTLS and enforces the CN gate itself; serve plaintext
        # on loopback only (never on an external interface)
        plain_backend = getattr(self, "plain_backend", False)
        ctx = None if plain_backend else _tls.server_context()
        self._tls_on = ctx is not None
        self._allowed_cns = _tls.allowed_cn_patterns()
        bind_host = "127.0.0.1" if plain_backend else self.host
        if ctx is None:
            self._httpd = ThreadingHTTPServer((bind_host, self.port), Handler)
        else:
            # mTLS on every listener (`weed/security/tls.go` semantics).
            # The accepted socket is wrapped WITHOUT handshaking: the
            # handshake runs lazily on first read inside the per-connection
            # handler thread, so a stalled client cannot pin the accept loop.
            class TLSHTTPServer(ThreadingHTTPServer):
                def get_request(inner):
                    sock, addr = inner.socket.accept()
                    sock.settimeout(60)
                    return (
                        ctx.wrap_socket(
                            sock, server_side=True,
                            do_handshake_on_connect=False,
                        ),
                        addr,
                    )

            self._httpd = TLSHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._handler_cls = Handler
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def enable_unix_socket(self, path: str) -> None:
        """Extra AF_UNIX listener sharing this service's routes — the
        `-filer.localSocket` feature (`weed/command/filer.go`): same-host
        clients (mounts especially) skip the TCP stack. The unix path is
        same-host-trusted, like the reference's — no TLS/guard applies,
        and requests bypass any engine front (they reach Python directly).
        Call after start()."""
        import socketserver

        class handler(self._handler_cls):
            # TCP_NODELAY does not exist on AF_UNIX sockets
            disable_nagle_algorithm = False
            _unix_peer = True  # exempt from the mTLS CN gate (same-host)

        class UnixHTTPServer(ThreadingHTTPServer):
            address_family = socket.AF_UNIX

            def server_bind(inner):
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    pass
                # skip HTTPServer.server_bind: it unpacks server_address
                # as (host, port), which a unix path is not
                socketserver.TCPServer.server_bind(inner)
                inner.server_name = "localhost"
                inner.server_port = 0

        srv = UnixHTTPServer(path, handler)
        self._unix_httpd = srv
        self._unix_path = path
        threading.Thread(target=srv.serve_forever, daemon=True).start()

    @property
    def unix_url(self) -> str | None:
        """http+unix:// URL for the local-socket listener, or None."""
        path = getattr(self, "_unix_path", None)
        if path is None:
            return None
        return "http+unix://" + urllib.parse.quote(path, safe="")

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        unix = getattr(self, "_unix_httpd", None)
        if unix is not None:
            unix.shutdown()
            unix.server_close()
            self._unix_httpd = None
            try:
                os.unlink(self._unix_path)
            except OSError:
                pass
            self._unix_path = None  # unix_url must stop advertising it

    @property
    def url(self) -> str:
        scheme = "https" if getattr(self, "_tls_on", False) else "http"
        return f"{scheme}://{self.host}:{self.port}"


def _since_param(query: dict):
    """Parse the shared `?since=` incremental cursor (None when absent;
    ValueError on anything non-finite — the routes turn that into a 400,
    never an unhandled 500). Both /debug/metrics/history and
    /debug/events use this: pass the previous response's unrounded
    `watermark` back and only strictly-newer items ship."""
    import math

    since = query.get("since")
    if since is None:
        return None
    since = float(since)
    if not math.isfinite(since):
        raise ValueError(since)
    return since


def _register_debug_routes(service: "HTTPService") -> None:
    """`/debug/traces` (recent finished traces, JSON; ?limit= & ?min_ms=),
    `/debug/requests` (in-flight spans; ?limit=), and the profiling
    surface: `/debug/pprof/profile` (?seconds= & ?hz=; collapsed-stack
    text, ?format=json for the structured form), `/debug/pprof/threads`
    (instant all-thread dump), `/debug/pprof/device` (jax.profiler trace
    tarball; 501 without jax), plus the PR-4 history/alert surface:
    `/debug/metrics/history` (?family= & ?window= & ?samples=; the
    self-scraped ring with windowed counter rates) and `/debug/alerts`
    (?window=; every rule's firing state). Registered by enable_tracing, so on
    catch-all namespaces (the filer) they precede — and shadow —
    same-named file paths. Malformed numeric query params are a 400 with
    a JSON error, never an unhandled 500."""
    from seaweedfs_tpu.stats import trace as trace_mod

    col = trace_mod.collector()

    @service.route("GET", r"/debug/traces")
    def debug_traces(req: Request) -> Response:
        import math

        trace_id = req.query.get("id")
        if trace_id is not None:
            # exact-lookup (?id=): exemplar links and cluster.why resolve
            # one trace without paging the whole ring. Malformed ids are
            # a 400 with a JSON error, consistent with the other routes.
            if not re.fullmatch(r"[0-9a-f]{1,32}", trace_id):
                return Response(
                    {"error": f"malformed trace id {trace_id!r}"
                              " (lowercase hex)"}, 400
                )
            spans = col.trace_spans(trace_id)
            return Response({
                "trace_id": trace_id,
                "found": bool(spans),
                "spans": spans,
            })
        try:
            limit = int(req.query.get("limit", 20))
            min_ms = float(req.query.get("min_ms", 0))
            if not math.isfinite(min_ms):
                raise ValueError(min_ms)
        except ValueError:
            return Response(
                {"error": "limit/min_ms must be finite numbers"}, 400
            )
        return Response({
            "traces": col.traces(limit=limit, min_ms=min_ms),
            "capacity": col.max_spans,
        })

    @service.route("GET", r"/debug/requests")
    def debug_requests(req: Request) -> Response:
        try:
            limit = int(req.query.get("limit", 0))
        except ValueError:
            return Response({"error": "limit must be numeric"}, 400)
        in_flight = col.inflight()
        if limit > 0:
            in_flight = in_flight[:limit]
        return Response({"in_flight": in_flight})

    @service.route("GET", r"/debug/pprof/profile")
    def debug_pprof_profile(req: Request) -> Response:
        from seaweedfs_tpu.stats import profiler as prof_mod

        try:
            seconds = prof_mod.clamp_seconds(req.query.get("seconds", 2))
            hz = int(req.query.get("hz", 100))
        except ValueError:
            return Response({"error": "seconds/hz must be finite numbers"}, 400)
        try:
            out = prof_mod.profile(seconds=seconds, hz=hz)
        except prof_mod.ProfilerBusy as e:
            return Response({"error": str(e)}, 429)
        out["role"] = service.trace_role or service.metrics_role
        out["proc"] = prof_mod.PROCESS_TOKEN  # cluster.profile dedup key
        if req.query.get("format") == "json":
            return Response(out)
        return Response(prof_mod.render_collapsed(out["stacks"]))

    @service.route("GET", r"/debug/pprof/threads")
    def debug_pprof_threads(req: Request) -> Response:
        from seaweedfs_tpu.stats import profiler as prof_mod

        return Response({
            "role": service.trace_role or service.metrics_role,
            "threads": prof_mod.threads_dump(),
        })

    @service.route("GET", r"/debug/metrics/history")
    def debug_metrics_history(req: Request) -> Response:
        import math

        from seaweedfs_tpu.stats import history as history_mod
        from seaweedfs_tpu.stats import profiler as prof_mod

        hist = history_mod.default_history()
        try:
            window = float(req.query.get("window", hist.retention_seconds))
            max_samples = int(req.query.get("samples", 16))
            if not math.isfinite(window) or window <= 0:
                raise ValueError(window)
            # ?since=<mono_ts>: incremental cursor — ship only samples
            # after the caller's watermark (the previous response's
            # "watermark" field), not the full ring every poll
            since = _since_param(req.query)
        except ValueError:
            return Response(
                {"error": "window/samples/since must be finite numbers"},
                400,
            )
        hist.ensure_fresh()
        from seaweedfs_tpu.stats import default_registry as _dr

        return Response({
            "interval": hist.interval,
            "slots": hist.slots,
            "window": window,
            "scrapes": hist.scrapes_total,
            # pass this back as ?since= for the next incremental poll.
            # Unrounded on purpose: sample timestamps are rounded to 3
            # decimals for display, so a rounded-DOWN watermark could sit
            # below the exact stored timestamp of the scrape it names and
            # re-ship that scrape's samples on the next poll.
            "watermark": hist.last_scrape,
            "proc": prof_mod.PROCESS_TOKEN,  # cluster.top dedup key
            "series": hist.snapshot(
                family=req.query.get("family") or None,
                window=window,
                max_samples=max(0, max_samples),
                since=since,
            ),
            # histogram exemplars ride here, not in the 0.0.4 text format
            # (which has no exemplar syntax): per (labels, upper bucket),
            # the freshest sample's trace id — the p99 -> trace join
            "exemplars": _dr().exemplars(
                family=req.query.get("family") or None
            ),
        })

    @service.route("GET", r"/debug/alerts")
    def debug_alerts(req: Request) -> Response:
        import math

        from seaweedfs_tpu.stats import alerts as alerts_mod
        from seaweedfs_tpu.stats import profiler as prof_mod

        window = req.query.get("window")
        try:
            if window is not None:
                window = float(window)
                if not math.isfinite(window) or window <= 0:
                    raise ValueError(window)
        except ValueError:
            return Response(
                {"error": "window must be a positive finite number"}, 400
            )
        out = alerts_mod.engine().status(window=window)
        out["proc"] = prof_mod.PROCESS_TOKEN
        return Response(out)

    @service.route("GET", r"/debug/events")
    def debug_events(req: Request) -> Response:
        """The flight-recorder journal (stats/events.py): typed events
        with correlation keys, filterable by ?type= / ?volume= /
        ?trace= / ?since= (+ ?limit=). `?since=` is the same strictly-
        after cursor /debug/metrics/history carries: pass the previous
        response's unrounded `watermark` back and a watch-mode poller
        stops re-shipping the whole ring. cluster.why fans this out
        across every node and assembles the causal timeline."""
        from seaweedfs_tpu.stats import events as events_mod
        from seaweedfs_tpu.stats import profiler as prof_mod

        q = req.query
        try:
            limit = int(q.get("limit", 256))
            volume = int(q["volume"]) if "volume" in q else None
            since = _since_param(q)
        except ValueError:
            return Response(
                {"error": "limit/volume/since must be finite numbers"}, 400
            )
        type_ = q.get("type") or None
        if type_ is not None and type_ not in events_mod.EVENT_TYPES:
            return Response(
                {"error": f"unknown event type {type_!r}",
                 "types": sorted(events_mod.EVENT_TYPES)}, 400
            )
        rec = events_mod.recorder()
        return Response({
            "proc": prof_mod.PROCESS_TOKEN,  # cluster.why dedup key
            "role": service.trace_role or service.metrics_role,
            "enabled": rec.enabled,
            "capacity": rec.capacity,
            "recorded": rec.recorded_total,
            "dropped": rec.dropped_total,
            # pass back as ?since= next poll. Unrounded on purpose: event
            # ts are rounded to 6 decimals for display, and a rounded-
            # DOWN watermark would re-ship its own newest event.
            "watermark": rec.last_wall,
            "events": rec.events(type=type_, volume=volume,
                                 trace=q.get("trace") or None,
                                 since=since,
                                 collection=q.get("collection") or None,
                                 limit=limit),
        })

    @service.route("GET", r"/debug/usage")
    def debug_usage(req: Request) -> Response:
        """The bounded-cardinality tenant accountant (stats/usage.py):
        top-K collections by requests/bytes/errors, the `_other` fold,
        and the sketch's exported error bound. ?n= caps the tenant rows."""
        from seaweedfs_tpu.stats import profiler as prof_mod
        from seaweedfs_tpu.stats import usage as usage_mod

        try:
            n = int(req.query["n"]) if "n" in req.query else None
            if n is not None and n < 1:
                raise ValueError(n)
        except ValueError:
            return Response({"error": "n must be a positive integer"}, 400)
        out = usage_mod.accountant().snapshot(n=n)
        out["proc"] = prof_mod.PROCESS_TOKEN
        out["role"] = service.trace_role or service.metrics_role
        return Response(out)

    @service.route("GET", r"/debug/heat")
    def debug_heat(req: Request) -> Response:
        """The heat engine's view (stats/heat.py): per-volume heat
        scores, per-node/dir days-to-full forecasts, and — on a master —
        the heartbeat-fed collection/node rollup. ?n= caps each list."""
        from seaweedfs_tpu.stats import heat as heat_mod
        from seaweedfs_tpu.stats import profiler as prof_mod

        try:
            n = int(req.query["n"]) if "n" in req.query else None
            if n is not None and n < 1:
                raise ValueError(n)
        except ValueError:
            return Response({"error": "n must be a positive integer"}, 400)
        out = heat_mod.engine().snapshot()
        rollup_colls, rollup_nodes = [], []
        for ru in heat_mod.rollups():
            snap = ru.snapshot()
            rollup_colls.extend(snap["collections"])
            rollup_nodes.extend(snap["nodes"])
        if rollup_colls or rollup_nodes:
            out["collections"] = rollup_colls
            out["nodes"] = rollup_nodes
        if n is not None:
            for k in ("volumes", "forecast", "collections", "nodes"):
                if k in out:
                    out[k] = out[k][:n]
        out["proc"] = prof_mod.PROCESS_TOKEN
        out["role"] = service.trace_role or service.metrics_role
        return Response(out)

    @service.route("GET", r"/qos/limits")
    def qos_limits_get(req: Request) -> Response:
        """This process's admission-control state (qos/admission.py):
        limits, gates, queue bounds, admitted/queued/shed counters and
        live bucket levels. `/debug/qos` is the same payload."""
        from seaweedfs_tpu.qos import admission as qos_mod
        from seaweedfs_tpu.stats import profiler as prof_mod

        out = qos_mod.controller().status()
        act = None
        from seaweedfs_tpu.qos import actuator as act_mod

        a = act_mod.actuator()
        if a is not None:
            act = {"level": a.level, "burn": round(a.last_burn, 3),
                   "fast_burn": a.fast_burn}
        out["actuator"] = act
        out["proc"] = prof_mod.PROCESS_TOKEN
        out["role"] = service.trace_role or service.metrics_role
        return Response(out)

    service.route("GET", r"/debug/qos")(qos_limits_get)

    @service.route("POST", r"/qos/limits")
    def qos_limits_post(req: Request) -> Response:
        """Runtime limit updates for THIS process — the cluster.qos verb
        fans this out across discovered gateways. Body (all optional):
          {"limits": {"tenant-a": 100, "tenant-b": [50, 200]},
           "default": 25, "queue_depth": 32, "queue_wait": 0.25,
           "spec": "tenant-a=100,*=25"}
        `limits`/`spec` replace the whole table (declarative, like the
        CLI flag); values are rps or [rps, burst]. Posting any config
        arms admission on a metered server."""
        from seaweedfs_tpu.qos import admission as qos_mod

        p = req.json()
        ctl = qos_mod.controller()
        try:
            limits, default = p.get("limits"), p.get("default")
            if "spec" in p:
                limits, default = qos_mod.parse_limits_spec(p["spec"])
            ctl.set_limits(limits=limits, default=default,
                           queue_depth=p.get("queue_depth"),
                           queue_wait=p.get("queue_wait"))
            qos_mod.enable()
        except (ValueError, TypeError) as e:
            return Response({"error": str(e)}, 400)
        return Response({"ok": True, "armed": ctl.armed,
                         "limits": ctl.status()["limits"],
                         "default": ctl.status()["default"]})

    @service.route("GET", r"/debug/faults")
    def debug_faults_get(req: Request) -> Response:
        from seaweedfs_tpu.util import faults as faults_mod

        snap = faults_mod.snapshot()
        return Response({
            "points": snap,
            "declared": list(faults_mod.ALL_POINTS),
            "armed": sum(1 for p in snap if p["armed"] is not None),
        })

    @service.route("POST", r"/debug/faults")
    def debug_faults_post(req: Request) -> Response:
        """Runtime fault arming for THIS process — the cluster.faults
        verb fans this out across discovered nodes. Body:
          {"action": "arm", "point": ..., "mode": ...,
           "rate"/"ms"/"frac"/"count"/"key": ...}
          {"action": "disarm", "point": ...}
          {"action": "disarm_all"}
        Engine-side points additionally try the optional
        sw_fl_inject_fault ABI via the serving fastlane when one exists
        (hasattr-degraded: absence is reported, never an error)."""
        from seaweedfs_tpu.util import faults as faults_mod

        if not faults_mod.runtime_arming_enabled():
            # mutating route on every role: 403 unless the operator
            # opted this process in (-faults flag, even bare, or
            # SEAWEEDFS_TPU_FAULTS=1) — a reachable port must not be
            # enough to arm torn writes on a production server
            return Response(
                {"error": "fault injection disabled for this process"
                          " (start with -faults or SEAWEEDFS_TPU_FAULTS=1)"},
                403,
            )
        p = req.json()
        action = p.get("action", "arm")
        try:
            if action == "arm":
                spec = faults_mod.arm(
                    p["point"], p["mode"],
                    rate=p.get("rate", 1.0), ms=p.get("ms", 0.0),
                    frac=p.get("frac", 0.5), count=p.get("count", -1),
                    key=p.get("key", ""), after=p.get("after", 0),
                )
                return Response({"ok": True, "point": p["point"],
                                 "armed": spec.to_dict()})
            if action == "disarm":
                return Response({
                    "ok": True, "point": p["point"],
                    "was_armed": faults_mod.disarm(p["point"]),
                })
            if action == "disarm_all":
                return Response({"ok": True,
                                 "disarmed": faults_mod.disarm_all()})
        except (KeyError, ValueError) as e:
            return Response({"error": str(e)}, 400)
        return Response({"error": f"unknown action {action!r}"}, 400)

    @service.route("GET", r"/debug/pprof/device")
    def debug_pprof_device(req: Request) -> Response:
        from seaweedfs_tpu.stats import profiler as prof_mod

        try:
            seconds = prof_mod.clamp_seconds(req.query.get("seconds", 2))
        except ValueError:
            return Response({"error": "seconds must be a finite number"}, 400)
        try:
            data = prof_mod.device_trace(seconds)
        except prof_mod.DeviceProfilerUnavailable as e:
            return Response({"error": str(e)}, 501)
        except prof_mod.ProfilerBusy as e:
            return Response({"error": str(e)}, 429)
        return Response(
            data,
            content_type="application/gzip",
            headers={
                "Content-Disposition": 'attachment; filename="jax-trace.tar.gz"'
            },
        )


class MetricsService(HTTPService):
    """Standalone /metrics listener for servers whose main port has a
    catch-all namespace (the filer) — the reference's `-metricsPort`."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__(host, port)
        from seaweedfs_tpu.stats import default_registry

        reg = default_registry()

        @self.route("GET", r"/metrics")
        def metrics(req: Request) -> Response:
            return Response(
                reg.render().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )

        self.serve_debug_routes()


def peer_url(hostport: str) -> str:
    """Scheme-qualify another node's advertised host:port. Heartbeats and
    lookups carry bare addresses; when process-wide mTLS is configured
    (`security.tls`), every peer listener is TLS too."""
    if hostport.startswith(("http://", "https://")):
        return hostport
    scheme = "https" if _tls.client_context() is not None else "http"
    return f"{scheme}://{hostport}"


# --- tiny client helpers ----------------------------------------------------
# Every outbound call in this repo routes through these helpers (or
# PooledHTTP); the default timeout is the shared RetryPolicy one so no
# call anywhere can hang a worker forever — callers pass their own only
# to tighten (heartbeats) or loosen (volume copies).
from seaweedfs_tpu.util.retry import DEFAULT_TIMEOUT as _DEFAULT_TIMEOUT


def http_request(
    method: str,
    url: str,
    body: bytes | None = None,
    headers: dict | None = None,
    timeout: float = _DEFAULT_TIMEOUT,
) -> tuple[int, dict, bytes]:
    from seaweedfs_tpu.stats import trace as _trace

    headers = _trace.with_trace_headers(headers)
    if url.startswith("http+unix://"):
        return _unix_http_request(method, url, body, headers, timeout)
    req = urllib.request.Request(url, data=body, method=method)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    ctx = _tls.client_context() if url.startswith("https:") else None
    try:
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _unix_http_request(
    method: str, url: str, body: bytes | None, headers: dict | None,
    timeout: float,
) -> tuple[int, dict, bytes]:
    """HTTP over a unix domain socket. URL form
    `http+unix://<percent-encoded-socket-path><request-path>` — the same
    convention requests-unix-socket/docker clients use. Server side:
    HTTPService.enable_unix_socket (`-filer.localSocket`)."""
    import http.client
    import socket as _socket

    rest = url[len("http+unix://"):]
    sock_quoted, _, path_qs = rest.partition("/")
    sock_path = urllib.parse.unquote(sock_quoted)

    class _Conn(http.client.HTTPConnection):
        def __init__(self) -> None:
            super().__init__("localhost", timeout=timeout)

        def connect(self) -> None:
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.settimeout(timeout)
            s.connect(sock_path)
            self.sock = s

    conn = _Conn()
    try:
        conn.request(method, "/" + path_qs, body=body,
                     headers=dict(headers or {}))
        resp = conn.getresponse()
        return resp.status, dict(resp.headers), resp.read()
    finally:
        conn.close()


def get_json(url: str, timeout: float = _DEFAULT_TIMEOUT) -> dict:
    status, _, body = http_request("GET", url, timeout=timeout)
    data = json.loads(body) if body else {}
    if status >= 400:
        raise IOError(f"GET {url} -> {status}: {data}")
    return data


def post_json(url: str, payload: dict | None = None,
              timeout: float = _DEFAULT_TIMEOUT) -> dict:
    body = json.dumps(payload or {}).encode()
    status, _, out = http_request(
        "POST", url, body, {"Content-Type": "application/json"}, timeout
    )
    data = json.loads(out) if out else {}
    if status >= 400:
        raise IOError(f"POST {url} -> {status}: {data}")
    return data


class PooledHTTP:
    """Thread-local keep-alive connections per endpoint.

    urllib opens (and tears down) a TCP connection per call, so hot
    small-request paths — `weed benchmark`'s 1KB writes/reads, replication
    fan-outs — end up measuring connection setup instead of the server.
    The reference's Go clients all reuse connections; this is the
    equivalent for the data-plane hot paths. Honors process mTLS."""

    def __init__(self, timeout: float = _DEFAULT_TIMEOUT) -> None:
        import weakref

        self._tl = threading.local()
        self.timeout = timeout
        # weak: a dead handler thread's conns must not be pinned forever —
        # GC of its thread-local dict lets the sockets finalize
        self._all = weakref.WeakSet()
        self._all_mu = threading.Lock()

    def request(
        self,
        method: str,
        url: str,
        body: bytes | None = None,
        headers: dict | None = None,
        idempotent: bool = False,
    ) -> tuple[int, dict, bytes]:
        import http.client
        import ssl as _ssl

        from seaweedfs_tpu.stats import trace as _trace

        headers = _trace.with_trace_headers(headers)
        u = urllib.parse.urlsplit(url)
        key = f"{u.scheme}://{u.netloc}"
        pool = getattr(self._tl, "conns", None)
        if pool is None:
            pool = self._tl.conns = {}
        path = u.path + (f"?{u.query}" if u.query else "")
        last: Exception | None = None
        # stale-socket retry only when a re-send cannot duplicate a side
        # effect: GET/HEAD always; writes only when the caller declares
        # them idempotent (fid-addressed chunk uploads are)
        attempts = (0, 1) if method in ("GET", "HEAD") or idempotent else (0,)
        for attempt in attempts:
            conn = pool.get(key)
            if conn is None:
                if u.scheme == "https":
                    ctx = _tls.client_context() or _ssl.create_default_context()
                    conn = http.client.HTTPSConnection(
                        u.netloc, timeout=self.timeout, context=ctx
                    )
                else:
                    conn = http.client.HTTPConnection(
                        u.netloc, timeout=self.timeout
                    )
                pool[key] = conn
                with self._all_mu:
                    self._all.add(conn)
            try:
                if conn.sock is None:
                    conn.connect()
                    # headers and body go out as separate writes; without
                    # TCP_NODELAY Nagle + delayed ACK adds ~40ms per request
                    conn.sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                conn.request(method, path, body=body, headers=headers or {})
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, dict(resp.headers), data
            except (http.client.HTTPException, OSError) as e:
                last = e
                conn.close()
                pool.pop(key, None)
                with self._all_mu:
                    self._all.discard(conn)
        raise last  # type: ignore[misc]

    def close(self) -> None:
        """Close every connection this pool ever opened, across threads
        (worker threads exit without closing their thread-locals)."""
        import weakref

        with self._all_mu:
            conns = list(self._all)
            self._all = weakref.WeakSet()
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass
        pool = getattr(self._tl, "conns", None)
        if pool:
            pool.clear()
