"""WebDAV gateway over the filer.

Behavioral port of `weed/server/webdav_server.go:144-641` (which adapts
golang.org/x/net/webdav's FileSystem onto the filer): here the WebDAV
protocol layer itself is implemented directly — OPTIONS, PROPFIND (Depth
0/1), GET/HEAD/PUT/DELETE, MKCOL, MOVE, COPY, and class-2 LOCK/UNLOCK
(in-memory lock table, enough for macOS/Windows clients that refuse to
write without locks).

All storage operations go through the filer's HTTP API via FilerClient, so
the gateway is stateless like the reference's.
"""

from __future__ import annotations

import time
import urllib.parse
import uuid
from xml.etree import ElementTree as ET
from xml.sax.saxutils import escape

from seaweedfs_tpu.filer.filer_client import FilerClient

from .httpd import HTTPService, Request, Response

DAV_NS = "DAV:"


def _rfc1123(ts: float) -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime(ts))


def _iso8601(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class WebDavServer:
    def __init__(self, filer_url: str, host: str = "127.0.0.1",
                 port: int = 7333, read_only: bool = False,
                 slow_ms: float | None = None,
                 master_url: str | None = None) -> None:
        self.fc = FilerClient(filer_url)
        self.read_only = read_only
        # like the S3 gateway: no master link of its own, so an optional
        # master_url ships telemetry frames (stats/aggregate.py)
        self.master_url = master_url
        self._telemetry_pusher = None
        self.service = HTTPService(host, port)
        # request metrics + tracing + the /debug and /debug/pprof surface,
        # like every other role. The WebDAV namespace is a catch-all (any
        # path may be a file), so /metrics stays off the main port
        # (serve_route=False) and the /debug routes, registered first,
        # shadow same-named file paths — the filer's convention.
        self.service.enable_metrics("webdav", serve_route=False)
        if slow_ms is not None:  # -slowMs: per-role slow-span threshold
            from seaweedfs_tpu.stats import trace as trace_mod

            trace_mod.set_slow_threshold_ms(slow_ms, role="webdav")
        # path -> (token, expiry). Locks are actually enforced: mutations on
        # a locked path demand the token via the If header, LOCK on a live
        # lock is refused (423), and entries expire at the advertised
        # timeout (advisor r1 finding #3).
        self._locks: dict[str, tuple[str, float]] = {}
        self.lock_timeout = 3600.0
        self._routes()

    def start(self) -> None:
        self.service.start()
        if self.master_url:
            from seaweedfs_tpu.stats import aggregate as agg_mod

            self._telemetry_pusher = agg_mod.TelemetryPusher(
                "webdav", lambda: self.url, self.master_url)
            self._telemetry_pusher.start()

    def stop(self) -> None:
        if self._telemetry_pusher is not None:
            self._telemetry_pusher.stop()
            self._telemetry_pusher = None
        self.service.stop()

    @property
    def url(self) -> str:
        return self.service.url

    # --- helpers -------------------------------------------------------------
    @staticmethod
    def _norm(path: str) -> str:
        path = urllib.parse.unquote(path)
        if path != "/" and path.endswith("/"):
            path = path.rstrip("/")
        return path or "/"

    def _entry(self, path: str) -> dict | None:
        if path == "/":
            return {"full_path": "/", "is_directory": True,
                    "attributes": {"mtime": 0, "mime": ""}}
        return self.fc.get_entry(path)

    def _prop_xml(self, href_path: str, entry: dict) -> str:
        attrs = entry.get("attributes") or {}
        is_dir = bool(entry.get("is_directory"))
        mtime = attrs.get("mtime", 0)
        size = attrs.get("file_size", 0)
        mime = attrs.get("mime", "") or "application/octet-stream"
        href = urllib.parse.quote(href_path + ("/" if is_dir and href_path != "/" else ""))
        restype = "<D:resourcetype><D:collection/></D:resourcetype>" if is_dir \
            else "<D:resourcetype/>"
        length = "" if is_dir else f"<D:getcontentlength>{size}</D:getcontentlength>"
        ctype = "" if is_dir else f"<D:getcontenttype>{escape(mime)}</D:getcontenttype>"
        etag = attrs.get("md5", "") or str(mtime)
        return (
            f"<D:response><D:href>{href}</D:href>"
            f"<D:propstat><D:prop>"
            f"{restype}{length}{ctype}"
            f"<D:getlastmodified>{_rfc1123(mtime)}</D:getlastmodified>"
            f"<D:creationdate>{_iso8601(attrs.get('crtime', mtime))}</D:creationdate>"
            f'<D:getetag>"{escape(etag)}"</D:getetag>'
            f"<D:displayname>{escape(entry['full_path'].rsplit('/', 1)[-1] or '/')}"
            f"</D:displayname>"
            f"</D:prop><D:status>HTTP/1.1 200 OK</D:status></D:propstat>"
            f"</D:response>"
        )

    def _multistatus(self, parts: list[str]) -> Response:
        body = (
            '<?xml version="1.0" encoding="utf-8"?>'
            '<D:multistatus xmlns:D="DAV:">' + "".join(parts) + "</D:multistatus>"
        ).encode()
        return Response(body, 207,
                        {"Content-Type": 'application/xml; charset="utf-8"'})

    # --- locking -------------------------------------------------------------
    def _live_lock(self, path: str) -> str | None:
        """Current unexpired token for path, dropping expired entries."""
        held = self._locks.get(path)
        if held is None:
            return None
        token, expiry = held
        if time.time() >= expiry:
            self._locks.pop(path, None)
            return None
        return token

    def _lock_conflict(
        self, req: Request, path: str, check_descendants: bool = False
    ) -> Response | None:
        """423 unless the request carries the live lock token in its If
        header (RFC 4918 §6; clients send `If: (<token>)`). Locks are
        depth-infinity (RFC 4918 §7): a lock on a collection covers every
        member, so ancestors of the target are checked too; recursive
        DELETE/MOVE also checks locks held below the target."""
        if_header = req.headers.get("If", "")
        probe = path
        while True:
            token = self._live_lock(probe)
            if token is not None and token not in if_header:
                return Response({"error": "locked"}, 423)
            if probe == "/":
                break
            probe = probe.rsplit("/", 1)[0] or "/"
        if check_descendants:
            prefix = path.rstrip("/") + "/"
            for locked in list(self._locks):
                if locked.startswith(prefix):
                    token = self._live_lock(locked)
                    if token is not None and token not in if_header:
                        return Response({"error": "locked"}, 423)
        return None

    def _drop_locks_under(self, path: str) -> None:
        """Forget the lock on `path` and on everything below it (after a
        successful DELETE or MOVE — the resources the locks named are gone)."""
        self._locks.pop(path, None)
        prefix = path.rstrip("/") + "/"
        for locked in list(self._locks):
            if locked.startswith(prefix):
                self._locks.pop(locked, None)

    # --- routes --------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service
        any_path = r"(/.*)"

        @svc.route("OPTIONS", any_path)
        def options(req: Request) -> Response:
            return Response(b"", 200, {
                "DAV": "1, 2",
                "MS-Author-Via": "DAV",
                "Allow": "OPTIONS, GET, HEAD, PUT, DELETE, PROPFIND, "
                         "PROPPATCH, MKCOL, MOVE, COPY, LOCK, UNLOCK",
            })

        @svc.route("PROPFIND", any_path)
        def propfind(req: Request) -> Response:
            path = self._norm(req.path)
            depth = req.headers.get("Depth", "1")
            entry = self._entry(path)
            if entry is None:
                return Response({"error": "not found"}, 404)
            parts = [self._prop_xml(path, entry)]
            if entry.get("is_directory") and depth != "0":
                listing = self.fc.list(path if path != "/" else "/")
                for e in listing.get("Entries") or []:
                    child = {
                        "full_path": e["FullPath"],
                        "is_directory": e["IsDirectory"],
                        "attributes": {
                            "mtime": e.get("Mtime", 0),
                            "file_size": e.get("FileSize", 0),
                            "mime": e.get("Mime", ""),
                            "md5": e.get("Md5", ""),
                        },
                    }
                    parts.append(self._prop_xml(e["FullPath"], child))
            return self._multistatus(parts)

        @svc.route("PROPPATCH", any_path)
        def proppatch(req: Request) -> Response:
            path = self._norm(req.path)
            if self._entry(path) is None:
                return Response({"error": "not found"}, 404)
            # accept-and-ignore property writes like the reference's
            # (go webdav has no proppatch persistence hooks either)
            return self._multistatus([
                f"<D:response><D:href>{urllib.parse.quote(path)}</D:href>"
                f"<D:propstat><D:prop/>"
                f"<D:status>HTTP/1.1 200 OK</D:status></D:propstat></D:response>"
            ])

        @svc.route("GET", any_path)
        def get(req: Request) -> Response:
            return self._get(req, head=False)

        @svc.route("HEAD", any_path)
        def head(req: Request) -> Response:
            return self._get(req, head=True)

        @svc.route("PUT", any_path)
        def put(req: Request) -> Response:
            if self.read_only:
                return Response({"error": "read-only"}, 403)
            path = self._norm(req.path)
            conflict = self._lock_conflict(req, path)
            if conflict is not None:
                return conflict
            mime = req.headers.get("Content-Type", "")
            try:
                self.fc.put(path, req.body, content_type=mime)
            except OSError as e:
                return Response({"error": str(e)}, 409)
            return Response(b"", 201)

        @svc.route("DELETE", any_path)
        def delete(req: Request) -> Response:
            if self.read_only:
                return Response({"error": "read-only"}, 403)
            path = self._norm(req.path)
            conflict = self._lock_conflict(req, path, check_descendants=True)
            if conflict is not None:
                return conflict
            if self._entry(path) is None:
                return Response({"error": "not found"}, 404)
            self.fc.delete(path, recursive=True)
            self._drop_locks_under(path)  # RFC 4918 §9.6: DELETE removes
            return Response(b"", 204)     # locks on the deleted resources

        @svc.route("MKCOL", any_path)
        def mkcol(req: Request) -> Response:
            if self.read_only:
                return Response({"error": "read-only"}, 403)
            path = self._norm(req.path)
            conflict = self._lock_conflict(req, path)
            if conflict is not None:
                return conflict
            if self._entry(path) is not None:
                return Response({"error": "exists"}, 405)
            self.fc.mkdir(path)
            return Response(b"", 201)

        @svc.route("MOVE", any_path)
        def move(req: Request) -> Response:
            return self._move_or_copy(req, is_move=True)

        @svc.route("COPY", any_path)
        def copy(req: Request) -> Response:
            return self._move_or_copy(req, is_move=False)

        @svc.route("LOCK", any_path)
        def lock(req: Request) -> Response:
            path = self._norm(req.path)
            held = self._live_lock(path)
            if held is not None:
                if held in req.headers.get("If", ""):  # refresh own lock
                    self._locks[path] = (held, time.time() + self.lock_timeout)
                    token = held
                else:
                    return Response({"error": "locked"}, 423)
            else:
                # an exclusive depth-infinity lock anywhere above or below
                # forbids creating this one (RFC 4918 §7: a collection lock
                # covers members; a new lock would cover locked descendants)
                conflict = self._lock_conflict(req, path, check_descendants=True)
                if conflict is not None:
                    return conflict
                token = f"opaquelocktoken:{uuid.uuid4()}"
                self._locks[path] = (token, time.time() + self.lock_timeout)
            owner = ""
            if req.body:
                try:
                    root = ET.fromstring(req.body)
                    o = root.find(f"{{{DAV_NS}}}owner")
                    if o is not None and o.text:
                        owner = o.text
                except ET.ParseError:
                    pass
            body = (
                '<?xml version="1.0" encoding="utf-8"?>'
                '<D:prop xmlns:D="DAV:"><D:lockdiscovery><D:activelock>'
                "<D:locktype><D:write/></D:locktype>"
                "<D:lockscope><D:exclusive/></D:lockscope>"
                "<D:depth>infinity</D:depth>"
                f"<D:owner>{escape(owner)}</D:owner>"
                "<D:timeout>Second-3600</D:timeout>"
                f"<D:locktoken><D:href>{token}</D:href></D:locktoken>"
                "</D:activelock></D:lockdiscovery></D:prop>"
            ).encode()
            return Response(body, 200, {
                "Content-Type": 'application/xml; charset="utf-8"',
                "Lock-Token": f"<{token}>",
            })

        @svc.route("UNLOCK", any_path)
        def unlock(req: Request) -> Response:
            path = self._norm(req.path)
            token = self._live_lock(path)
            if token is not None and \
                    token not in req.headers.get("Lock-Token", ""):
                return Response({"error": "wrong lock token"}, 409)
            self._locks.pop(path, None)
            return Response(b"", 204)

    def _get(self, req: Request, head: bool) -> Response:
        path = self._norm(req.path)
        entry = self._entry(path)
        if entry is None:
            return Response({"error": "not found"}, 404)
        if entry.get("is_directory"):
            return Response({"error": "is a collection"}, 405)
        headers = {}
        rng = req.headers.get("Range")
        status, resp_headers, body = self.fc.get(
            path, range_header=rng
        )
        if status >= 300:
            return Response(body or b"", status)
        for h in ("Content-Type", "ETag", "Last-Modified", "Content-Range",
                  "Accept-Ranges"):
            if resp_headers.get(h):
                headers[h] = resp_headers[h]
        if head:
            headers["Content-Length"] = str(
                (entry.get("attributes") or {}).get("file_size", len(body))
            )
            return Response(b"", status, headers)
        return Response(body, status, headers)

    def _move_or_copy(self, req: Request, is_move: bool) -> Response:
        if self.read_only:
            return Response({"error": "read-only"}, 403)
        src = self._norm(req.path)
        dest_header = req.headers.get("Destination", "")
        if not dest_header:
            return Response({"error": "missing Destination"}, 400)
        dst = self._norm(urllib.parse.urlparse(dest_header).path)
        if is_move:  # COPY does not mutate the source
            conflict = self._lock_conflict(req, src, check_descendants=True)
            if conflict is not None:
                return conflict
        conflict = self._lock_conflict(req, dst, check_descendants=True)
        if conflict is not None:
            return conflict
        entry = self._entry(src)
        if entry is None:
            return Response({"error": "not found"}, 404)
        overwrite = req.headers.get("Overwrite", "T") != "F"
        existed = self._entry(dst) is not None
        if existed and not overwrite:
            return Response({"error": "destination exists"}, 412)
        if is_move:
            try:
                self.fc.rename(src, dst)
            except OSError as e:
                return Response({"error": str(e)}, 409)
            self._drop_locks_under(src)  # locks name paths, not resources
        else:
            if entry.get("is_directory"):
                self._copy_tree(src, dst)
            else:
                data = self.fc.read(src)
                mime = (entry.get("attributes") or {}).get("mime", "")
                self.fc.put(dst, data, content_type=mime)
        return Response(b"", 204 if existed else 201)

    def _copy_tree(self, src: str, dst: str) -> None:
        self.fc.mkdir(dst)
        for e in self.fc.list(src).get("Entries") or []:
            child_src = e["FullPath"]
            child_dst = dst + "/" + child_src.rsplit("/", 1)[-1]
            if e["IsDirectory"]:
                self._copy_tree(child_src, child_dst)
            else:
                self.fc.put(child_dst, self.fc.read(child_src),
                            content_type=e.get("Mime", ""))
