"""Filer HTTP server: upload pipeline with auto-chunking + MD5 tee, streamed
ranged reads via visible intervals, directory listings, recursive delete.

Reference: `weed/server/filer_server_handlers_write_autochunk.go:26-155`,
`_write_upload.go:30-141` (chunk fan-out + whole-stream MD5),
`_read.go:91` (ranged streaming), `filer/stream.go:153`.

One-shot blob hashing (per-chunk ETag MD5, inline small-content MD5) goes
through ops.hash_service: a micro-batching queue that coalesces the chunks
of one upload AND concurrent requests into single batch-kernel calls —
ops.md5_kernel/crc32c_kernel on an attached chip, one GIL-released C++
batch call otherwise (SURVEY.md §2.2). The whole-stream MD5 tee
(`_write_upload.go:48`) stays a sequential CPU hash: MD5 cannot
parallelize within one stream, only across blobs.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import time
import urllib.parse

from seaweedfs_tpu.util import cipher as cipher_util
from seaweedfs_tpu.util import glog
from seaweedfs_tpu.util.compression import decompress_data, maybe_compress_data

from seaweedfs_tpu.filer import Attributes, Entry, FileChunk, Filer
from seaweedfs_tpu.ops.hash_service import get_hash_service
from seaweedfs_tpu.filer.filechunks import (
    maybe_manifestize,
    resolve_chunk_manifest,
    total_size,
    view_from_chunks,
)
from seaweedfs_tpu.filer.filer import FilerError, normalize
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.filer.wdclient import WeedClient

from .httpd import HTTPService, Request, Response

SMALL_CONTENT_LIMIT = 2 * 1024  # inline small files in the entry


class FilerServer:
    def __init__(
        self,
        master_url: str,
        host: str = "127.0.0.1",
        port: int = 8888,
        store_kind: str = "memory",
        store_path: str | None = None,
        chunk_size_mb: int = 4,
        default_replication: str = "",
        collection: str = "",
        security=None,
        metrics_port: int = -1,
        cipher: bool = False,
        compress: bool = True,
        chunk_cache_dir: str | None = None,
        notification_queue=None,
        peers: list[str] | None = None,
        dedup: bool = False,
        dedup_avg_bits: int = 16,
        dedup_min: int = 16 * 1024,
        dedup_max: int = 512 * 1024,
        local_socket: str | None = None,
        slow_ms: float | None = None,
        telemetry_dir: str | None = None,
        telemetry_retention_mb: float | None = None,
        qos_limits: str | None = None,
    ) -> None:
        from seaweedfs_tpu.security import Guard, SecurityConfig

        from .httpd import MetricsService

        self.security = security or SecurityConfig()
        self.filer = Filer(make_store(store_kind, store_path))
        self.filer.notification_queue = notification_queue
        self.client = WeedClient(master_url, jwt_key=self.security.write_key,
                                 read_jwt_key=self.security.read_key)
        self.chunk_size = chunk_size_mb * 1024 * 1024
        self.default_replication = default_replication
        self.collection = collection
        self.service = HTTPService(host, port)
        if self.security.white_list:
            self.service.guard = Guard(self.security.white_list)
        # the filer's namespace is a catch-all (any path may be a file, incl.
        # /metrics), so metrics get their own listener (`-metricsPort`;
        # -1 = ephemeral port, 0 = disabled, >0 = fixed)
        self.service.enable_metrics("filer", serve_route=False)
        # -telemetry.dir: durable history/event spool (stats/store.py)
        if telemetry_dir:
            from seaweedfs_tpu.stats import store as store_mod

            store_mod.enable(telemetry_dir, telemetry_retention_mb)
        if slow_ms is not None:  # -slowMs: per-role slow-span threshold
            from seaweedfs_tpu.stats import trace as trace_mod

            trace_mod.set_slow_threshold_ms(slow_ms, role="filer")
        # -qos.limits: arm admission control (qos/) + the burn actuator;
        # without the flag the per-request check is one attribute read
        if qos_limits is not None:
            from seaweedfs_tpu.qos import actuator as qos_act
            from seaweedfs_tpu.qos import admission as qos_mod

            limits, default = qos_mod.parse_limits_spec(qos_limits)
            qos_mod.controller().set_limits(limits=limits, default=default)
            qos_mod.enable()
            qos_act.start(master_url=master_url)
        self.metrics_service = (
            MetricsService(host, max(metrics_port, 0)) if metrics_port != 0 else None
        )
        # -encryptVolumeData / compression defaults (`weed/command/filer.go`)
        if cipher and not cipher_util.available():
            # fail at boot, not with a 500 on the first write
            raise RuntimeError(
                "-encryptVolumeData needs the 'cryptography' package,"
                " which is not installed"
            )
        self.cipher = cipher
        self.compress = compress
        # CDC dedup (filer/dedup.py): content-defined chunking + hash index.
        # Mutually exclusive with cipher — random per-chunk AES keys make
        # equal plaintexts distinct, and convergent encryption leaks equality.
        self.dedup = dedup and not cipher
        if self.dedup:
            import threading as _threading

            from seaweedfs_tpu.filer.dedup import DedupIndex

            self.dedup_index = DedupIndex(self.filer)
            self.dedup_avg_bits = dedup_avg_bits
            self.dedup_min = dedup_min
            self.dedup_max = dedup_max
            # gc-vs-upload coordination (see dedup_gc): hits record the fid
            # under this lock; gc condemns keys under the same lock, so every
            # hit either lands before the gc decision (gc skips the fid) or
            # sees the key condemned (upload treats it as a miss).
            self._dedup_mu = _threading.Lock()
            self._dedup_recent: dict[str, float] = {}
            self._dedup_condemned: set[str] = set()
        from seaweedfs_tpu.util.chunk_cache import TieredChunkCache

        self.chunk_cache = TieredChunkCache(disk_dir=chunk_cache_dir)
        # distributed lock manager hosted on the filer group (weed/cluster)
        from seaweedfs_tpu.cluster import DistributedLockManager, LockRing

        self.lock_ring = LockRing()
        self.dlm = DistributedLockManager()
        self._static_peers = list(peers or [])
        # remote-storage mounts (weed/remote_storage): configs + dir mounts
        self._remote_confs: dict = {}
        self._remote_mounts: dict = {}
        self._load_remote_state()
        # `-filer.localSocket` (weed/command/filer.go): same-host clients
        # (mounts) reach the filer over a unix domain socket
        self.local_socket = local_socket
        # per-path storage rules (`weed/filer/filer_conf.go`): loaded from
        # /etc/seaweedfs/filer.conf, hot-reloaded via the meta-log
        from seaweedfs_tpu.filer.filer_conf import FILER_CONF_PATH, FilerConf

        conf_entry = self.filer.find_entry(FILER_CONF_PATH)
        self.filer_conf = FilerConf.from_bytes(
            bytes(conf_entry.content) if conf_entry else b"")
        self.filer.subscribe(self._conf_on_meta)
        self._register_stop = __import__("threading").Event()
        self._fl_collector = None
        # gateway ordinal/count from the master's cluster registry
        # (/cluster/register response): shards the fid lease vid-space
        # so N filer front doors never contend on the same volume
        self._gateway_ordinal = 0
        self._gateway_count = 1
        self._routes()

    def _conf_on_meta(self, ev) -> None:
        """Hot-reload /etc/seaweedfs/filer.conf on any mutation of it."""
        from seaweedfs_tpu.filer.filer_conf import FILER_CONF_PATH, FilerConf

        target = ev.new_entry or ev.old_entry
        if target is None or target.full_path != FILER_CONF_PATH:
            return
        if ev.new_entry is not None and not ev.new_entry.content and \
                ev.new_entry.chunks:
            # chunk-backed conf (written by an old build): refusing to
            # parse b"" keeps the PREVIOUS rules instead of silently
            # dropping enforcement
            glog.warning("filer.conf is chunk-backed; keeping previous"
                         " rules (rewrite it to inline)")
            return
        content = ev.new_entry.content if ev.new_entry else b""
        self.filer_conf = FilerConf.from_bytes(bytes(content))
        self._fl_push_rules()

    # control-plane namespaces the native front door must always defer
    # to Python — a query-less POST /qos/limits is a config update for
    # the route table, not an inline file write
    FL_RESERVED_PREFIXES = ("/qos/",)

    def _fl_push_rules(self) -> None:
        """Tell the engine which prefixes carry storage rules (their
        writes must resolve collection/replication/ttl in Python)."""
        if not getattr(self, "_fl_filer_on", False) or self.fastlane is None:
            return
        prefixes = list(self.FL_RESERVED_PREFIXES) \
            + list(self.filer_conf.prefixes())
        blob = b"".join(p.encode() + b"\0" for p in prefixes)
        self.fastlane._lib.sw_fl_filer_rules_set(
            self.fastlane.handle, blob, len(prefixes))

    def _start_fastlane(self) -> None:
        """Front the filer with the engine. Proxied (Python) requests ride a
        max_backend=2 concurrency governor (measured 4-5x over uncapped at
        16 connections on the GIL); long-poll meta subscriptions bypass the
        cap. On top of that, FILER MODE serves the hot path natively
        (VERDICT r4 next #3; reference hot path
        `filer_server_handlers_write_autochunk.go:26-155`):
          * writes <= SMALL_CONTENT_LIMIT: inline entry — md5 + journal
            append + ack in C++, zero volume hops
          * larger single-chunk writes: fid minted from a master lease the
            Python side refreshes, chunk POSTed to the volume engine, entry
            journaled before the ack
          * reads: path -> location cache (inline bytes served from memory;
            chunk-backed relayed to the volume engine with the entry's
            ETag), invalidated/refreshed by the meta-log subscriber
        The journal is replayed into the store on startup (crash safety),
        and drained frames become real entries via Filer.create_entry."""
        from seaweedfs_tpu.storage import fastlane as fl_mod

        self.fastlane = fl_mod.front_service(
            self.service,
            guard_active=getattr(self.service, "guard", None) is not None,
            max_backend=2,
        )
        self._fl_filer_on = False
        if self.fastlane is None or self.cipher or self.dedup:
            # cipher/dedup transform chunks in ways only Python implements
            return
        import tempfile

        if self.filer.store.__class__.__name__ == "MemoryStore":
            journal = ""  # store dies with the process; a WAL buys nothing
        else:
            base = getattr(self.filer.store, "path", None)
            d = os.path.dirname(base) if base else tempfile.gettempdir()
            journal = os.path.join(d, "filer_native.journal")
            self._fl_replay_journal(journal)
        rc = self.fastlane._lib.sw_fl_filer_enable(
            self.fastlane.handle, journal.encode(), self.chunk_size,
            1 if self.compress else 0,
        )
        if rc != 0:
            return
        self._fl_journal_path = journal
        if journal:
            self.fastlane._lib.sw_fl_filer_journal_reset(self.fastlane.handle)
        self._fl_filer_on = True
        self._fl_drain_mu = __import__("threading").Lock()
        self._fl_buf = __import__("ctypes").create_string_buffer(1 << 20)
        self.filer.subscribe(self._fl_on_meta)
        self._fl_push_rules()  # fs.configure prefixes defer to Python
        self._register_front_collector()

    FL_FRONT_FAMILIES = (
        "SeaweedFS_filer_fastlane_native_total",
        "SeaweedFS_filer_fastlane_fallback_total",
    )

    def _register_front_collector(self) -> None:
        """Export the engine's front-door accounting so a silent fall-back
        regime (like r05's rejected lease) is a rate on /metrics — and the
        `fastlane_fallback` alert — instead of a log line."""
        from seaweedfs_tpu.stats import default_registry
        from seaweedfs_tpu.storage import fastlane as fl_mod

        def lines() -> list[str]:
            fl = self.fastlane
            if fl is None or fl.stopped:
                return []
            server = f"{self.service.host}:{fl.port}"
            return fl_mod.front_metric_lines(
                fl, "SeaweedFS_filer_fastlane", server)

        self._fl_collector = default_registry().register_collector(
            lines, names=self.FL_FRONT_FAMILIES)

    def start(self) -> None:
        import threading

        self._start_fastlane()
        if self.local_socket:
            self.service.enable_unix_socket(self.local_socket)
        if self.metrics_service is not None:
            self.metrics_service.start()
        self.dlm.host = self.url
        self.lock_ring.set_servers(self._static_peers + [self.url])
        self._register_once()
        t = threading.Thread(target=self._register_loop, daemon=True)
        t.start()
        if self._fl_filer_on:
            try:
                self._fl_lease_refresh()
            except Exception:
                pass  # master not ready: the loop retries
            threading.Thread(target=self._fl_filer_loop, daemon=True).start()

    # --- native filer mode (engine-side writes/reads) -------------------------
    _FL_FRAME_HDR = __import__("struct").Struct("<IB3xQQ32sHHHH")

    def _fl_parse_frames(self, buf: bytes):
        """Entry frames as written by fastlane.cpp filer_frame()."""
        hdr = self._FL_FRAME_HDR
        off = 0
        while off + hdr.size <= len(buf):
            (total, kind, size, mtime, md5, plen, flen, mlen,
             clen) = hdr.unpack_from(buf, off)
            if total < hdr.size or off + total > len(buf):
                break  # torn tail (crash mid-append): stop cleanly
            p = off + hdr.size
            path = buf[p:p + plen].decode("utf-8", "replace"); p += plen
            fid = buf[p:p + flen].decode(); p += flen
            mime = buf[p:p + mlen].decode("utf-8", "replace"); p += mlen
            content = bytes(buf[p:p + clen])
            yield kind, size, mtime, md5.decode(), path, fid, mime, content
            off += total

    def _fl_replay_journal(self, path: str) -> None:
        """Crash recovery: acked native writes whose entries never reached
        the store (process died before the drain) are re-applied from the
        journal — the filer analog of .idx replay on volume load."""
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return
        for frame in self._fl_parse_frames(buf):
            self._fl_apply(*frame)

    def _fl_apply(self, kind: int, size: int, mtime: int, md5: str,
                  path: str, fid: str, mime: str, content: bytes) -> None:
        if kind == 2:
            # natively-acked DELETE (the engine tombstoned its cache and
            # journaled this frame): apply to the store + reclaim chunks.
            # Idempotent for journal replay — an already-gone path is fine.
            try:
                chunks = self.filer.delete_entry(path)
            except FilerError:
                return
            self._reclaim_chunks(chunks)
            return
        entry = Entry(full_path=path)
        entry.attributes.mime = mime
        entry.attributes.file_size = size
        entry.attributes.mtime = float(mtime)
        entry.attributes.md5 = md5
        if kind == 1:
            entry.content = content
        else:
            entry.chunks = [FileChunk(
                file_id=fid, offset=0, size=size, etag=md5,
                modified_ts_ns=int(mtime * 1_000_000_000),
            )]
        # parents carry the WRITE's timestamp, not the drain's — a lazily
        # applied entry must not make its directory look newer than its
        # contents (age-based sweeps like s3.clean.uploads compare mtimes)
        missing = []
        p = path.rsplit("/", 1)[0] or "/"
        while p != "/" and self.filer.find_entry(p) is None:
            missing.append(p)
            p = p.rsplit("/", 1)[0] or "/"
        for d in reversed(missing):
            de = Entry(full_path=d, is_directory=True,
                       attributes=Attributes(mode=0o755))
            de.attributes.mtime = de.attributes.crtime = float(mtime)
            try:
                self.filer.create_entry(de)
            except FilerError:
                break
        old = self.filer.find_entry(path)
        try:
            freed = self.filer.create_entry(entry)
        except FilerError:
            # the store rejected an acked native write (e.g. the path is a
            # directory): the engine cache must not keep serving a phantom
            # — no meta event fires on a failed create, so purge directly
            self.fastlane._lib.sw_fl_filer_cache_del(
                self.fastlane.handle, path.encode())
            glog.warning("native write to %s rejected by store; dropped",
                         path)
            return
        # journal replay is idempotent: never reclaim the very chunk this
        # frame records (a replayed frame sees itself as the old entry)
        new_fids = {c.file_id for c in entry.chunks}
        if old is not None and old.hard_link_id:
            self._reclaim_chunks(
                [c for c in freed if c.file_id not in new_fids])
        elif old is not None and old.chunks:
            self._reclaim_chunks(
                [c for c in old.chunks if c.file_id not in new_fids])

    def _fl_filer_drain(self, once: bool = False) -> int:
        """Apply engine-journaled entries to the store (read-your-writes:
        the Python read/write/delete handlers call this first). once=True
        processes a single buffer so the caller can interleave other
        housekeeping (lease refresh) during a heavy backlog."""
        if not getattr(self, "_fl_filer_on", False):
            return 0
        import ctypes

        total = 0
        with self._fl_drain_mu:
            while True:
                n = int(self.fastlane._lib.sw_fl_filer_drain(
                    self.fastlane.handle, ctypes.addressof(self._fl_buf),
                    len(self._fl_buf)))
                if n <= 0:
                    break
                for fr in self._fl_parse_frames(self._fl_buf.raw[:n]):
                    self._fl_apply(*fr)
                    total += 1
                if once:
                    break
        return total

    # how many volumes' leases the engine should hold at once: chunk
    # writes round-robin across the pool, a spent/failed volume degrades
    # throughput instead of zeroing it, and refreshes amortize N volumes
    # per low-watermark instead of churning one
    _FL_LEASE_POOL = 3

    def _fl_lease_refresh(self, count: int = 20000) -> None:
        """Top up the engine's lease POOL from the master: each assign
        (count=N) leases one volume's fid range, and the engine round-robins
        chunk writes across unspent ranges so a native write costs zero
        master round-trips. Wildcard upload/read JWTs are minted from the
        filer's key copies, as the reference filer signs its own volume
        tokens. Never touches a stopped engine (the r05 bench logged its
        rc=-1 'lease rejected' from exactly that shutdown race)."""
        from seaweedfs_tpu.storage import fastlane as fl_mod
        from seaweedfs_tpu.storage.file_id import parse_needle_id_cookie

        fl = self.fastlane
        if fl is None or fl.stopped or self._register_stop.is_set():
            return
        if not fl.tls_client_ok:
            # mTLS without the engine's TLS client context (OpenSSL
            # resolution failed): chunk uploads go through Python (inline
            # writes stay native — no volume hop)
            return
        upload_auth = read_auth = ""
        from seaweedfs_tpu.security.jwt import encode_jwt

        if self.security.write_key:
            tok = encode_jwt(self.security.write_key,
                             {"fid": "", "exp": int(time.time()) + 3600})
            upload_auth = f"BEARER {tok}"
        if self.security.read_key:
            tok = encode_jwt(self.security.read_key,
                             {"fid": "", "exp": int(time.time()) + 3600})
            read_auth = f"BEARER {tok}"
        live = fl.lease_count()
        if live < 0:
            return  # engine stopped between checks
        self._fl_lease_top_at = time.monotonic()
        for _ in range(max(1, self._FL_LEASE_POOL - live)):
            if fl.stopped or self._register_stop.is_set():
                return
            a = self.client.assign(
                count=count, replication=self.default_replication,
                collection=self.collection,
                # lease-pool vid-space sharding: with N registered filer
                # gateways, this one only leases volumes in its slice
                # (the master falls back to the whole space when the
                # slice has no writables — correctness over partition)
                shard=(f"{self._gateway_ordinal}:{self._gateway_count}"
                       if getattr(self, "_gateway_count", 1) > 1 else ""),
            )
            if a.get("error"):
                return
            vid_s, _, key_hash = a["fid"].partition(",")
            key, cookie = parse_needle_id_cookie(key_hash)
            loc = a.get("publicUrl") or a.get("url")
            host, _, port = loc.rpartition(":")
            rc = int(fl._lib.sw_fl_filer_lease_set(
                fl.handle, host.encode(), int(port), int(vid_s),
                cookie, key, key + count, upload_auth.encode(),
                read_auth.encode(),
            ))
            from seaweedfs_tpu.stats import events as events_mod

            events_mod.emit(
                "lease_churn", volume=int(vid_s), node=loc,
                action=("leased" if rc == 0
                        else "kept" if rc == 1 else "rejected"),
                rc=rc, count=count,
            )
            if rc == 1:
                # the master granted a vid the engine already holds with a
                # healthy unspent range (the engine kept the range,
                # refreshing endpoint + auth): the cluster has fewer
                # writable volumes than the pool target, so further
                # top-up probes this round would only repeat the answer.
                # Probe again in ~60s instead of burning a count=20000
                # master assign every 5s forever.
                self._fl_lease_small_until = time.monotonic() + 55.0
                return
            if rc != 0:
                # e.g. the volume registered by hostname (the engine needs
                # an IP): chunk writes stay on the Python path. Without a
                # backoff the 20ms loop would burn a count=20000 master
                # assignment per tick forever.
                self._fl_lease_backoff_until = time.monotonic() + 30.0
                # this rejection IS the cause of pathological no_lease /
                # lease_spent front-door fallbacks — journal it so
                # cluster.why can name the root of a fallback regime
                events_mod.emit(
                    "fallback_fastlane", volume=int(vid_s), node=loc,
                    reason="lease_rejected",
                    detail=fl_mod.error_str(fl._lib, rc),
                )
                glog.warning(
                    "filer native lease rejected by engine (volume %s): %s;"
                    " chunk writes stay on the Python path", loc,
                    fl_mod.error_str(fl._lib, rc))
                return

    def _fl_filer_loop(self) -> None:  # pragma: no cover - timing loop
        while not self._register_stop.is_set():
            try:
                fl = self.fastlane
                if fl is None or fl.stopped:
                    return
                applied = 0
                while True:
                    # lease first, one drain buffer at a time: a heavy
                    # write backlog must not starve the fid lease (native
                    # writes fall back to the slow proxy when it runs dry)
                    live = fl.lease_count()
                    if live < 0:
                        return  # engine stopped: never re-lease against it

                    rem = int(fl._lib.sw_fl_filer_lease_remaining(fl.handle))
                    # top up when keys run low or the pool emptied; an
                    # UNDER-TARGET pool (small cluster: fewer writable
                    # volumes than the target — assigns keep landing on
                    # the same vid) re-tops only every 5s, not per tick
                    want = (rem < 5000 or live == 0
                            or (live < self._FL_LEASE_POOL
                                and time.monotonic() >= getattr(
                                    self, "_fl_lease_top_at", 0.0) + 5.0
                                and time.monotonic() >= getattr(
                                    self, "_fl_lease_small_until", 0.0)))
                    if want and time.monotonic() >= getattr(
                            self, "_fl_lease_backoff_until", 0.0):
                        try:
                            self._fl_lease_refresh()
                        except Exception:
                            # master down/unreachable: same 30s backoff so
                            # the 20ms loop doesn't hammer it
                            self._fl_lease_backoff_until = (
                                time.monotonic() + 30.0)
                    got = self._fl_filer_drain(once=True)
                    applied += got
                    if got == 0:
                        break
                if applied and getattr(self, "_fl_journal_path", ""):
                    # refuses (harmlessly) if new frames queued meanwhile
                    self.fastlane._lib.sw_fl_filer_journal_reset(
                        self.fastlane.handle)
            except Exception:
                pass
            self._register_stop.wait(0.02)

    def _fl_on_meta(self, ev) -> None:
        """Meta-log subscriber keeping the engine's path cache coherent:
        every local mutation re-puts (still natively servable) or deletes
        (anything the native path cannot serve) the affected paths.

        Runs SYNCHRONOUSLY under the Filer lock (_notify), so it must
        never block on the network — volume locations come from the vid
        cache only (peek). A peek miss just deletes the cache entry; the
        first Python-served read re-populates it from outside the lock
        (_fl_cache_push in _do_read)."""
        if not getattr(self, "_fl_filer_on", False) or self.fastlane is None:
            return
        old, new = ev.old_entry, ev.new_entry
        if old is not None and (new is None
                                or old.full_path != new.full_path):
            self.fastlane._lib.sw_fl_filer_cache_del(
                self.fastlane.handle, old.full_path.encode())
        if new is not None:
            self._fl_cache_push(new, blocking_lookup=False)

    def _fl_cache_push(self, entry, blocking_lookup: bool) -> None:
        """Install (or purge) one entry in the engine's path cache.
        blocking_lookup=True may resolve the chunk's volume over HTTP and
        must only be used outside the Filer lock (the read path)."""
        lib, h = self.fastlane._lib, self.fastlane.handle
        path = entry.full_path
        a = entry.attributes
        from seaweedfs_tpu.filer.filer_notify import SYSTEM_TREE_PREFIX

        if path.startswith(SYSTEM_TREE_PREFIX):
            # the .system log tree emits no meta events (Filer._notify
            # skips SYSTEM_LOG_DIR): a cached entry under it could never
            # be invalidated, so never cache it — from the read path
            # either (the native write path is gated in fastlane.cpp)
            lib.sw_fl_filer_cache_del(h, path.encode())
            return
        if (entry.is_directory or a.ttl_sec > 0 or entry.hard_link_id
                or not a.md5):
            lib.sw_fl_filer_cache_del(h, path.encode())
            return
        if entry.content:
            lib.sw_fl_filer_cache_put(
                h, path.encode(), b"", 0, b"", (a.mime or "").encode(),
                a.md5.encode(), len(entry.content), int(a.mtime),
                bytes(entry.content), len(entry.content),
            )
            return
        ch = entry.chunks[0] if len(entry.chunks) == 1 else None
        if (ch is not None and not ch.cipher_key and not ch.is_compressed
                and not ch.is_chunk_manifest and ch.offset == 0
                and self.fastlane.tls_client_ok):  # relay speaks mTLS too
            try:
                vid = int(ch.file_id.split(",")[0])
                locs = self.client.lookup_cached(vid)
                if locs is None and blocking_lookup:
                    locs = self.client.lookup(vid)
                if locs:
                    host, _, port = locs[0].rpartition(":")
                    rc = lib.sw_fl_filer_cache_put(
                        h, path.encode(), host.encode(), int(port),
                        ch.file_id.encode(), (a.mime or "").encode(),
                        a.md5.encode(), ch.size, int(a.mtime), None, 0,
                    )
                    if rc == 0:
                        return
            except Exception:
                pass
        lib.sw_fl_filer_cache_del(h, path.encode())

    def _register_once(self) -> None:
        """Announce to the master's cluster membership (`cluster.go` rides
        KeepConnected; here the equivalent periodic POST)."""
        try:
            from seaweedfs_tpu.server.httpd import http_request

            payload = {"type": "filer", "address": self.url}
            try:
                # cluster telemetry frame rides the registration beat
                # (stats/aggregate.py) — same piggyback the volume
                # heartbeat uses, no extra connection
                from seaweedfs_tpu.stats import aggregate as agg_mod

                payload["telemetry"] = agg_mod.build_frame(
                    "filer", self.url, interval=5.0)
            except Exception:
                pass
            _status, _hdrs, body = http_request(
                "POST", self.client.master_url + "/cluster/register",
                body=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"}, timeout=5,
            )
            # the registry answers with this filer's position among the
            # live filer group — the fid-lease shard key (each gateway
            # leases only vids where vid % gateways == ordinal, so front
            # doors scale without lease contention)
            try:
                out = json.loads(body)
                n = int(out.get("gateways", 0))
                i = int(out.get("ordinal", -1))
                if n >= 1 and 0 <= i < n:
                    self._gateway_ordinal, self._gateway_count = i, n
            except Exception:
                pass
        except Exception:
            pass

    def _register_loop(self) -> None:
        while not self._register_stop.wait(5.0):
            self._register_once()
            self.dlm.sweep()
            try:
                # native-path admission check (storage/fastlane.py):
                # requests the engine front door served still debit the
                # tenant's qos bucket via the usage ABI deltas
                from seaweedfs_tpu.storage import fastlane as fl_mod

                self._qos_usage_state = fl_mod.qos_charge_usage(
                    getattr(self, "fastlane", None),
                    getattr(self, "_qos_usage_state", {}))
            except Exception:
                pass

    def stop(self) -> None:
        self._register_stop.set()
        self._fl_filer_on = False
        if self._fl_collector is not None:
            from seaweedfs_tpu.stats import default_registry

            default_registry().unregister_collector(self._fl_collector)
            self._fl_collector = None
        if getattr(self, "fastlane", None) is not None:
            self.fastlane.stop()
            self.fastlane = None
        self.service.stop()
        if self.metrics_service is not None:
            self.metrics_service.stop()
        self.filer.close()

    @property
    def url(self) -> str:
        if getattr(self, "fastlane", None) is not None:
            scheme = "https" if self.fastlane.tls else "http"
            return f"{scheme}://{self.service.host}:{self.fastlane.port}"
        return self.service.url

    # --- upload pipeline --------------------------------------------------------
    def _upload_chunks(
        self, data: bytes, ttl: str, collection: str, replication: str,
        mime: str = "", filename: str = "",
    ) -> tuple[list[FileChunk], str]:
        """Split into chunks, upload each, tee a whole-stream MD5
        (`filer_server_handlers_write_upload.go:30`). Each chunk is
        independently maybe-compressed (mime heuristic) and AES-GCM
        encrypted when the filer runs ciphered (`upload_content.go`)."""
        from seaweedfs_tpu.stats import trace

        if self.dedup:
            with trace.span("filer.upload_chunks_cdc", role="filer",
                            bytes=len(data)):
                return self._upload_chunks_cdc(
                    data, ttl, collection, replication, mime=mime,
                    filename=filename,
                )
        with trace.span("filer.upload_chunks", role="filer", bytes=len(data)):
            return self._upload_chunks_plain(
                data, ttl, collection, replication, mime=mime,
                filename=filename,
            )

    def _upload_chunks_plain(
        self, data: bytes, ttl: str, collection: str, replication: str,
        mime: str = "", filename: str = "",
    ) -> tuple[list[FileChunk], str]:
        ext = os.path.splitext(filename)[1]
        md5 = hashlib.md5()
        chunks: list[FileChunk] = []
        pieces = [
            data[o : o + self.chunk_size]
            for o in range(0, len(data), self.chunk_size)
        ]
        # per-chunk MD5 via the batch hash service: every chunk of this
        # upload (and of concurrent uploads) coalesces into one batch-kernel
        # call (`upload_content.go` md5 ETag semantics)
        etag_futures = get_hash_service().submit_many(pieces)
        # batched Assign: one master RPC leases fids for EVERY chunk of
        # this upload (base fid + _delta fids on one volume) instead of an
        # assign round-trip per chunk — on multi-chunk uploads the master
        # hop was costlier than the chunk POST itself
        batch_fids: list[str] | None = None
        batch_loc = batch_auth = ""
        if len(pieces) > 1:
            try:
                batch_fids, batch_loc, batch_auth = self.client.assign_batch(
                    len(pieces), replication=replication,
                    collection=collection, ttl=ttl,
                )
            except IOError:
                batch_fids = None  # per-chunk assigns still work
        offset = 0
        for i, piece in enumerate(pieces):
            md5.update(piece)
            logical_size = len(piece)
            payload, compressed = (
                maybe_compress_data(piece, mime, ext) if self.compress
                else (piece, False)
            )
            key_b64 = ""
            if self.cipher:
                payload, key = cipher_util.encrypt(payload)
                key_b64 = base64.b64encode(key).decode()
            if batch_fids is not None:
                out = self.client.upload_to(
                    batch_fids[i], batch_loc, payload, ttl=ttl,
                    auth=batch_auth,
                )
                out["fid"] = batch_fids[i]
            else:
                out = self.client.upload(
                    payload, replication=replication, collection=collection,
                    ttl=ttl,
                )
            chunks.append(
                FileChunk(
                    file_id=out["fid"],
                    offset=offset,
                    size=logical_size,
                    modified_ts_ns=time.time_ns(),
                    etag=out.get("eTag", ""),
                    cipher_key=key_b64,
                    is_compressed=compressed,
                )
            )
            offset += logical_size
        for chunk, fut in zip(chunks, etag_futures):
            chunk.etag = fut.md5_hex()
        if not data:
            md5.update(b"")
        return chunks, md5.hexdigest()

    def _upload_chunks_cdc(
        self, data: bytes, ttl: str, collection: str, replication: str,
        mime: str = "", filename: str = "",
    ) -> tuple[list[FileChunk], str]:
        """Dedup write path (filer/dedup.py, BASELINE config 4): cut at
        content-defined boundaries, key every chunk by its SW128 identity
        hash (span_keys — ~3.5x cheaper than MD5), and upload only the
        chunks whose (identity, length) key is new; known chunks reference
        the already-stored fileId, reusing the MD5 ETag recorded at insert.
        MD5 runs ONLY over index misses (their upload ETags) — on a dup-
        heavy stream almost no MD5 is paid at all. Boundaries follow
        content, so shifted or partially-edited re-uploads still dedup."""
        from seaweedfs_tpu.ops import cdc

        ext = os.path.splitext(filename)[1]
        md5 = hashlib.md5()
        md5.update(data)
        cuts = cdc.find_boundaries(
            memoryview(data), avg_bits=self.dedup_avg_bits,
            min_size=self.dedup_min, max_size=self.dedup_max,
            backend=cdc.pick_backend(),
        )
        hash_svc = get_hash_service()
        idx = self.dedup_index
        keys = hash_svc.span_keys(memoryview(data), cuts, seed=idx.seed)
        # pass 1: classify against the index; collect the miss spans.
        # A key repeating WITHIN this upload is a miss only once — later
        # occurrences defer to the first one's insert (sentinel "defer"),
        # preserving intra-upload dedup across the two-pass split.
        DEFER = "defer"
        recs: list[dict | str | None] = []
        miss_ranges: list[tuple[int, int]] = []
        seen_this_upload: set[str] = set()
        prev = 0
        for c, khash in zip(cuts, keys):
            ln = c - prev
            key = f"{khash}-{ln:x}"
            rec = idx.lookup(key)
            if rec is not None:
                # linearize vs gc: record the fid as freshly referenced, or
                # learn the key was condemned this instant and re-upload
                with self._dedup_mu:
                    if key in self._dedup_condemned:
                        rec = None
                    else:
                        self._dedup_recent[rec["fid"]] = time.monotonic()
            if rec is None and key in seen_this_upload:
                rec = DEFER
            recs.append(rec)
            if rec is None:
                miss_ranges.append((prev, ln))
                seen_this_upload.add(key)
            prev = c
        # pass 2: one MD5 batch over ONLY the missed spans (upload ETags)
        miss_md5s = iter(hash_svc.md5_spans(memoryview(data), miss_ranges))
        chunks: list[FileChunk] = []
        offset = 0
        prev = 0
        for c, khash, rec in zip(cuts, keys, recs):
            ln = c - prev
            key = f"{khash}-{ln:x}"
            defer_md5 = None
            if rec is DEFER:
                # repeat of an earlier chunk in this same upload: its
                # first occurrence has inserted by now (or was TTL'd /
                # condemned — then upload this occurrence individually)
                rec = idx.lookup(key)
                if rec is None:
                    defer_md5 = hash_svc.md5_spans(
                        memoryview(data), [(prev, ln)])[0]
            if rec is not None and not isinstance(rec, str):
                idx.hits += 1
                idx.bytes_saved += ln
                chunks.append(
                    FileChunk(
                        file_id=rec["fid"], offset=offset, size=ln,
                        modified_ts_ns=time.time_ns(),
                        etag=rec.get("etag", ""),
                        is_compressed=bool(rec.get("z")),
                    )
                )
            else:
                idx.misses += 1
                etag = defer_md5 if defer_md5 is not None else next(miss_md5s)
                piece = data[prev:c]  # bytes materialized only for uploads
                payload, compressed = (
                    maybe_compress_data(piece, mime, ext) if self.compress
                    else (piece, False)
                )
                out = self.client.upload(
                    payload, replication=replication, collection=collection,
                    ttl=ttl,
                )
                chunks.append(
                    FileChunk(
                        file_id=out["fid"], offset=offset, size=ln,
                        modified_ts_ns=time.time_ns(), etag=etag,
                        is_compressed=compressed,
                    )
                )
                # TTL'd chunks expire under shared references; skip the index
                if not ttl:
                    with self._dedup_mu:
                        self._dedup_condemned.discard(key)
                        self._dedup_recent[out["fid"]] = time.monotonic()
                    # shadow entry keyed by the chunk's MD5: lets
                    # _dedup_managed answer "is this fid index-owned?" from
                    # chunk metadata alone (it has no content to re-hash).
                    # Shadow FIRST: its lifetime must cover the primary's,
                    # or a crash window would leave a primary whose blob
                    # overwrite-reclaim no longer recognizes as shared.
                    idx.insert(f"m{etag}-{ln:x}",
                               {"fid": out["fid"], "p": key})
                    idx.insert(key, {"fid": out["fid"], "z": int(compressed),
                                     "etag": etag})
            prev = c
            offset += ln
        return chunks, md5.hexdigest()

    def _save_manifest_blob(self, blob: bytes) -> FileChunk:
        # manifests carry every per-chunk AES key — on a ciphered filer they
        # must be as opaque to volume servers as the data itself
        key_b64 = ""
        if self.cipher:
            blob, key = cipher_util.encrypt(blob)
            key_b64 = base64.b64encode(key).decode()
        out = self.client.upload(blob, collection=self.collection)
        return FileChunk(
            file_id=out["fid"], offset=0, size=len(blob),
            modified_ts_ns=time.time_ns(), cipher_key=key_b64,
        )

    def _fetch_chunk(self, chunk: FileChunk) -> bytes:
        raw = self.client.fetch(chunk.file_id)
        if chunk.cipher_key:
            raw = cipher_util.decrypt(raw, base64.b64decode(chunk.cipher_key))
        return raw

    def _resolved_chunks(self, entry: Entry) -> list[FileChunk]:
        return resolve_chunk_manifest(self._fetch_chunk, entry.chunks)

    # --- remote storage mounts (weed/remote_storage + read_remote.go) -----------
    def _load_remote_state(self) -> None:
        from seaweedfs_tpu.remote_storage import CONF_FILE, MOUNT_FILE

        for path, attr in ((CONF_FILE, "_remote_confs"),
                           (MOUNT_FILE, "_remote_mounts")):
            e = self.filer.find_entry(path)
            if e is not None and e.content:
                try:
                    setattr(self, attr, json.loads(e.content))
                except ValueError:
                    pass

    def _save_remote_state(self) -> None:
        from seaweedfs_tpu.remote_storage import CONF_FILE, MOUNT_FILE

        for path, value in ((CONF_FILE, self._remote_confs),
                            (MOUNT_FILE, self._remote_mounts)):
            body = json.dumps(value).encode()
            e = self.filer.find_entry(path)
            if e is None:
                e = Entry(full_path=path, content=body)
                e.attributes.file_size = len(body)
                self.filer.create_entry(e)
            else:
                e.content = body
                e.attributes.file_size = len(body)
                self.filer.update_entry(e)

    def _remote_mount_for(self, path: str):
        """Longest mounted prefix covering path -> (mount_dir, mount)."""
        best = None
        for d, mount in self._remote_mounts.items():
            if path == d or path.startswith(d.rstrip("/") + "/"):
                if best is None or len(d) > len(best[0]):
                    best = (d, mount)
        return best

    def _remote_client(self, config_name: str):
        from seaweedfs_tpu.remote_storage import make_remote_client

        conf = self._remote_confs.get(config_name)
        if conf is None:
            raise FilerError(f"remote config {config_name!r} not found")
        return make_remote_client(conf)

    def _remote_meta_sync(self, mount_dir: str) -> int:
        """Traverse the remote tree and (re)create stub entries carrying
        remote.* extended attrs and no chunks (`remote.mount`/`meta.sync`)."""
        from seaweedfs_tpu.remote_storage import (
            REMOTE_KEY, REMOTE_MTIME, REMOTE_SIZE, REMOTE_STORAGE,
        )

        mount = self._remote_mounts[mount_dir]
        client = self._remote_client(mount["config"])
        base = mount.get("path", "")
        n = 0
        for rel, size, mtime in client.traverse(base):
            full = normalize(f"{mount_dir}/{rel}")
            existing = self.filer.find_entry(full)
            key = f"{base.rstrip('/')}/{rel}".lstrip("/") if base else rel
            if existing is not None:
                ext = existing.extended
                if ext.get(REMOTE_KEY) == key and \
                        float(ext.get(REMOTE_MTIME, 0)) >= mtime:
                    continue  # unchanged
                existing.extended.update({
                    REMOTE_KEY: key, REMOTE_SIZE: str(size),
                    REMOTE_MTIME: str(mtime),
                    REMOTE_STORAGE: mount["config"],
                })
                existing.chunks = []  # changed upstream: drop stale cache
                existing.attributes.file_size = size
                self._reclaim_chunks(self.filer.update_entry(existing))
            else:
                e = Entry(full_path=full)
                e.attributes.file_size = size
                e.attributes.mtime = mtime
                e.extended = {
                    REMOTE_KEY: key, REMOTE_SIZE: str(size),
                    REMOTE_MTIME: str(mtime),
                    REMOTE_STORAGE: mount["config"],
                }
                self.filer.create_entry(e)
            n += 1
        return n

    def _remote_cache_entry(self, entry: Entry) -> Entry:
        """Read-through: pull remote bytes into local chunks on first access
        (`read_remote.go` CacheRemoteObjectToLocalCluster)."""
        from seaweedfs_tpu.remote_storage import REMOTE_KEY, REMOTE_STORAGE

        key = entry.extended.get(REMOTE_KEY)
        config = entry.extended.get(REMOTE_STORAGE)
        if not key or not config:
            return entry
        client = self._remote_client(config)
        data = client.read_file(key)
        if len(data) <= SMALL_CONTENT_LIMIT:
            entry.content = data
            entry.attributes.md5 = get_hash_service().submit(data).md5_hex()
        else:
            chunks, md5_hex = self._upload_chunks(
                data, "", self.collection, self.default_replication,
                mime=entry.attributes.mime, filename=entry.full_path,
            )
            entry.chunks = maybe_manifestize(self._save_manifest_blob, chunks)
            entry.attributes.md5 = md5_hex
        entry.attributes.file_size = len(data)
        self._reclaim_chunks(self.filer.update_entry(entry))
        return entry

    def _register_remote_routes(self, svc) -> None:
        @svc.route("POST", r"/__remote__/configure")
        def remote_configure(req: Request) -> Response:
            p = req.json()
            self._remote_confs[p["name"]] = p["conf"]
            self._save_remote_state()
            return Response({"ok": True, "configs": list(self._remote_confs)})

        @svc.route("POST", r"/__remote__/mount")
        def remote_mount(req: Request) -> Response:
            p = req.json()
            dir_ = normalize(p["dir"])
            if p.get("config") not in self._remote_confs:
                return Response(
                    {"error": f"unknown remote config {p.get('config')!r}"}, 400
                )
            self._remote_mounts[dir_] = {
                "config": p["config"], "path": p.get("path", ""),
            }
            self._save_remote_state()
            try:
                n = self._remote_meta_sync(dir_)
            except (FilerError, OSError, ValueError) as e:
                return Response({"error": str(e)}, 500)
            return Response({"ok": True, "dir": dir_, "synced": n})

        @svc.route("POST", r"/__remote__/mount_buckets")
        def remote_mount_buckets(req: Request) -> Response:
            # `command_remote_mount_buckets.go`: mount every bucket of a
            # configured remote under /buckets/<name> and pull metadata
            from seaweedfs_tpu.remote_storage import make_remote_client

            p = req.json()
            conf_name = p.get("config")
            conf = self._remote_confs.get(conf_name)
            if conf is None:
                return Response(
                    {"error": f"unknown remote config {conf_name!r}"}, 400)
            try:
                client = make_remote_client(conf)
                buckets = client.list_buckets()
            except (OSError, ValueError, NotImplementedError) as e:
                return Response({"error": f"list buckets: {e}"}, 500)
            mounted = []
            for b in buckets:
                dir_ = f"/buckets/{b}"
                # persist BEFORE syncing (like /__remote__/mount): a
                # partial failure must leave the completed mounts durable,
                # not in-memory-only until a restart drops them
                self._remote_mounts[dir_] = {"config": conf_name, "path": b}
                self._save_remote_state()
                try:
                    self._remote_meta_sync(dir_)
                except (FilerError, OSError, ValueError) as e:
                    return Response(
                        {"error": f"sync {dir_}: {e}", "mounted": mounted},
                        500)
                mounted.append(b)
            return Response({"ok": True, "mounted": mounted})

        @svc.route("POST", r"/__remote__/unmount")
        def remote_unmount(req: Request) -> Response:
            dir_ = normalize(req.json()["dir"])
            if self._remote_mounts.pop(dir_, None) is None:
                return Response({"error": f"{dir_} not mounted"}, 404)
            self._save_remote_state()
            return Response({"ok": True})

        @svc.route("GET", r"/__remote__/mounts")
        def remote_mounts(req: Request) -> Response:
            return Response({
                "mounts": self._remote_mounts,
                "configs": {k: v.get("kind", "?")
                            for k, v in self._remote_confs.items()},
            })

        @svc.route("POST", r"/__remote__/meta_sync")
        def remote_meta_sync(req: Request) -> Response:
            dir_ = normalize(req.json()["dir"])
            if dir_ not in self._remote_mounts:
                return Response({"error": f"{dir_} not mounted"}, 404)
            n = self._remote_meta_sync(dir_)
            return Response({"ok": True, "synced": n})

        @svc.route("POST", r"/__remote__/cache")
        def remote_cache(req: Request) -> Response:
            from seaweedfs_tpu.remote_storage import REMOTE_KEY

            path = normalize(req.json().get("dir", req.json().get("path", "/")))
            cached = 0

            def walk(p: str) -> None:
                nonlocal cached
                for e in self.filer.list_entries(p):
                    if e.is_directory:
                        walk(e.full_path)
                    elif e.extended.get(REMOTE_KEY) and not e.chunks \
                            and not e.content:
                        self._remote_cache_entry(e)
                        cached += 1

            entry = self.filer.find_entry(path)
            if entry is None:
                return Response({"error": f"{path} not found"}, 404)
            if entry.is_directory:
                walk(path)
            elif entry.extended.get(REMOTE_KEY):
                self._remote_cache_entry(entry)
                cached = 1
            return Response({"ok": True, "cached": cached})

        @svc.route("POST", r"/__remote__/uncache")
        def remote_uncache(req: Request) -> Response:
            from seaweedfs_tpu.remote_storage import REMOTE_KEY

            path = normalize(req.json().get("dir", "/"))
            dropped = 0

            def walk(p: str) -> None:
                nonlocal dropped
                for e in self.filer.list_entries(p):
                    if e.is_directory:
                        walk(e.full_path)
                    elif e.extended.get(REMOTE_KEY) and (e.chunks or e.content):
                        self._reclaim_chunks(e.chunks)
                        e.chunks = []
                        e.content = b""
                        self.filer.update_entry(e)
                        dropped += 1

            entry = self.filer.find_entry(path)
            if entry is None:
                return Response({"error": f"{path} not found"}, 404)
            if entry.is_directory:
                walk(path)
            elif entry.extended.get(REMOTE_KEY) and (
                entry.chunks or entry.content
            ):
                self._reclaim_chunks(entry.chunks)
                entry.chunks = []
                entry.content = b""
                self.filer.update_entry(entry)
                dropped = 1
            return Response({"ok": True, "uncached": dropped})

    # --- routes -----------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service
        path_re = r"(/.*)"
        self._register_remote_routes(svc)

        # metadata subscription (must register before the catch-all namespace):
        # long-poll equivalent of gRPC SubscribeMetadata
        # (`weed/server/filer_grpc_server_sub_meta.go`)
        @svc.route("GET", r"/__meta__/events")
        def meta_events(req: Request) -> Response:
            from seaweedfs_tpu.stats import trace

            trace.annotate(long_poll=True)  # slow by design: skip slow-log
            # native-write entries only become meta events when applied
            self._fl_filer_drain()
            since = int(req.query.get("since_ns", 0))
            limit = int(req.query.get("limit", 1024))
            wait = float(req.query.get("wait", 0))
            batch = self.filer.event_payloads_since(since, limit, wait=min(wait, 30.0))
            events = [json.loads(p) for _, p in batch]
            next_ts = batch[-1][0] if batch else since
            return Response(
                {"events": events, "next_ts_ns": next_ts,
                 "signature": self.filer.signature}
            )

        @svc.route("GET", r"/__dedup__/stats")
        def dedup_stats(req: Request) -> Response:
            if not self.dedup:
                return Response({"enabled": False})
            out = self.dedup_index.stats()
            out["enabled"] = True
            return Response(out)

        @svc.route("POST", r"/__dedup__/gc")
        def dedup_gc(req: Request) -> Response:
            if not self.dedup:
                return Response({"error": "dedup not enabled"}, 400)
            return Response(self.dedup_gc())

        # --- distributed lock manager (weed/cluster/lock_manager) ---
        @svc.route("POST", r"/__dlm__/lock")
        def dlm_lock(req: Request) -> Response:
            from seaweedfs_tpu.cluster import LockedError

            p = req.json()
            key = p["key"]
            target = self.lock_ring.server_for(key)
            if target and target != self.url:
                return Response({"moved_to": target}, 307)
            try:
                token, expires = self.dlm.lock(
                    key, p.get("owner", "?"), float(p.get("ttl_sec", 30)),
                    token=p.get("token", ""),
                )
            except LockedError as e:
                return Response({"error": str(e), "owner": e.owner}, 409)
            return Response(
                {"ok": True, "token": token, "expires_at": expires}
            )

        @svc.route("POST", r"/__dlm__/unlock")
        def dlm_unlock(req: Request) -> Response:
            from seaweedfs_tpu.cluster import LockedError

            p = req.json()
            key = p["key"]
            target = self.lock_ring.server_for(key)
            if target and target != self.url:
                return Response({"moved_to": target}, 307)
            try:
                self.dlm.unlock(key, p.get("token", ""))
            except LockedError as e:
                return Response({"error": str(e), "owner": e.owner}, 409)
            return Response({"ok": True})

        @svc.route("GET", r"/__dlm__/status")
        def dlm_status(req: Request) -> Response:
            return Response({
                "ring": self.lock_ring.servers(),
                "host": self.url,
            })

        @svc.route("POST", r"/__meta__/notify")
        def meta_notify(req: Request) -> Response:
            # `command_fs_meta_notify.go`: recursively (re)send every
            # entry under a directory to the notification queue so a
            # downstream replicator can bootstrap from existing data
            self._fl_filer_drain()
            p = req.json()
            root = normalize(p.get("directory", "/"))
            if self.filer.notification_queue is None:
                return Response({"error": "no notification queue"
                                          " configured"}, 400)
            sent = 0

            def walk(d: str) -> None:
                nonlocal sent
                for e in self.filer.list_entries(d, limit=1 << 31):
                    self.filer.notification_queue.send_message(
                        e.full_path,
                        {"directory": d, "old_entry": None,
                         "new_entry": e.to_dict(),
                         "ts_ns": time.time_ns(), "signatures": []},
                    )
                    sent += 1
                    if e.is_directory:
                        walk(e.full_path)

            walk(root)
            return Response({"sent": sent})

        @svc.route("POST", r"/__meta__/change_volume_id")
        def meta_change_volume_id(req: Request) -> Response:
            # `command_fs_meta_change_volume_id.go`: after volumes are
            # relocated/renumbered (e.g. cross-cluster copies), rewrite
            # the volume id inside chunk fids under a directory. The
            # blobs themselves moved — freed-chunk reclaim must not run.
            self._fl_filer_drain()
            p = req.json()
            root = normalize(p.get("directory", "/"))
            mapping = {str(k): str(v)
                       for k, v in (p.get("mapping") or {}).items()}
            if not mapping:
                return Response({"error": "empty volume id mapping"}, 400)
            changed = 0

            def rewrite(chunks) -> bool:
                hit = False
                for c in chunks:
                    vid, _, rest = c.file_id.partition(",")
                    if vid in mapping:
                        c.file_id = f"{mapping[vid]},{rest}"
                        hit = True
                return hit

            def walk(d: str) -> None:
                nonlocal changed
                for e in self.filer.list_entries(d, limit=1 << 31):
                    if e.is_directory:
                        walk(e.full_path)
                        continue
                    if rewrite(e.chunks):
                        self.filer.create_entry(e)  # freed fids ignored
                        changed += 1

            walk(root)
            return Response({"changed": changed})

        @svc.route("POST", r"/__meta__/merge_volumes")
        def meta_merge_volumes(req: Request) -> Response:
            # `command_fs_merge_volumes.go`: move chunks out of volume
            # `from_vid` into `to_vid` (needle key/cookie preserved, so
            # existing fids only change their volume part) and rewrite
            # the metadata; dry-run unless apply. Old blobs are deleted
            # after their entry is updated.
            self._fl_filer_drain()
            p = req.json()
            root = normalize(p.get("directory", "/"))
            from_vid = str(p.get("from_vid", ""))
            to_vid = str(p.get("to_vid", ""))
            apply = bool(p.get("apply"))
            if not from_vid or not to_vid or from_vid == to_vid:
                return Response(
                    {"error": "need distinct from_vid and to_vid"}, 400)
            try:
                targets = self.client.lookup(int(to_vid))
            except (IOError, ValueError) as e:
                return Response({"error": f"target volume: {e}"}, 400)
            target = targets[0]
            moved = planned = 0
            skipped: list[str] = []

            import copy as _copy

            from seaweedfs_tpu.server.httpd import http_request, peer_url

            manifest_skipped = 0

            def migrate(entry) -> bool:
                nonlocal moved, planned
                changed = False
                old_chunks = []
                for c in entry.chunks:
                    vid, _, rest = c.file_id.partition(",")
                    if vid != from_vid:
                        continue
                    planned += 1
                    if not apply:
                        continue
                    new_fid = f"{to_vid},{rest}"
                    try:
                        # key collision in the target volume would clobber
                        # a foreign needle (a same-key/other-cookie needle
                        # HEADs 404 but still fails the overwrite check
                        # below — caught the same way)
                        st, _, _ = http_request(
                            "HEAD", f"{peer_url(target)}/{new_fid}")
                        if st == 200:
                            skipped.append(c.file_id)
                            continue
                        data = self.client.fetch(c.file_id)
                        self.client.upload_to(new_fid, target, data)
                    except IOError:
                        skipped.append(c.file_id)
                        continue
                    old_chunks.append(_copy.copy(c))
                    c.file_id = new_fid
                    changed = True
                    moved += 1
                if changed:
                    self.filer.create_entry(entry)  # moved, not freed
                    # reclaim via the shared path: dedup-managed blobs
                    # (shared with other entries / the dedup index) are
                    # kept, everything else is deleted
                    self._reclaim_chunks(old_chunks)
                return changed

            def walk(d: str) -> None:
                nonlocal manifest_skipped
                for e in self.filer.list_entries(d, limit=1 << 31):
                    if e.is_directory:
                        walk(e.full_path)
                        continue
                    if any(c.is_chunk_manifest for c in e.chunks):
                        # inner manifest fids may live in from_vid too;
                        # migrating them means rewriting manifest blobs —
                        # report instead of claiming a full drain
                        manifest_skipped += 1
                        continue
                    if any(c.file_id.startswith(from_vid + ",")
                           for c in e.chunks):
                        migrate(e)

            walk(root)
            return Response({"planned": planned, "moved": moved,
                             "skipped": skipped,
                             "manifest_entries_skipped": manifest_skipped,
                             "applied": apply})

        @svc.route("GET", r"/__meta__/info")
        def meta_info(req: Request) -> Response:
            return Response(
                {
                    "signature": self.filer.signature,
                    "latest_ts_ns": self.filer.log_buffer.latest_ts_ns,
                    "master": self.client.master_url,
                    "chunk_size": self.chunk_size,
                }
            )

        @svc.route("GET", path_re)
        def read(req: Request) -> Response:
            shed = self._admit(req)
            if shed is not None:
                return shed
            resp = self._do_read(req, head=False)
            self._account_usage(req, resp, bytes_out=len(resp.body))
            return resp

        @svc.route("HEAD", path_re)
        def head(req: Request) -> Response:
            shed = self._admit(req)
            if shed is not None:
                return shed
            resp = self._do_read(req, head=True)
            self._account_usage(req, resp)
            return resp

        @svc.route("POST", path_re)
        def post(req: Request) -> Response:
            shed = self._admit(req)
            if shed is not None:
                return shed
            resp = self._do_write(req)
            self._account_usage(
                req, resp,
                bytes_in=int(req.headers.get("Content-Length") or 0))
            return resp

        @svc.route("PUT", path_re)
        def put(req: Request) -> Response:
            shed = self._admit(req)
            if shed is not None:
                return shed
            resp = self._do_write(req)
            self._account_usage(
                req, resp,
                bytes_in=int(req.headers.get("Content-Length") or 0))
            return resp

        @svc.route("DELETE", path_re)
        def delete(req: Request) -> Response:
            shed = self._admit(req)
            if shed is not None:
                return shed
            resp = self._do_delete(req)
            self._account_usage(req, resp)
            return resp

    def _resolve_collection(self, req: Request) -> str:
        """The tenant dimension both usage accounting AND qos admission
        key on — resolved exactly like the write path's placement:
        explicit ?collection=, then the fs.configure rule, then the
        filer default."""
        path = normalize(urllib.parse.unquote(req.path))
        coll = req.query.get("collection")
        if not coll and not path.startswith("/etc/"):
            rule = self.filer_conf.match(path) or {}
            coll = rule.get("collection")
        return coll or self.collection or "default"

    def _admit(self, req: Request) -> Response | None:
        """QoS admission at the engine boundary (qos/admission.py),
        BEFORE any bytes move. None = admitted; otherwise a typed
        429/503 with Retry-After and a machine-readable reason — never
        an untyped failure. The unconfigured path is one attribute
        check inside qos.admit."""
        from seaweedfs_tpu import qos as qos_mod

        if not qos_mod.controller().armed:
            return None
        try:
            coll = self._resolve_collection(req)
            cls = qos_mod.classify(req.method, req.headers)
            d = qos_mod.admit(coll, cls)
        except Exception:  # admission must never fail a request untyped
            return None
        if d is None:
            return None
        return Response(d.to_dict(), d.status, headers=d.headers())

    def _account_usage(self, req: Request, resp: Response,
                       bytes_in: int = 0, bytes_out: int = 0) -> None:
        """Tenant accounting for the Python front door (stats/usage.py).
        Requests the fastlane engine serves natively never reach these
        handlers — the accountant folds those in separately from the
        engine's per-collection counters, so nothing double-counts."""
        try:
            from seaweedfs_tpu.stats import usage as usage_mod

            usage_mod.accountant().record(
                self._resolve_collection(req),
                bytes_in=float(bytes_in), bytes_out=float(bytes_out),
                error=resp.status >= 500,
            )
        except Exception:  # accounting must never fail a request
            pass

    # --- handlers ---------------------------------------------------------------
    @staticmethod
    def _parse_signatures(req: Request) -> list[int]:
        """?signatures=1,2 — carried by filer.sync replays to break
        replication loops (`filer_sync.go:119-385`)."""
        raw = req.query.get("signatures", "")
        out = []
        for piece in raw.split(","):
            piece = piece.strip()
            if piece:
                try:
                    out.append(int(piece))
                except ValueError:
                    pass
        return out

    def _do_write(self, req: Request) -> Response:
        # read-your-writes across the native/Python boundary: overwrite
        # detection below must see entries the engine acked but Python
        # hasn't applied yet (same for reads and deletes)
        self._fl_filer_drain()
        path = normalize(urllib.parse.unquote(req.path))
        signatures = self._parse_signatures(req)
        if "mv.from" in req.query:
            # POST /new/path?mv.from=/old/path — rename/move, matching the
            # reference filer's mv.from query API (filer_server_handlers_write.go)
            try:
                self.filer.rename(req.query["mv.from"], path)
            except FilerError as e:
                return Response({"error": str(e)}, 409)
            return Response({"ok": True}, 200)
        if "link.from" in req.query:
            # POST /new/path?link.from=/old/path — hard link (the FUSE Link
            # flow, `weed/mount/weedfs_link.go:53`; counter semantics from
            # `weed/filer/filerstore_hardlink.go`)
            try:
                link = self.filer.create_hard_link(req.query["link.from"], path)
            except FilerError as e:
                return Response({"error": str(e)}, 409)
            return Response(
                {"ok": True, "nlink": link.hard_link_counter}, 201
            )
        if req.query.get("meta.entry") == "true":
            # raw metadata restore (fs.meta.load): entry dict incl. chunks
            try:
                entry = Entry.from_dict(req.json())
                entry.full_path = path
                freed = self.filer.create_entry(entry, signatures=signatures)
                self._reclaim_chunks(freed)
            except (FilerError, KeyError, ValueError) as e:
                return Response({"error": str(e)}, 409)
            return Response({"name": entry.name}, 201)
        if path.endswith("/") or req.query.get("mkdir") == "true":
            e = Entry(full_path=path, is_directory=True,
                      attributes=Attributes(mode=0o755))
            self.filer.create_entry(e, signatures=signatures)
            return Response({"name": e.name}, 201)
        part = req.multipart_file()
        if part is not None:
            filename, mime, data = part
        else:
            data = req.body
            mime = req.headers.get("Content-Type", "")
            filename = path.rsplit("/", 1)[-1]
        # fs.configure per-path rules (filer_conf.go): longest prefix wins;
        # explicit query params still override the rule's defaults. The
        # /etc/ config area is EXEMPT — a broad read-only rule must never
        # brick the very file that removes it.
        rule = {} if path.startswith("/etc/") else (
            self.filer_conf.match(path) or {})
        if rule.get("read_only"):
            return Response(
                {"error": f"{rule.get('location_prefix')} is read-only"
                          " (fs.configure)"}, 403)
        rule_ttl = rule.get("ttl") or ""
        if rule_ttl:
            from seaweedfs_tpu.storage.types import TTL as _TTL

            try:  # a malformed persisted rule must not 500 a whole subtree
                _TTL.parse(rule_ttl)
            except (ValueError, KeyError):
                glog.warning("fs.configure rule %s has invalid ttl %r;"
                             " ignoring it", rule.get("location_prefix"),
                             rule_ttl)
                rule_ttl = ""
        ttl = req.query.get("ttl") or rule_ttl
        collection = (req.query.get("collection") or rule.get("collection")
                      or self.collection)
        replication = (req.query.get("replication")
                       or rule.get("replication")
                       or self.default_replication)

        from seaweedfs_tpu.storage.types import TTL

        entry = Entry(full_path=path)
        entry.attributes.mime = mime
        entry.attributes.file_size = len(data)
        entry.attributes.ttl_sec = TTL.parse(ttl).minutes() * 60
        entry.attributes.mtime = time.time()
        # /etc/seaweedfs/ config files are ALWAYS inlined: their
        # loaders (filer.conf hot-reload) read entry.content, and a
        # config silently chunked past 2KB would parse as empty —
        # rules vanishing without a trace
        if (len(data) <= SMALL_CONTENT_LIMIT
                or (path.startswith("/etc/seaweedfs/")
                    and len(data) <= 4 * 1024 * 1024)):
            entry.content = data
            entry.attributes.md5 = get_hash_service().submit(data).md5_hex()
        else:
            chunks, md5_hex = self._upload_chunks(
                data, ttl, collection, replication, mime=mime, filename=filename
            )
            entry.chunks = maybe_manifestize(self._save_manifest_blob, chunks)
            entry.attributes.md5 = md5_hex
        old_entry = self.filer.find_entry(path)
        try:
            freed = self.filer.create_entry(entry, signatures=signatures)
        except FilerError as e:
            return Response({"error": str(e)}, 409)
        if old_entry is not None and old_entry.hard_link_id:
            # hardlinked target: surviving links still reference the shared
            # chunks — reclaim only what the detach actually freed
            self._reclaim_chunks(freed)
        elif old_entry is not None and old_entry.chunks:
            self._reclaim_chunks(old_entry.chunks)  # overwritten version's blobs
        return Response(
            {"name": entry.name, "size": len(data), "md5": entry.attributes.md5},
            201,
        )

    def _reclaim_chunks(self, chunks) -> None:
        for c in chunks:
            try:
                if c.is_chunk_manifest:
                    for inner in resolve_chunk_manifest(self._fetch_chunk, [c]):
                        if not self._dedup_managed(inner):
                            self.client.delete(inner.file_id)
                    self.client.delete(c.file_id)  # manifests are never shared
                    continue
                if self._dedup_managed(c):
                    continue
                self.client.delete(c.file_id)
            except Exception:
                pass

    def _dedup_managed(self, chunk: FileChunk) -> bool:
        """True when the chunk's blob is owned by the dedup index — other
        entries may reference the same fid, so delete/overwrite must not
        reclaim it (`fs.dedup.gc` does, once nothing references it).
        Consults the MD5-keyed shadow entry ("m<md5>-<len>", written next
        to every SW128 primary) because chunk metadata carries only the
        MD5 ETag; legacy md5-primary keys (pre-SW128 indexes) still match
        via the bare-key fallback."""
        if not self.dedup or not chunk.etag:
            return False
        for key in (f"m{chunk.etag}-{chunk.size:x}",
                    f"{chunk.etag}-{chunk.size:x}"):
            rec = self.dedup_index.lookup(key)
            if rec is None:
                continue
            if rec.get("fid") == chunk.file_id:
                return True
            # racing first-uploads of the same content can leave the
            # shadow pointing at the loser's fid while the primary (the
            # fid dup-hits actually hand out) holds the winner's — follow
            # the shadow's primary pointer so the winner stays protected
            primary = rec.get("p")
            if primary:
                prec = self.dedup_index.lookup(primary)
                if prec is not None and prec.get("fid") == chunk.file_id:
                    return True
        return False

    def dedup_gc(self) -> dict:
        """Walk the namespace, then drop every index entry (and delete its
        blob) that no live entry references. The reclaim path promised by
        `filer/dedup.py`. Concurrency-safe against in-flight dedup'd
        uploads: a lookup-hit records its fid in `_dedup_recent` under
        `_dedup_mu` before the entry exists, and the gc decision runs under
        the same lock — so a hit either precedes the decision (gc skips the
        fid as recently referenced) or follows the key's condemnation (the
        upload sees `_dedup_condemned` and re-uploads instead)."""
        from seaweedfs_tpu.filer.dedup import DEDUP_DIR

        gc_start = time.monotonic()
        referenced: set[str] = set()

        def walk(p: str) -> None:
            for e in self.filer.list_entries(p, limit=1 << 31):
                if e.is_directory:
                    if e.full_path != DEDUP_DIR:
                        walk(e.full_path)
                    continue
                chunks = e.chunks
                if any(c.is_chunk_manifest for c in chunks):
                    # a manifest we cannot resolve hides data fids — any
                    # error here must abort the gc, not shrink the pin set
                    chunks = self._resolved_chunks(e)
                for c in chunks:
                    referenced.add(c.file_id)

        try:
            walk("/")
        except Exception as e:
            return {"error": f"namespace walk failed, gc aborted: {e}",
                    "scanned": 0, "dropped": 0, "bytes_freed": 0, "errors": 1}
        scanned = dropped = freed = errors = 0
        for key, rec in list(self.dedup_index.iter_records()):
            scanned += 1
            fid = rec.get("fid", "")
            if not fid or fid in referenced:
                continue
            # Shadow entries ("m<md5>-<len>") must OUTLIVE their primary —
            # a shadow removed while the primary still hands out the fid
            # would let overwrite-reclaim delete a shared blob. They are
            # only swept here once their primary is gone (crash orphans).
            is_shadow = key.startswith("m") and len(key) > 33
            if is_shadow:
                primary = rec.get("p", "")
                if primary and self.dedup_index.lookup(primary) is not None:
                    continue  # primary alive: the pair drops together below
                try:
                    self.dedup_index.remove(key)
                except Exception:
                    errors += 1
                continue
            with self._dedup_mu:
                # referenced (or re-inserted) since the walk began: keep
                ts = self._dedup_recent.get(fid)
                if ts is not None and ts >= gc_start - 1.0:
                    continue
                self._dedup_condemned.add(key)
            try:
                # index entry first: if this fails the blob merely leaks and
                # a later gc retries; the reverse order would leave the index
                # handing out a deleted fid (silent data loss)
                self.dedup_index.remove(key)
            except Exception:
                errors += 1
                continue
            # the paired shadow goes with its primary (etag recorded at
            # insert); failure just leaves an orphan the next gc sweeps
            etag = rec.get("etag", "")
            if etag:
                try:
                    self.dedup_index.remove(
                        f"m{etag}-{key.rsplit('-', 1)[1]}")
                except Exception:
                    pass
            try:
                self.client.delete(fid)
            except Exception:
                errors += 1  # blob leaked; index is already consistent
                continue
            dropped += 1
            try:
                freed += int(key.rsplit("-", 1)[1], 16)
            except (IndexError, ValueError):
                pass
        with self._dedup_mu:  # prune the recency map so it stays bounded
            cutoff = time.monotonic() - 600.0
            self._dedup_recent = {
                f: t for f, t in self._dedup_recent.items() if t > cutoff
            }
        return {
            "scanned": scanned, "dropped": dropped,
            "bytes_freed": freed, "errors": errors,
        }

    def _do_read(self, req: Request, head: bool) -> Response:
        self._fl_filer_drain()
        path = normalize(urllib.parse.unquote(req.path))
        entry = self.filer.find_entry(path)
        if entry is None:
            return Response({"error": f"{path} not found"}, 404)
        if req.query.get("metadata") == "true":
            return Response(entry.to_dict())
        if entry.is_directory:
            if req.headers.get("X-Sw-S3"):
                # S3-front relay: object keys never resolve to listings —
                # the gateway translates this into NoSuchKey
                return Response({"error": f"{path} is a directory"}, 404)
            return self._list_dir(req, entry)
        if (
            entry.attributes.ttl_sec > 0
            and entry.attributes.mtime + entry.attributes.ttl_sec < time.time()
        ):
            self.filer.delete_entry(path)  # expired: reap lazily
            return Response({"error": f"{path} expired"}, 404)
        if not entry.content and not entry.chunks:
            from seaweedfs_tpu.remote_storage import REMOTE_KEY

            if entry.extended.get(REMOTE_KEY):
                # read-through: cache the remote object locally on first
                # access (`read_remote.go` CacheRemoteObjectToLocalCluster)
                try:
                    entry = self._remote_cache_entry(entry)
                except (FilerError, OSError) as e:
                    return Response({"error": f"remote fetch: {e}"}, 502)
        # a Python-served read is the out-of-lock chance to (re)populate
        # the engine's path cache (the meta-log subscriber can only peek
        # at volume locations; here a blocking lookup is safe)
        if getattr(self, "_fl_filer_on", False) and self.fastlane is not None:
            self._fl_cache_push(entry, blocking_lookup=True)
        etag = entry.attributes.md5 or str(entry.attributes.mtime)
        headers = {
            "ETag": f'"{etag}"',
            "Accept-Ranges": "bytes",
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(entry.attributes.mtime)
            ),
        }
        if entry.attributes.mime:
            headers["Content-Type"] = entry.attributes.mime
        if req.headers.get("If-None-Match") == f'"{etag}"':
            return Response(b"", 304, headers)
        size = entry.size()
        start, end = 0, size - 1
        status = 200
        rng = req.headers.get("Range")
        if rng and rng.startswith("bytes=") and "," not in rng:
            spec = rng[6:]
            s, _, e = spec.partition("-")
            try:
                start = int(s) if s else max(0, size - int(e))
                end = int(e) if e and s else size - 1
            except ValueError:
                # RFC 7233: unintelligible specs are ignored (full entity)
                # — same rule as the native paths (parse_range_spec)
                start, end = 0, size - 1
            else:
                end = min(end, size - 1)
                if start > end:
                    return Response(
                        b"", 416, {"Content-Range": f"bytes */{size}"})
                status = 206
                headers["Content-Range"] = f"bytes {start}-{end}/{size}"
        if head:
            headers["X-File-Size"] = str(size)
            headers["Content-Length"] = str(size)
            return Response(b"", 200 if status == 200 else status, headers)
        body = self._read_range(entry, start, end - start + 1)
        return Response(body, status, headers)

    def _read_range(self, entry: Entry, offset: int, size: int) -> bytes:
        """Visible-interval resolution + ranged chunk fetches
        (`filer/stream.go:153` StreamContent)."""
        if entry.content:
            return entry.content[offset : offset + size]
        chunks = self._resolved_chunks(entry)
        by_fid = {c.file_id: c for c in chunks}
        views = view_from_chunks(chunks, offset, size)
        buf = bytearray(size)
        for view in views:
            chunk = by_fid.get(view.file_id)
            if chunk is not None and (chunk.cipher_key or chunk.is_compressed):
                # transformed chunks can't be range-read on the volume
                # server; fetch whole via the tiered cache, decode, slice
                # (`filer/stream.go` fetchChunkRange → ReaderCache)
                piece = self._fetch_whole_chunk(chunk)[
                    view.offset_in_chunk : view.offset_in_chunk + view.size
                ]
            else:
                rng = (
                    f"bytes={view.offset_in_chunk}-"
                    f"{view.offset_in_chunk + view.size - 1}"
                )
                piece = self.client.fetch(view.file_id, range_header=rng)
            dst = view.view_offset - offset
            buf[dst : dst + len(piece)] = piece
        return bytes(buf)

    def _fetch_whole_chunk(self, chunk: FileChunk) -> bytes:
        """Whole-chunk fetch + decrypt + decompress. Decoded ciphertext is
        cached in memory only — the disk tiers must never hold plaintext of
        encrypted chunks (the reference's ReaderCache is mem-only too)."""
        cached = (
            self.chunk_cache.mem.get(chunk.file_id) if chunk.cipher_key
            else self.chunk_cache.get_chunk(chunk.file_id)
        )
        if cached is not None:
            return cached
        raw = self.client.fetch(chunk.file_id)
        if chunk.cipher_key:
            raw = cipher_util.decrypt(raw, base64.b64decode(chunk.cipher_key))
        if chunk.is_compressed:
            raw = decompress_data(raw)
        if chunk.cipher_key:
            self.chunk_cache.mem.set(chunk.file_id, raw)
        else:
            self.chunk_cache.set_chunk(chunk.file_id, raw)
        return raw

    def _list_dir(self, req: Request, entry: Entry) -> Response:
        limit = int(req.query.get("limit", 1024))
        last = req.query.get("lastFileName", "")
        entries = self.filer.list_entries(entry.full_path, last, False, limit)
        accept = (req.headers.get("Accept") or "")
        if "text/html" in accept and "application/json" not in accept:
            # browsers get the directory browser (`weed/server/filer_ui`);
            # API clients keep the JSON listing. Attribute values go
            # through quoteattr (escape() leaves double quotes — an XSS
            # hole via filenames) and hrefs are percent-encoded (names
            # with %/#/? would link to the wrong resource otherwise).
            from xml.sax.saxutils import escape as _esc
            from xml.sax.saxutils import quoteattr as _qa

            def _href(p: str) -> str:
                return _qa(urllib.parse.quote(p))

            rows = []
            if entry.full_path != "/":
                rows.append(f"<tr><td><a href={_href(entry.parent)}>..</a>"
                            "</td><td></td><td></td></tr>")
            for e in entries:
                name = _esc(e.name) + ("/" if e.is_directory else "")
                size = "" if e.is_directory else str(e.size())
                mtime = time.strftime(
                    "%Y-%m-%d %H:%M", time.localtime(e.attributes.mtime))
                rows.append(f"<tr><td><a href={_href(e.full_path)}>{name}"
                            f'</a></td><td align="right">{size}</td>'
                            f"<td>{mtime}</td></tr>")
            more = ""
            if len(entries) == limit:
                next_url = (f"{urllib.parse.quote(entry.full_path)}"
                            f"?lastFileName="
                            f"{urllib.parse.quote_plus(entries[-1].name)}"
                            f"&limit={limit}")
                more = f"<p><a href={_qa(next_url)}>more…</a></p>"
            html = (
                "<html><head><title>seaweedfs-tpu filer"
                f" {_esc(entry.full_path)}</title></head><body>"
                f"<h3>{_esc(entry.full_path)}</h3>"
                '<table cellpadding="4">'
                "<tr><th align=\"left\">name</th>"
                "<th align=\"right\">size</th>"
                "<th align=\"left\">modified</th></tr>"
                + "".join(rows) + f"</table>{more}</body></html>"
            )
            return Response(html.encode(),
                            content_type="text/html; charset=utf-8")
        return Response(
            {
                "Path": entry.full_path,
                "Entries": [
                    {
                        "FullPath": e.full_path,
                        "IsDirectory": e.is_directory,
                        "FileSize": e.size(),
                        "Mtime": e.attributes.mtime,
                        "Mime": e.attributes.mime,
                        "Md5": e.attributes.md5,
                    }
                    for e in entries
                ],
                "LastFileName": entries[-1].name if entries else "",
                "ShouldDisplayLoadMore": len(entries) == limit,
            }
        )

    def _do_delete(self, req: Request) -> Response:
        self._fl_filer_drain()
        path = normalize(urllib.parse.unquote(req.path))
        rule = {} if path.startswith("/etc/") else (
            self.filer_conf.match(path) or {})
        if rule.get("read_only"):
            return Response(
                {"error": f"{rule.get('location_prefix')} is read-only"
                          " (fs.configure)"}, 403)
        recursive = req.query.get("recursive") == "true"
        try:
            chunks = self.filer.delete_entry(
                path, recursive=recursive,
                signatures=self._parse_signatures(req),
            )
        except FilerError as e:
            return Response({"error": str(e)}, 409)
        self._reclaim_chunks(chunks)
        return Response(b"", 204)
