"""Volume server: HTTP data plane + admin plane + heartbeat loop.

Reference: `weed/server/volume_server_handlers_read.go:45` /
`_write.go:18` (GET/POST/DELETE /<vid>,<fid>), `store_replicate.go:26`
(synchronous replica fan-out), `volume_grpc_erasure_coding.go` (EC verbs —
JSON admin endpoints here), `volume_grpc_client_to_master.go:50` (heartbeat).
"""

from __future__ import annotations

import json
import queue
import re
import threading
import time
import urllib.parse

import numpy as np

from seaweedfs_tpu.security import Guard, SecurityConfig
from seaweedfs_tpu.security.jwt import token_from_request, verify_file_jwt
from seaweedfs_tpu.storage import crc as crc_mod
from seaweedfs_tpu.storage.erasure_coding import decoder as ec_decoder
from seaweedfs_tpu.storage.erasure_coding import encoder as ec_encoder
from seaweedfs_tpu.storage.erasure_coding import geometry
from seaweedfs_tpu.storage.file_id import parse_key_hash_with_delta
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import NotFound, VolumeError, volume_file_name
from seaweedfs_tpu.util import faults
from seaweedfs_tpu.util.retry import READ_POLICY

from .httpd import HTTPService, Request, Response, get_json, http_request, post_json, peer_url

FID_RE = r"/(\d+),([0-9a-fA-F_]+)(?:\.[^/]*)?"
_SAFE_EXT_RE = re.compile(r"\.(dat|idx|vif|ecx|ecj|ec\d\d)")

# partition-from-peer faults: the heartbeat seam drops beats (the master
# sees staleness, evacuate fires), the fan-out seam fails replica pushes
# (the client retries with a fresh assignment). Each passes the server's
# identity as the scope key so in-process test clusters can fault ONE node.
_FP_HEARTBEAT = faults.register("volume.heartbeat.send")
_FP_REPLICATE = faults.register("volume.replicate.fanout")
# pipelined-rebuild hop seam: an `error` here kills one node's partial-sum
# stage mid-chain — the orchestrator's retry ladder must restart the chain
# minus this hop or fall back to classic whole-shard pulls
_FP_PARTIAL = faults.register("repair.partial_fetch")

# streaming rebuild sessions: bounded in-flight window per hop (chunks
# parked on the forward queue) and the stall budget after which a hop
# declares its downstream wedged (the orchestrator's ladder restarts)
STREAM_WINDOW = 4
STREAM_STALL_TIMEOUT = 30.0
STREAM_SESSION_MAX_AGE = 600.0


class _PartialError(Exception):
    """A partial-sum hop step failed; `payload` is the attribution dict
    the orchestrator's retry ladder reads (error, failed_hop_server)."""

    def __init__(self, payload: dict, status: int) -> None:
        super().__init__(payload.get("error", "partial step failed"))
        self.payload = payload
        self.status = status


class VolumeServer:
    def __init__(
        self,
        directories: list[str],
        master_url: str,
        host: str = "127.0.0.1",
        port: int = 8080,
        public_url: str = "",
        data_center: str = "",
        rack: str = "",
        pulse_seconds: int = 5,
        max_volume_count: int = 100,
        security: SecurityConfig | None = None,
        local_socket: str | None = None,
        slow_ms: float | None = None,
        scrub_interval: float = 0.0,
        scrub_rate_mb: float = 8.0,
        telemetry_dir: str | None = None,
        telemetry_retention_mb: float | None = None,
    ) -> None:
        # -mserver may list several masters; heartbeats follow the raft
        # leader hint (`volume_grpc_client_to_master.go` re-dial on redirect)
        self.master_urls = [
            peer_url(u)
            for u in master_url.split(",") if u
        ]
        self.master_urls = [u.rstrip("/") for u in self.master_urls]
        self.master_url = self.master_urls[0]
        self.security = security or SecurityConfig()
        self.service = HTTPService(host, port)
        if self.security.white_list:
            self.service.guard = Guard(self.security.white_list)
        self.service.enable_metrics("volume")
        # -telemetry.dir: durable spool under the data dir — pre-crash
        # history/events replay into the rings before traffic starts, so
        # /debug/metrics/history and /debug/events survive a kill -9
        if telemetry_dir:
            from seaweedfs_tpu.stats import store as store_mod

            store_mod.enable(telemetry_dir, telemetry_retention_mb)
        if slow_ms is not None:  # -slowMs: per-role slow-span threshold
            from seaweedfs_tpu.stats import trace as _trace

            _trace.set_slow_threshold_ms(slow_ms, role="volume")
        self.store: Store | None = None
        self._dirs = directories
        self._host = host
        self._public_url = public_url
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.max_volume_count = max_volume_count
        self.volume_size_limit = 30 * 1024 * 1024 * 1024
        self._stop = threading.Event()
        self.fastlane = None  # native data-plane front door when available
        self.local_socket = local_socket  # same-host unix listener
        self._metrics_collector = None  # registry handle (start/stop)
        # in-flight pipelined rebuilds: vid -> {writers, targets, ...}.
        # The orchestrator drives start -> partial chunks -> commit; a
        # replaced/aborted state discards its tmp files (never a
        # half-written file under a valid shard name).
        self._partial_rebuilds: dict[int, dict] = {}
        self._partial_lock = threading.Lock()
        # streaming rebuild sessions (the hop-parallel half of the
        # pipelined plane): session id -> per-hop state. Each hop ACKs a
        # chunk after scaling its local shards and parking the XOR'd sum
        # on a bounded forward queue; a forwarder thread ships it
        # downstream while the hop computes the NEXT chunk — an H-hop,
        # N-chunk rebuild costs ~(H + N) chunk-times instead of H x N.
        self._partial_streams: dict[str, dict] = {}
        self._stream_lock = threading.Lock()
        # background integrity scrubber (maintenance/scrub.py): walks
        # volumes/EC shards in token-bucket-throttled passes. -scrub.
        # interval 0 disables the loop; /admin/scrub/run still works.
        self.scrubber = None
        self.scrub_interval = float(scrub_interval)
        self.scrub_rate_mb = float(scrub_rate_mb)
        self._routes()

    def _start_fastlane(self) -> None:
        """Put the native epoll engine (storage/fastlane.py) in front of the
        Python service: it serves data-plane GET/POST/PUT/DELETE across all
        cores and proxies everything else here."""
        from seaweedfs_tpu.storage import fastlane as fl_mod

        # the signing keys ride into sw_fl_start so they are in place before
        # the engine accepts its first connection: reads/writes stay native
        # when the token verifies; invalid/missing tokens proxy to Python
        # for the exact 401
        self.fastlane = fl_mod.front_service(
            self.service, guard_active=bool(self.security.white_list),
            jwt_write_key=self.security.write_key or "",
            jwt_read_key=self.security.read_key or "",
            secure_reads=bool(self.security.read_key),
        )

    @property
    def data_port(self) -> int:
        return self.fastlane.port if self.fastlane else self.service.port

    def start(self) -> None:
        self._start_fastlane()
        if self.local_socket:
            self.service.enable_unix_socket(self.local_socket)
        self.store = Store(
            self._dirs,
            ip=self._host,
            port=self.data_port,
            public_url=self._public_url,
        )
        if self.fastlane:
            for vid in self.store.volume_ids():
                self._fl_register(vid)
            threading.Thread(target=self._fl_drain_loop, daemon=True).start()
            # tenant accounting: native ops never reach a Python handler,
            # so the accountant folds the engine's per-collection counter
            # deltas in at scrape time
            from seaweedfs_tpu.stats import usage as usage_mod

            usage_mod.accountant().attach_engine(self.fastlane)
        self._register_metrics_collector()
        for loc in self.store.locations:
            loc.max_volume_count = self.max_volume_count
        for loc in self.store.locations:
            for ev in loc.ec_volumes.values():
                self._attach_shard_fetcher(ev)
        from seaweedfs_tpu.maintenance.scrub import VolumeScrubber

        self.scrubber = VolumeScrubber(
            self.store, node_id=f"{self._host}:{self.data_port}",
            rate_mb=self.scrub_rate_mb,
            active_tmp_paths=self._active_rebuild_tmps,
        )
        if self.scrub_interval > 0:
            threading.Thread(target=self._scrub_loop, daemon=True).start()
        self.heartbeat_once()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        # Calibrate the EC pipeline backend (host GFNI vs TPU, measured
        # link rate) at boot instead of inside the first ec.encode request —
        # on a relayed chip the probe incl. jax init costs seconds that a
        # data-plane RPC should never absorb. Result is process-cached.
        def _calibrate():  # pragma: no cover - timing-dependent
            try:
                from seaweedfs_tpu.ops.rs_kernel import pick_pipeline_backend

                pick_pipeline_backend()
            except Exception:
                pass

        threading.Thread(target=_calibrate, daemon=True).start()

    def stop(self) -> None:  # idempotent: fixtures may stop twice
        self._stop.set()
        if self._metrics_collector is not None:
            from seaweedfs_tpu.stats import default_registry

            default_registry().unregister_collector(self._metrics_collector)
            self._metrics_collector = None
        if self.fastlane:
            from seaweedfs_tpu.stats import usage as usage_mod

            usage_mod.accountant().detach_engine(self.fastlane)
            self.fastlane.drain()
            self.fastlane.stop()
            self.fastlane = None
        self.service.stop()
        with self._partial_lock:  # orphaned rebuild tmp files die with us
            for state in self._partial_rebuilds.values():
                state["writers"].abort()
            self._partial_rebuilds.clear()
        with self._stream_lock:  # wake forwarder threads so they exit
            streams, self._partial_streams = (
                list(self._partial_streams.values()), {})
        for st in streams:
            self._teardown_stream(st)
        if self.store:
            self.store.close()
            self.store = None

    @property
    def url(self) -> str:
        if self.fastlane:
            scheme = "https" if self.fastlane.tls else "http"
            return f"{scheme}://{self._host}:{self.fastlane.port}"
        return self.service.url

    # --- fastlane lifecycle -----------------------------------------------------
    def _fl_forward_writes(self, v) -> bool:
        """Writes the engine must hand to Python: replicated volumes (the
        fan-out runs here) — see _do_write. Online-EC volumes ack on local
        durability + parity emit, so they stay native even when their
        placement nominally demands replicas."""
        if v.online_ec is not None and v.online_ec.active:
            return False
        rp = v.super_block.replica_placement
        return rp is not None and rp.copy_count() > 1

    def _fl_register(self, vid: int) -> None:
        if not self.fastlane:
            return
        v = self.store.get_volume(vid)
        if v is not None:
            if self.fastlane.register_volume(v, self._fl_forward_writes(v)) \
                    and v.online_ec is not None:
                # arm the engine's O(1) stripe accumulator: the drain
                # loop polls readiness instead of re-checking tails
                self.fastlane.ec_online_arm(
                    vid, v.online_ec.stripe, v.online_ec.watermark
                )

    def _fl_unregister(self, vid: int) -> None:
        if self.fastlane:
            self.fastlane.unregister_volume(vid)  # waits in-flight + drains

    def _fl_sync_flags(self, vid: int) -> None:
        if not self.fastlane:
            return
        v = self.store.get_volume(vid)
        if v is not None:
            self.fastlane.set_flags(vid, v.readonly, self._fl_forward_writes(v))

    def _fl_drain_loop(self) -> None:  # pragma: no cover - timing loop
        tick = 0
        last = {"native_reads": 0, "native_writes": 0, "native_deletes": 0,
                "proxied": 0}
        while not self._stop.is_set():
            try:
                self.fastlane.drain()
                self._pump_online_ec()
                tick += 1
                if tick % 50 == 0:  # ~1s flag reconcile (low-disk readonly...)
                    for vid in list(self.fastlane._volumes):
                        self._fl_sync_flags(vid)
                    self._fl_fold_metrics(last)
            except Exception:
                pass
            self._stop.wait(0.02)

    def _pump_online_ec(self) -> None:
        """Stream engine-written bytes through the online RS encoder:
        native appends never touch a Python handler, so the drain loop is
        their encode hook. The engine's stripe accumulator answers
        readiness in O(1); only a full stripe (or an aged partial row —
        the timed trickle flush) invokes the Python-side encode."""
        if self.store is None:
            return
        for loc in self.store.locations:
            for v in list(loc.volumes.values()):
                w = v.online_ec
                if w is None or not w.active or w.sealed:
                    continue
                pend = (
                    self.fastlane.ec_online_pending(v.id)
                    if self.fastlane else None
                )
                if pend is not None:
                    full_stripes, tail = pend
                    if full_stripes <= 0 and tail <= w.watermark and \
                            w._pending_since is None:
                        continue  # nothing new, nothing aging out
                w.pump()
                if pend is not None:
                    # unconditional re-sync: a Python-path handler pump
                    # advances the watermark without touching the engine,
                    # and a stale armed watermark would report 'pending'
                    # forever (defeating this very skip)
                    self.fastlane.ec_online_advance(v.id, w.watermark)

    def _fl_fold_metrics(self, last: dict) -> None:
        """Natively-served requests never reach the instrumented Python
        handlers; fold the engine's counters into the Prometheus registry
        so request-rate dashboards keep seeing the data plane. (Latency
        histograms remain Python-path-only.)"""
        svc = self.service
        if svc.metrics_role is None:
            return
        stats = self.fastlane.stats()
        for key, method, code in (
            ("native_reads", "GET", "200"),
            ("native_writes", "POST", "201"),
            ("native_deletes", "DELETE", "202"),
        ):
            delta = stats[key] - last[key]
            if delta > 0:
                svc._m_total.labels(svc.metrics_role, method, code).inc(delta)
            last[key] = stats[key]
        last["proxied"] = stats["proxied"]  # proxied ones count in Python

    # --- metrics collector ------------------------------------------------------
    FL_FAMILIES = (
        "SeaweedFS_volume_fastlane_requests_total",
        "SeaweedFS_volume_fastlane_request_seconds",
        "SeaweedFS_volume_fastlane_bytes_total",
        "SeaweedFS_volume_fastlane_proxied_total",
        "SeaweedFS_volume_fastlane_volume_requests_total",
        "SeaweedFS_volume_fastlane_volume_bytes_total",
        "SeaweedFS_volume_disk_used_bytes",
        "SeaweedFS_volume_disk_free_bytes",
    )

    def _register_metrics_collector(self) -> None:
        """Scrape-time exporter for the series the Python registry cannot
        count itself: the fastlane engine's per-op histograms/byte counters
        (C-side atomics, read via sw_fl_get_metrics) and per-directory disk
        gauges. The `server` label disambiguates multiple servers sharing
        one process registry (test clusters)."""
        from seaweedfs_tpu.stats import default_registry

        self._metrics_collector = default_registry().register_collector(
            self._metrics_lines, names=self.FL_FAMILIES,
        )

    def _metrics_lines(self) -> list[str]:
        import os as _os

        from seaweedfs_tpu.stats.metrics import _fmt_labels

        server = f"{self._host}:{self.data_port}"
        lines: list[str] = []

        def sample(family: str, labels: dict, value, suffix: str = "") -> None:
            # integers render exactly: '{:g}' would clip large byte counters
            # to 6 significant digits and flatline rate() between scrapes
            v = str(int(value)) if float(value).is_integer() else f"{value:g}"
            lines.append(
                f"{family}{suffix}"
                f"{_fmt_labels(tuple(labels), tuple(labels.values()))}"
                f" {v}"
            )

        fl = self.fastlane
        if fl is not None:
            m = fl.metrics()
            lines.append("# HELP SeaweedFS_volume_fastlane_requests_total "
                         "requests served natively by the fastlane engine")
            lines.append("# TYPE SeaweedFS_volume_fastlane_requests_total counter")
            if m is not None:
                for op, st in m["ops"].items():
                    if op == "proxied":
                        continue
                    sample("SeaweedFS_volume_fastlane_requests_total",
                           {"server": server, "op": op}, st["count"])
                lines.append("# TYPE SeaweedFS_volume_fastlane_proxied_total counter")
                sample("SeaweedFS_volume_fastlane_proxied_total",
                       {"server": server}, m["ops"]["proxied"]["count"])
                lines.append("# TYPE SeaweedFS_volume_fastlane_bytes_total counter")
                for op, st in m["ops"].items():
                    sample("SeaweedFS_volume_fastlane_bytes_total",
                           {"server": server, "op": op}, st["bytes"])
                lines.append(
                    "# TYPE SeaweedFS_volume_fastlane_request_seconds histogram")
                for op, st in m["ops"].items():
                    cum = 0
                    for bound, c in zip(m["bounds_s"], st["buckets"]):
                        cum += c
                        sample("SeaweedFS_volume_fastlane_request_seconds",
                               {"server": server, "op": op,
                                "le": "{:g}".format(bound)}, cum, "_bucket")
                    # +Inf and _count come from the buckets themselves (incl.
                    # the engine's overflow slot), not the separately-read
                    # count: relaxed-atomic snapshots taken mid-observe would
                    # otherwise yield a non-monotonic histogram
                    cum += st["buckets"][-1]
                    sample("SeaweedFS_volume_fastlane_request_seconds",
                           {"server": server, "op": op, "le": "+Inf"},
                           cum, "_bucket")
                    sample("SeaweedFS_volume_fastlane_request_seconds",
                           {"server": server, "op": op}, st["seconds_sum"],
                           "_sum")
                    sample("SeaweedFS_volume_fastlane_request_seconds",
                           {"server": server, "op": op}, cum, "_count")
                lines.append(
                    "# TYPE SeaweedFS_volume_fastlane_volume_requests_total"
                    " counter")
                for vid in sorted(fl._volumes):
                    vm = fl.volume_metrics(vid)
                    if vm is None:
                        continue
                    for op, cnt in (("read", vm["reads"]),
                                    ("write", vm["writes"]),
                                    ("delete", vm["deletes"])):
                        sample(
                            "SeaweedFS_volume_fastlane_volume_requests_total",
                            {"server": server, "volume": vid, "op": op}, cnt)
                    for op, nb in (("read", vm["read_bytes"]),
                                   ("write", vm["write_bytes"])):
                        sample(
                            "SeaweedFS_volume_fastlane_volume_bytes_total",
                            {"server": server, "volume": vid, "op": op}, nb)
            else:
                # stale .so without sw_fl_get_metrics: plain counters only
                st = fl.stats()
                for op, cnt in (("read", st["native_reads"]),
                                ("write", st["native_writes"]),
                                ("delete", st["native_deletes"])):
                    sample("SeaweedFS_volume_fastlane_requests_total",
                           {"server": server, "op": op}, cnt)
                lines.append("# TYPE SeaweedFS_volume_fastlane_proxied_total counter")
                sample("SeaweedFS_volume_fastlane_proxied_total",
                       {"server": server}, st["proxied"])
        store = self.store
        if store is not None:
            lines.append("# TYPE SeaweedFS_volume_disk_used_bytes gauge")
            lines.append("# TYPE SeaweedFS_volume_disk_free_bytes gauge")
            for loc in store.locations:
                try:
                    sv = _os.statvfs(loc.directory)
                except OSError:
                    continue
                sample("SeaweedFS_volume_disk_used_bytes",
                       {"server": server, "dir": loc.directory},
                       (sv.f_blocks - sv.f_bfree) * sv.f_frsize)
                sample("SeaweedFS_volume_disk_free_bytes",
                       {"server": server, "dir": loc.directory},
                       sv.f_bavail * sv.f_frsize)
        return lines

    # --- heartbeat --------------------------------------------------------------
    def heartbeat_once(self) -> None:
        """One heartbeat POST. Sampled tracing (first beat, then every
        12th): a root span makes the master's handler span join the same
        trace so ack propagation stays visible in /debug/traces, but an
        every-beat span would flood the bounded ring with heartbeat noise
        and evict real request traces."""
        from seaweedfs_tpu.stats import trace

        n = getattr(self, "_hb_count", 0)
        self._hb_count = n + 1
        if n % 12:
            self._heartbeat_once()
            return
        with trace.span("volume.heartbeat", role="volume"):
            self._heartbeat_once()

    def _heartbeat_once(self) -> None:
        import json as _json

        try:
            _FP_HEARTBEAT.hit(key=f"{self._host}:{self.data_port}")
        except (faults.FaultInjected, ConnectionError, OSError):
            return  # partitioned from the master: the beat just vanishes
        if self.fastlane:  # report the engine's appends, not a stale view
            self.fastlane.drain()
        hb = self.store.collect_heartbeat()
        if self.fastlane:
            # per-volume cumulative op counters ride the beat: the master's
            # heat rollup (stats/heat.py) turns consecutive beats into
            # per-collection/per-node access rates. Cumulative, not deltas —
            # a dropped beat then costs resolution, not correctness.
            for v in hb.get("volumes", ()):
                vm = self.fastlane.volume_metrics(int(v.get("id", 0)))
                if vm is None:
                    continue
                v["read_ops"] = vm["reads"]
                v["write_ops"] = vm["writes"] + vm["deletes"]
                v["read_bytes"] = vm["read_bytes"]
                v["write_bytes"] = vm["write_bytes"]
        hb["data_center"] = self.data_center
        hb["rack"] = self.rack
        hb["max_volume_count"] = self.max_volume_count
        if self.scrubber is not None:
            # unresolved scrub findings ride the beat: the master's
            # scrub detector routes each kind to its heal. Capped — a
            # massively rotted volume (thousands of corrupt needles)
            # must not bloat every heartbeat; repairs resolve findings
            # as they land, so the rest ride later beats
            hb["scrub_findings"] = self.scrubber.unresolved()[:64]
            # volumes a scrub pass holds right now: the master's vacuum
            # detector defers their compaction until the pass moves on
            hb["scrub_active"] = self.scrubber.active_volumes()
        tele = self._telemetry_frame()
        if tele is not None:
            hb["telemetry"] = tele
        body = _json.dumps(hb).encode()
        tried = 0
        rotation = [u for u in self.master_urls if u != self.master_url]
        while tried <= len(rotation) + 1:
            tried += 1
            try:
                status, _, out = http_request(
                    "POST", f"{self.master_url}/heartbeat", body=body,
                    headers={"Content-Type": "application/json"}, timeout=10,
                )
                data = _json.loads(out) if out else {}
            except Exception:
                if rotation:
                    self.master_url = rotation.pop(0)
                    continue
                return
            if status == 200:
                self.volume_size_limit = int(
                    data.get("volume_size_limit", self.volume_size_limit)
                )
                return
            leader = data.get("leader")
            if data.get("error") == "raft.not.leader" and leader:
                self.master_url = leader.rstrip("/")
                continue
            if rotation:
                self.master_url = rotation.pop(0)
                continue
            return

    def _telemetry_frame(self):
        """Cluster telemetry frame riding the heartbeat body
        (stats/aggregate.py). Rate-limited to the pulse: heartbeat_once
        also fires on state changes (mounts, vacuum, rebuilds), and a
        churn burst must not pay sketch serialization per event."""
        now = time.time()
        interval = max(float(self.pulse_seconds), 2.0)
        if now - getattr(self, "_telemetry_ts", 0.0) < interval:
            return None
        self._telemetry_ts = now
        try:
            from seaweedfs_tpu.stats import aggregate as agg_mod

            return agg_mod.build_frame(
                "volume", f"{self._host}:{self.data_port}",
                interval=interval, now=now,
            )
        except Exception:
            return None

    def _active_rebuild_tmps(self) -> set[str]:
        """Tmp shard paths belonging to IN-FLIGHT pipelined rebuilds —
        the scrubber's tmp-litter GC must never sweep these, any age."""
        with self._partial_lock:
            return {
                p
                for state in self._partial_rebuilds.values()
                for p in state["writers"].tmp_paths.values()
            }

    # --- streaming rebuild sessions ------------------------------------------
    def _scale_local_shards(
        self, vid: int, coefs: dict[int, list[int]], targets: list[int],
        offset: int, size: int, me: str,
    ) -> tuple[np.ndarray | None, int]:
        """One hop's locally-computed share of the repair sum for
        [offset, offset+size): scale this node's `use` shards by their
        coefficient columns on the GF kernel. Returns (contribution or
        None when the hop owns nothing, bytes read from local shards);
        raises _PartialError with orchestrator-readable attribution."""
        if not coefs:
            return None, 0
        ev = self.store.get_ec_volume(vid)
        if ev is None:
            raise _PartialError(
                {"error": "ec volume not mounted", "failed_hop_server": me},
                409)
        sids = sorted(coefs)
        rows = []
        read = 0
        for sid in sids:
            if len(coefs[sid]) != len(targets):
                raise _PartialError(
                    {"error": f"coefs for shard {sid} != targets",
                     "failed_hop_server": me}, 400)
            data = ev._pread_shard(sid, offset, size)
            if data is None:
                raise _PartialError(
                    {"error": "shard_unavailable", "shard": sid,
                     "failed_hop_server": me}, 409)
            read += len(data)
            rows.append(np.frombuffer(data, dtype=np.uint8))
        m = np.array([coefs[s] for s in sids], dtype=np.uint8).T
        contrib = ec_decoder.partial_contribution(m, np.stack(rows), ev.codec)
        return contrib, read

    def _stream_forwarder(self, state: dict) -> None:
        """Per-session forwarder thread on a mid-chain hop: ship queued
        chunks downstream IN ORDER while the HTTP handler computes the
        next one — the overlap the (H + N) wall-clock comes from. A
        downstream failure is recorded on the session (attributed, with
        the chunk index) and the queue keeps draining so upstream
        enqueues never block behind a dead hop."""
        nxt = state["downstream"][0]
        mchunks, _ = ec_decoder.stream_metrics()
        url_base = (
            nxt["url"] + "/admin/ec/partial/stream/chunk"
            f"?session={state['session']}"
        )
        while True:
            item = state["queue"].get()
            if item is None:
                return
            seq, offset, size, payload = item
            if state["error"] is not None:
                continue  # drain-and-discard: the session already failed
            url = url_base + f"&seq={seq}&offset={offset}&size={size}"

            def fwd():
                return http_request(
                    "POST", url, payload,
                    headers={"X-Repair-Crc": str(crc_mod.crc32c(payload))},
                    timeout=READ_POLICY.deadline,
                )

            try:
                status, _, out = READ_POLICY.call(fwd)
            except (IOError, OSError) as e:
                state["error"] = {
                    "error": "hop_unreachable",
                    "failed_hop_server": nxt.get("server", ""),
                    "chunk": seq, "detail": str(e)[:200],
                }
                continue
            except Exception as e:  # never die with chunks enqueued
                state["error"] = {
                    "error": "hop_failed",
                    "failed_hop_server": nxt.get("server", ""),
                    "chunk": seq, "detail": str(e)[:200],
                }
                continue
            if status != 200:
                try:
                    downstream = json.loads(out) if out else {}
                except ValueError:
                    downstream = {}
                downstream.setdefault("error", f"hop -> {status}")
                downstream.setdefault(
                    "failed_hop_server", nxt.get("server", ""))
                downstream.setdefault("chunk", seq)
                state["error"] = downstream
                continue
            state["forwarded"] += 1
            mchunks.labels("forwarded").inc()

    def _teardown_stream(self, state: dict) -> None:
        """Stop a session's forwarder (sentinel + join). Caller already
        removed it from _partial_streams."""
        q, t = state.get("queue"), state.get("thread")
        if q is not None:
            try:
                q.put(None, timeout=state.get("stall_timeout", 1.0))
            except queue.Full:
                # forwarder wedged mid-send: mark failed so it discards
                # the backlog, then the sentinel fits
                state["error"] = state["error"] or {
                    "error": "stream_stall",
                    "failed_hop_server": "", "chunk": -1}
                try:
                    q.put(None, timeout=5.0)
                except queue.Full:
                    pass
        if t is not None:
            t.join(timeout=10.0)

    def _sweep_streams_locked(self) -> list[dict]:
        """Drop sessions past the idle age (a dead orchestrator never
        closed them). Caller holds _stream_lock; returns the swept
        states for teardown OUTSIDE the lock."""
        now = time.time()
        swept = []
        for sid in list(self._partial_streams):
            st = self._partial_streams[sid]
            if now - st["touched"] > STREAM_SESSION_MAX_AGE:
                swept.append(self._partial_streams.pop(sid))
        return swept

    def _scrub_loop(self) -> None:  # pragma: no cover - timing loop
        while not self._stop.wait(self.scrub_interval):
            try:
                if self.fastlane:  # scrub the engine's appends too
                    self.fastlane.drain()
                found = self.scrubber.scrub_pass()
                if found:
                    # the master learns about fresh damage on the next
                    # beat anyway; beating now shortens time-to-heal
                    self.heartbeat_once()
            except Exception:
                pass

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.pulse_seconds):
            # without a fastlane drain loop, the pulse drives online-EC
            # stripe pumps (incl. the timed trickle flush); Python-path
            # writes also pump inline, so this is the aging backstop
            if self.fastlane is None:
                try:
                    self._pump_online_ec()
                except Exception:
                    pass
            # age out streaming sessions a dead orchestrator never
            # closed — each holds a forwarder thread + up to a window
            # of chunk payloads, and stream/open (the only other sweep
            # driver) may never arrive on this node again
            try:
                with self._stream_lock:
                    swept = self._sweep_streams_locked()
                for st in swept:
                    self._teardown_stream(st)
            except Exception:
                pass
            if getattr(self, "_leaving", False):
                continue  # volume.server.leave: stay up, stop heartbeating
            self.heartbeat_once()

    def _attach_shard_fetcher(self, ev) -> None:
        """Give an EcVolume remote shard sourcing: master ec_lookup for
        locations, then /admin/ec/shard range reads off sibling servers
        (`store_ec.go:281` readRemoteEcShardInterval) — plus the
        repair-bandwidth-optimal partial fan-in: one coefficient-scaled
        range per HOLDER (not per shard) for interval reconstruction."""
        me = f"{self._host}:{self.data_port}"
        state = {"expires": 0.0, "shards": {}}

        def shard_map() -> dict:
            import time as _time

            now = _time.time()
            if now > state["expires"]:
                info = get_json(
                    f"{self.master_url}/dir/ec_lookup?volumeId={ev.volume_id}",
                    timeout=5,
                )
                state["shards"] = info.get("shards", {})
                state["expires"] = now + 10
            return state["shards"]

        def fetch(shard_id: int, off: int, size: int) -> bytes | None:
            for target in shard_map().get(str(shard_id), []):
                if target == me:
                    continue
                status, _, body = http_request(
                    "GET",
                    peer_url(target) + f"/admin/ec/shard?volume={ev.volume_id}"
                    f"&shard={shard_id}&offset={off}&size={size}",
                    timeout=30,
                )
                if status == 200 and len(body) == size:
                    return body
            return None

        def fetch_partials(missing: int, off: int, size: int) -> bytes | None:
            """Reconstruct shard `missing`'s [off, off+size) range moving
            one partial per remote holder over the wire instead of one
            full range per shard (the ranged half of the pipelined-rebuild
            plane; EcVolume._recover_interval falls back to the classic
            fan-in ladder when any holder can't serve its partial)."""
            smap = shard_map()
            local = set(ev.shards)
            present = sorted(
                ({int(s) for s, holders in smap.items() if holders} | local)
                - {missing}
            )
            if len(present) < geometry.DATA_SHARDS_COUNT:
                return None
            use, matrix = ec_decoder.repair_coefficients(present, [missing])
            groups: dict[str, list[int]] = {}
            local_use: list[int] = []
            for sid in use:
                if sid in local:
                    local_use.append(sid)
                    continue
                holders = [t for t in smap.get(str(sid), []) if t != me]
                if not holders:
                    return None  # a use shard with no live holder
                groups.setdefault(holders[0], []).append(sid)
            acc = None
            if local_use:
                rows = []
                for sid in local_use:
                    data = ev._pread_shard(sid, off, size)
                    if data is None:
                        return None
                    rows.append(np.frombuffer(data, dtype=np.uint8))
                cols = [use.index(s) for s in local_use]
                acc = ec_decoder.xor_partials(acc, ec_decoder.partial_contribution(
                    matrix[:, cols], np.stack(rows), ev.codec
                ))
            for target, sids in groups.items():
                coefs = {
                    str(s): [int(matrix[0, use.index(s)])] for s in sids
                }
                url = (
                    peer_url(target) + f"/admin/ec/partial"
                    f"?volume={ev.volume_id}"
                    f"&collection={urllib.parse.quote(ev.collection)}"
                    f"&offset={off}&size={size}&targets={missing}"
                    f"&coefs={urllib.parse.quote(json.dumps(coefs))}"
                )
                status, hdrs, body = http_request(
                    "POST", url, b"", timeout=READ_POLICY.deadline)
                if status != 200 or len(body) != size:
                    return None
                want = hdrs.get("X-Repair-Crc")
                if want is not None and int(want) != crc_mod.crc32c(body):
                    return None
                acc = ec_decoder.xor_partials(
                    acc, np.frombuffer(body, dtype=np.uint8).reshape(1, size)
                )
            if acc is None:
                return None
            return np.ascontiguousarray(acc[0]).tobytes()

        ev.shard_fetcher = fetch
        ev.partial_fetcher = fetch_partials

    # --- replication --------------------------------------------------------------
    def _replicate(
        self,
        method: str,
        vid: int,
        fid: str,
        body: bytes,
        headers: dict,
        extra_query: dict | None = None,
    ) -> None:
        """Fan out to the other replica locations (`store_replicate.go:26`).
        All-or-nothing: any replica failure surfaces as an error so the client
        can retry with a fresh assignment. The original request's ttl/headers
        are forwarded so replicas store identical needles. Each replica push
        retries transient failures under the shared RetryPolicy (replicated
        PUT/DELETEs are fid-addressed, so a re-send cannot duplicate) before
        the all-or-nothing verdict."""
        me = f"{self._host}:{self.data_port}"
        _FP_REPLICATE.hit(key=me, volume=vid)
        try:
            info = get_json(f"{self.master_url}/dir/lookup?volumeId={vid}", timeout=5)
        except Exception as e:
            raise VolumeError(f"replicate lookup failed: {e}")
        qs = "type=replicate"
        for k, v in (extra_query or {}).items():
            qs += f"&{k}={urllib.parse.quote(str(v))}"
        for loc in info.get("locations", []):
            target = loc["url"]
            if target == me:
                continue

            def push(target=target):
                status, _, out = http_request(
                    method,
                    peer_url(target) + f"/{vid},{fid}?{qs}",
                    body=body,
                    headers={k: v for k, v in headers.items() if v},
                    timeout=READ_POLICY.deadline,
                )
                if status >= 500:  # transient server-side: worth a retry
                    raise IOError(f"replica {target} -> {status}")
                return status, out

            try:
                status, out = READ_POLICY.call(push)
            except (IOError, OSError) as e:
                raise VolumeError(f"replica write to {target} failed: {e}")
            if status >= 400:
                raise VolumeError(f"replica write to {target} failed: {out[:200]!r}")

    # --- routes -------------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service
        self._register_query_route(svc)

        @svc.route("GET", FID_RE)
        def read(req: Request) -> Response:
            return self._do_read(req, head=False)

        @svc.route("HEAD", FID_RE)
        def head(req: Request) -> Response:
            return self._do_read(req, head=True)

        @svc.route("POST", FID_RE)
        def write(req: Request) -> Response:
            return self._do_write(req)

        @svc.route("PUT", FID_RE)
        def put(req: Request) -> Response:
            return self._do_write(req)

        @svc.route("DELETE", FID_RE)
        def delete(req: Request) -> Response:
            return self._do_delete(req)

        @svc.route("GET", r"/status")
        def status(req: Request) -> Response:
            hb = self.store.collect_heartbeat()
            out = {"Version": "seaweedfs-tpu", **hb}
            if self.fastlane:
                out["fastlane"] = self.fastlane.stats()
            online = {
                str(v.id): v.online_ec.stats()
                for loc in self.store.locations
                for v in loc.volumes.values()
                if v.online_ec is not None
            }
            if online:
                out["ec_online"] = online
            return Response(out)

        @svc.route("POST", r"/admin/allocate_volume")
        def allocate(req: Request) -> Response:
            p = req.json()
            self.store.add_volume(
                int(p["volume"]),
                p.get("collection", ""),
                p.get("replication", "000"),
                p.get("ttl", ""),
                ec_online=bool(p.get("ecOnline", False)),
                ec_online_block=(
                    int(p["ecOnlineBlock"]) if p.get("ecOnlineBlock") else None
                ),
            )
            self._fl_register(int(p["volume"]))
            return Response({"ok": True})

        @svc.route("POST", r"/admin/delete_volume")
        def delete_volume(req: Request) -> Response:
            self._fl_unregister(int(req.json()["volume"]))
            self.store.delete_volume(int(req.json()["volume"]))
            self.heartbeat_once()  # master forgets this replica promptly
            return Response({"ok": True})

        @svc.route("POST", r"/admin/vacuum")
        def vacuum(req: Request) -> Response:
            vid = int(req.json()["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            garbage = v.garbage_level()
            # the commit swaps .dat/.idx files: the engine's fds would go
            # stale, so it hands the volume back to Python for the duration
            self._fl_unregister(vid)
            try:
                v.compact()
                v.commit_compact()
            finally:
                self._fl_register(vid)
            self.heartbeat_once()
            return Response({"ok": True, "garbage_was": garbage})

        @svc.route("POST", r"/admin/volume/readonly")
        def readonly(req: Request) -> Response:
            p = req.json()
            self.store.mark_readonly(int(p["volume"]), bool(p.get("readonly", True)))
            self._fl_sync_flags(int(p["volume"]))
            return Response({"ok": True})

        @svc.route("GET", r"/ui")
        def ui(req: Request) -> Response:
            # minimal HTML status page (`weed/server/volume_server_ui/`)
            rows = []
            if self.store is not None:
                for vid in self.store.volume_ids():
                    v = self.store.get_volume(vid)
                    if v is None:
                        continue
                    rows.append(
                        f"<tr><td>{vid}</td><td>{v.collection or '(default)'}"
                        f"</td><td>{v.size()}</td><td>{v.file_count()}</td>"
                        f"<td>{v.garbage_level():.1%}</td>"
                        f"<td>{'ro' if v.readonly else 'rw'}</td></tr>"
                    )
            html = (
                "<html><head><title>seaweedfs-tpu volume</title></head><body>"
                f"<h1>Volume server {self.url}</h1>"
                f"<p>master: {self.master_url}</p>"
                "<table border=1><tr><th>id</th><th>collection</th>"
                "<th>size</th><th>files</th><th>garbage</th><th>mode</th></tr>"
                + "".join(rows) + "</table>"
                "<p><a href='/status'>status json</a> | "
                "<a href='/metrics'>metrics</a></p>"
                "</body></html>"
            ).encode()
            return Response(html, content_type="text/html")

        @svc.route("POST", r"/admin/volume/configure_replication")
        def configure_replication(req: Request) -> Response:
            from seaweedfs_tpu.storage.types import ReplicaPlacement

            p = req.json()
            vid = int(p["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            try:
                rp = ReplicaPlacement.parse(str(p["replication"]))
            except (ValueError, KeyError) as e:
                return Response({"error": str(e)}, 400)
            v.configure_replication(rp)
            self._fl_sync_flags(vid)
            return Response({"ok": True, "replication": str(rp)})

        @svc.route("POST", r"/admin/leave")
        def leave(req: Request) -> Response:
            # stop heartbeating; the master expires this node
            # (`command_volume_server_leave.go` VolumeServerLeave rpc)
            self._leaving = True
            return Response({"ok": True})

        # --- tiering (volume_grpc_tier_upload.go / _download.go) ---
        @svc.route("POST", r"/admin/backend/configure")
        def backend_configure(req: Request) -> Response:
            from seaweedfs_tpu.storage.backend import BackendError, configure_backend

            p = req.json()
            try:
                configure_backend(p["id"], p["kind"],
                                  **p.get("options", {}))
            except (BackendError, KeyError) as e:
                return Response({"error": str(e)}, 400)
            return Response({"ok": True})

        @svc.route("POST", r"/admin/volume/tier_upload")
        def tier_upload(req: Request) -> Response:
            from seaweedfs_tpu.storage.backend import BackendError

            p = req.json()
            vid = int(p["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            self._fl_unregister(vid)
            try:
                size = v.tier_to_remote(
                    p["backend"], keep_local=bool(p.get("keepLocal", False))
                )
            except (VolumeError, BackendError) as e:
                self._fl_register(vid)
                return Response({"error": str(e)}, 409)
            return Response({"ok": True, "size": size})

        @svc.route("POST", r"/admin/volume/tier_download")
        def tier_download(req: Request) -> Response:
            from seaweedfs_tpu.storage.backend import BackendError

            p = req.json()
            vid = int(p["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            try:
                v.tier_to_local()
            except (VolumeError, BackendError) as e:
                return Response({"error": str(e)}, 409)
            self._fl_register(vid)
            return Response({"ok": True})

        @svc.route("GET", r"/admin/volume/tier_info")
        def tier_info(req: Request) -> Response:
            vid = int(req.query["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            return Response({"volume": vid, "remote": v.tier_info()})

        # --- EC verbs (volume_grpc_erasure_coding.go) ---
        @svc.route("POST", r"/admin/ec/generate")
        def ec_generate(req: Request) -> Response:
            p = req.json()
            vid = int(p["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            v.readonly = True
            # a native append already past the engine's readonly check could
            # still be mid-pwrite; unregister waits it out so the encoder
            # reads a quiescent .dat/.idx
            self._fl_unregister(vid)
            sealed_online = False
            try:
                base = v.base_name
                if v.online_ec is not None and v.online_ec.active:
                    # ingest already paid the GF math: the seal flushes
                    # the tail row and materializes data shards with a
                    # sequential copy — no re-encode
                    try:
                        v.online_ec.seal()
                        sealed_online = True
                    except RuntimeError:
                        pass  # degraded mid-seal: classic encode below
                if not sealed_online:
                    ec_encoder.write_ec_files(base)
                ec_encoder.write_sorted_file_from_idx(base)
            finally:
                self._fl_register(vid)  # readonly: native reads, proxied writes
            if not sealed_online:
                # classic path: the shards now belong to the EC volume —
                # detach any (degraded) stripe writer so a later destroy
                # can't mistake .ec10-.ec13 for its partial parity, and
                # write a plain .vif (seal() writes the online one,
                # recording the uniform stripe geometry)
                if v.online_ec is not None:
                    v.online_ec.close()
                    v.online_ec = None
                    import os as _os

                    try:
                        _os.unlink(base + ".ecp")
                    except OSError:
                        pass
                ec_encoder.save_volume_info(base + ".vif", version=v.version())
            return Response({"ok": True, "shards": list(range(14)),
                             "online": sealed_online})

        @svc.route("POST", r"/admin/ec/mount")
        def ec_mount(req: Request) -> Response:
            p = req.json()
            vid = int(p["volume"])
            # atomic: the old instance (if any) serves until the new one
            # is swapped in — concurrent reads never see a mount gap
            ev = self.store.remount_ec_volume(vid, p.get("collection", ""))
            if ev is None:
                return Response(
                    {"error": f"no local .ecx for ec volume {vid}"}, 404)
            self._attach_shard_fetcher(ev)
            self.heartbeat_once()
            return Response({"ok": True, "shards": ev.shard_ids()})

        @svc.route("POST", r"/admin/ec/unmount")
        def ec_unmount(req: Request) -> Response:
            self.store.unmount_ec_volume(int(req.json()["volume"]))
            self.heartbeat_once()
            return Response({"ok": True})

        @svc.route("POST", r"/admin/ec/rebuild")
        def ec_rebuild(req: Request) -> Response:
            p = req.json()
            vid = int(p["volume"])
            collection = p.get("collection", "")
            for loc in self.store.locations:
                from seaweedfs_tpu.storage.erasure_coding.ec_volume import (
                    ec_shard_file_name,
                )

                base = ec_shard_file_name(collection, loc.directory, vid)
                import os

                if any(
                    os.path.exists(base + geometry.to_ext(i)) for i in range(14)
                ):
                    rebuilt = ec_encoder.rebuild_ec_files(base)
                    return Response({"ok": True, "rebuilt": rebuilt})
            return Response({"error": f"no shards for volume {vid}"}, 404)

        @svc.route("POST", r"/admin/ec/online/rebuild")
        def ec_online_rebuild(req: Request) -> Response:
            """Re-arm a LIVE online-EC volume's striper and re-encode its
            parity from the durable .dat — the ec_rebuild executor's heal
            for a lost/torn parity shard (the ROADMAP online-rebuild
            follow-up). Safe under traffic: parity is a pure function of
            the append-only .dat, and the engine's stripe accumulator is
            re-synced to the fresh watermark."""
            vid = int(req.json()["volume"])
            v = self.store.get_volume(vid)
            if v is None or v.online_ec is None:
                return Response(
                    {"error": f"volume {vid} has no online-EC striper"}, 404
                )
            if self.fastlane:  # re-encode must cover the engine's appends
                self.fastlane.drain()
            rows = v.online_ec.rearm()
            if self.fastlane and vid in self.fastlane._volumes:
                self.fastlane.ec_online_advance(vid, v.online_ec.watermark)
            self.heartbeat_once()  # the parity-damage gauge clears now
            return Response({
                "ok": True, "rows": rows,
                "watermark": v.online_ec.watermark,
                "active": v.online_ec.active,
            })

        @svc.route("POST", r"/admin/ec/delete_volume")
        def ec_delete(req: Request) -> Response:
            """Delete the original volume files after EC spread
            (`command_ec_encode.go` deletes source replicas)."""
            vid = int(req.json()["volume"])
            self._fl_unregister(vid)  # EC serving runs in Python from here on
            self.store.delete_volume(vid)
            self.heartbeat_once()
            return Response({"ok": True})

        @svc.route("POST", r"/admin/ec/to_volume")
        def ec_to_volume(req: Request) -> Response:
            """Reconstruct the original .dat/.idx from locally-collected EC
            shards (`volume_grpc_erasure_coding.go:407 VolumeEcShardsToVolume`).
            Missing data shards are rebuilt from parity first."""
            import os

            p = req.json()
            vid = int(p["volume"])
            collection = p.get("collection", "")
            from seaweedfs_tpu.storage.erasure_coding import decoder as ec_decoder
            from seaweedfs_tpu.storage.erasure_coding.ec_volume import (
                ec_shard_file_name,
            )

            base = None
            for loc in self.store.locations:
                cand = ec_shard_file_name(collection, loc.directory, vid)
                if os.path.exists(cand + ".ecx"):
                    base = cand
                    break
            if base is None:
                return Response({"error": f"no .ecx for volume {vid}"}, 404)
            have = [
                s for s in range(geometry.TOTAL_SHARDS_COUNT)
                if os.path.exists(base + geometry.to_ext(s))
            ]
            if any(s not in have for s in range(geometry.DATA_SHARDS_COUNT)):
                ec_encoder.rebuild_ec_files(base)
            from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE

            # an EC volume with zero live needles still has its superblock
            # striped into .ec00 — never write a .dat shorter than that
            dat_size = max(
                ec_decoder.find_dat_file_size(base, base), SUPER_BLOCK_SIZE
            )
            shard_names = [
                base + geometry.to_ext(s)
                for s in range(geometry.DATA_SHARDS_COUNT)
            ]
            # online-sealed volumes striped with a recorded uniform block
            # geometry — the .vif is authoritative over the defaults
            info = ec_encoder.load_volume_info(base + ".vif")
            ec_decoder.write_dat_file(
                base, dat_size, shard_names,
                large_block_size=int(
                    info.get("large_block_size", geometry.LARGE_BLOCK_SIZE)),
                small_block_size=int(
                    info.get("small_block_size", geometry.SMALL_BLOCK_SIZE)),
            )
            ec_decoder.write_idx_file_from_ec_index(base)
            v = self.store.mount_volume(vid, collection)
            self._fl_register(vid)
            self.heartbeat_once()
            return Response({"ok": True, "size": v.size()})

        @svc.route("GET", r"/admin/ec/shard")
        def ec_shard_read(req: Request) -> Response:
            """Raw shard byte range — remote EC reads (`store_ec.go:281`).
            An OPEN online-EC volume serves the same ranges before any
            seal: parity from the incrementally-written .ec1x files, data
            shards as views into the live .dat (online.py
            read_shard_range)."""
            vid = int(req.query["volume"])
            shard = int(req.query["shard"])
            offset = int(req.query.get("offset", 0))
            size = int(req.query.get("size", -1))
            ev = self.store.get_ec_volume(vid)
            if ev is None:
                v = self.store.get_volume(vid)
                if v is not None and v.online_ec is not None and size >= 0:
                    data = v.online_ec.read_shard_range(shard, offset, size)
                    if data is None:
                        return Response(
                            {"error": f"shard {shard} range unavailable"}, 404)
                    return Response(
                        data, content_type="application/octet-stream")
                return Response({"error": "ec volume not mounted"}, 404)
            import os

            fd = ev.shards.get(shard)
            if fd is None:
                return Response({"error": f"shard {shard} not local"}, 404)
            if size < 0:
                size = ev.shard_size - offset
            data = os.pread(fd, size, offset)
            return Response(data, content_type="application/octet-stream")

        # --- pipelined partial-sum rebuild plane --------------------------
        # (repair-bandwidth-optimal rebuilds: arXiv:1412.3022 regenerating
        # codes for the per-repair traffic cut, arXiv:1207.6744 RapidRAID
        # for the hop-chained partial coding that kills the rebuilder's
        # 10x fan-in hotspot)

        @svc.route("POST", r"/admin/ec/partial/start")
        def ec_partial_start(req: Request) -> Response:
            """Open a pipelined rebuild on this node (the chain's terminal
            writer): pre-sized tmp shard files for `targets`, renamed into
            place only at commit — a dead orchestrator leaves ignorable
            .tmp litter, never a half-written shard under a valid name.
            `resume: true` keeps an existing same-target state and returns
            its committed frontier, so a restarted chain re-sends only the
            uncommitted suffix instead of every chunk from byte 0."""
            p = req.json()
            vid = int(p["volume"])
            targets = [int(s) for s in p.get("targets", [])]
            ev = self.store.get_ec_volume(vid)
            if ev is None:
                return Response({"error": "ec volume not mounted"}, 404)
            if not targets or any(
                t < 0 or t >= geometry.TOTAL_SHARDS_COUNT for t in targets
            ):
                return Response({"error": f"bad targets {targets}"}, 400)
            with self._partial_lock:
                old = self._partial_rebuilds.get(vid)
                if (
                    p.get("resume") and old is not None
                    and old["targets"] == targets
                ):
                    return Response({
                        "ok": True, "shard_size": old["shard_size"],
                        "targets": targets, "resumed": True,
                        "committed": old.get("committed", 0),
                    })
                old = self._partial_rebuilds.pop(vid, None)
                if old is not None:  # stale orchestrator: replace its state
                    old["writers"].abort()
                writers = ec_encoder._ShardWriters(
                    ev.data_base, ev.shard_size, shard_ids=targets
                )
                self._partial_rebuilds[vid] = {
                    "writers": writers, "targets": targets,
                    "shard_size": ev.shard_size,
                    "collection": p.get("collection", ""),
                    # contiguous per-shard byte frontier the chain has
                    # landed (chunks arrive in order): restarts resume here
                    "committed": 0,
                }
            return Response({
                "ok": True, "shard_size": ev.shard_size, "targets": targets,
                "committed": 0,
            })

        @svc.route("POST", r"/admin/ec/partial/commit")
        def ec_partial_commit(req: Request) -> Response:
            vid = int(req.json()["volume"])
            with self._partial_lock:
                state = self._partial_rebuilds.get(vid)
                if state is not None and \
                        state.get("committed", 0) < state["shard_size"]:
                    # committing a half-landed rebuild would rename a
                    # partially-written file under a valid shard name
                    return Response(
                        {"error": "rebuild incomplete",
                         "committed": state.get("committed", 0),
                         "shard_size": state["shard_size"]}, 409)
                state = self._partial_rebuilds.pop(vid, None)
            if state is None:
                return Response({"error": "no rebuild state"}, 404)
            state["writers"].close()
            # atomic swap: reads keep serving off the old instance until
            # the one that sees the rebuilt shards replaces it
            ev = self.store.remount_ec_volume(vid, state["collection"])
            if ev is None:
                return Response({"error": "ec volume vanished"}, 409)
            self._attach_shard_fetcher(ev)
            self.heartbeat_once()
            return Response({
                "ok": True, "rebuilt": state["targets"],
                "shards": ev.shard_ids(),
            })

        @svc.route("POST", r"/admin/ec/partial/abort")
        def ec_partial_abort(req: Request) -> Response:
            vid = int(req.json()["volume"])
            with self._partial_lock:
                state = self._partial_rebuilds.pop(vid, None)
            if state is not None:
                state["writers"].abort()
            return Response({"ok": True, "aborted": state is not None})

        @svc.route("POST", r"/admin/ec/partial")
        def ec_partial(req: Request) -> Response:
            """One partial-sum hop. Body: the accumulated partial so far
            (empty for the chain head), CRC-guarded. Query: volume /
            collection / offset / size / targets, plus either `chain`
            (JSON hop list, chain[0] == this node; forward the XOR to
            chain[1], the last hop writes into the /admin/ec/partial/start
            state) or bare `coefs` (range-limited partial served straight
            back — degraded reads fan in ONE scaled range per holder
            instead of one per shard). Every received/served payload
            counts into ec_repair_bytes_on_wire{mode="pipelined"}."""
            me = f"{self._host}:{self.data_port}"
            q = req.query
            vid = int(q["volume"])
            _FP_PARTIAL.hit(key=me, volume=vid)
            collection = q.get("collection", "")
            offset = int(q["offset"])
            size = int(q["size"])
            targets = [int(s) for s in q.get("targets", "").split(",") if s]
            if size <= 0 or offset < 0 or not targets:
                return Response({"error": "bad offset/size/targets"}, 400)
            chain = json.loads(q["chain"]) if "chain" in q else []
            # hop identity onto the request's server span: a pipelined
            # rebuild renders in cluster.trace as one cross-node chain of
            # `POST /admin/ec/partial` spans — the attrs say which hop
            from seaweedfs_tpu.stats import trace as _trace

            _trace.annotate(volume=vid, targets=targets, hop=me,
                            hops_left=len(chain))
            if chain:
                hop, rest = chain[0], chain[1:]
                coefs = {int(k): v for k, v in hop.get("coefs", {}).items()}
                write = bool(hop.get("write"))
            else:
                hop, rest, write = None, [], False
                coefs = {int(k): v for k, v in
                         json.loads(q.get("coefs", "{}")).items()}
            mbytes, _, _, _ = ec_decoder.repair_metrics()
            body = req.body
            if body:
                if len(body) != len(targets) * size:
                    return Response(
                        {"error": "partial size mismatch",
                         "failed_hop_server": me}, 409)
                want = req.headers.get("X-Repair-Crc")
                if want is not None and int(want) != crc_mod.crc32c(body):
                    return Response(
                        {"error": "crc_mismatch", "failed_hop_server": me},
                        409)
                mbytes.labels("pipelined").inc(len(body))
                partial = np.frombuffer(body, dtype=np.uint8) \
                    .reshape(len(targets), size).copy()
            else:
                partial = None
            try:
                contrib, local_read = self._scale_local_shards(
                    vid, coefs, targets, offset, size, me)
            except _PartialError as e:
                return Response(e.payload, e.status)
            if contrib is not None:
                partial = ec_decoder.xor_partials(partial, contrib) \
                    if partial is not None else contrib
            if partial is None:
                partial = np.zeros((len(targets), size), dtype=np.uint8)
            if rest:  # forward the accumulated sum to the next hop
                nxt = rest[0]
                payload = np.ascontiguousarray(partial).tobytes()
                url = (
                    nxt["url"] + f"/admin/ec/partial?volume={vid}"
                    f"&collection={urllib.parse.quote(collection)}"
                    f"&offset={offset}&size={size}"
                    f"&targets={','.join(str(t) for t in targets)}"
                    f"&chain={urllib.parse.quote(json.dumps(rest))}"
                )

                def fwd():
                    return http_request(
                        "POST", url, payload,
                        headers={"X-Repair-Crc":
                                 str(crc_mod.crc32c(payload))},
                        timeout=READ_POLICY.deadline,
                    )

                try:  # transport failures retry under the shared policy
                    status, _, out = READ_POLICY.call(fwd)
                except (IOError, OSError) as e:
                    return Response(
                        {"error": "hop_unreachable",
                         "failed_hop_server": nxt.get("server", ""),
                         "failed_hop": nxt["url"],
                         "detail": str(e)[:200]}, 502)
                try:
                    downstream = json.loads(out) if out else {}
                except ValueError:
                    downstream = {}
                if status != 200:
                    downstream.setdefault("error", f"hop -> {status}")
                    downstream.setdefault(
                        "failed_hop_server", nxt.get("server", ""))
                    return Response(downstream, 502)
                downstream["received"] = (
                    [len(body)] + downstream.get("received", []))
                downstream["read"] = (
                    [local_read] + downstream.get("read", []))
                return Response(downstream)
            if write:  # chain terminal: land the sum in the rebuild state
                with self._partial_lock:
                    state = self._partial_rebuilds.get(vid)
                    if state is None or state["targets"] != targets:
                        return Response(
                            {"error": "start_failed",
                             "detail": "no matching rebuild state",
                             "failed_hop_server": me}, 409)
                    for i, sid in enumerate(targets):
                        state["writers"].pwrite(sid, partial[i], offset)
                    if offset == state.get("committed", 0):
                        state["committed"] = offset + size
                return Response({"ok": True, "received": [len(body)],
                                 "read": [local_read]})
            # bare ranged partial: serve the scaled range back (option (b))
            payload = np.ascontiguousarray(partial).tobytes()
            mbytes.labels("pipelined").inc(len(payload))
            return Response(
                payload, content_type="application/octet-stream",
                headers={"X-Repair-Crc": str(crc_mod.crc32c(payload))},
            )

        # --- streaming session mode (hop-parallel chunk pipelining) -------
        # One /admin/ec/partial chain pass per CHUNK costs hops x chunks
        # sequential hop-steps (each nested POST holds the whole chain).
        # A stream session arms every hop once (open cascades down the
        # chain), then each chunk POST is ACKed after local compute +
        # enqueue — the hop's forwarder thread ships chunk k downstream
        # while the handler computes chunk k+1. Bounded queue = in-flight
        # window = backpressure: a stalled downstream fills the queue and
        # the enqueue timeout surfaces as a typed stream_stall.

        @svc.route("POST", r"/admin/ec/partial/stream/open")
        def ec_partial_stream_open(req: Request) -> Response:
            me = f"{self._host}:{self.data_port}"
            p = req.json()
            sid = str(p.get("session", ""))
            vid = int(p["volume"])
            chain = p.get("chain") or []
            targets = [int(t) for t in p.get("targets", [])]
            if not sid or not chain or not targets:
                return Response(
                    {"error": "bad session/chain/targets",
                     "failed_hop_server": me}, 400)
            _FP_PARTIAL.hit(key=me, volume=vid)
            from seaweedfs_tpu.stats import trace as _trace

            _trace.annotate(volume=vid, targets=targets, hop=me,
                            hops_left=len(chain), stream=True)
            hop, rest = chain[0], chain[1:]
            state = {
                "session": sid, "volume": vid,
                "collection": p.get("collection", ""),
                "targets": targets,
                "coefs": {int(k): v
                          for k, v in hop.get("coefs", {}).items()},
                "write": bool(hop.get("write")),
                "downstream": rest,
                "window": max(1, int(p.get("window", STREAM_WINDOW))),
                "stall_timeout": float(
                    p.get("stall_timeout", STREAM_STALL_TIMEOUT)),
                "received": 0, "read": 0, "forwarded": 0,
                "error": None, "touched": time.time(),
                "queue": None, "thread": None,
            }
            if rest:
                # arm the whole chain before any chunk flows: the open
                # cascades downstream synchronously (chain latency once,
                # not per chunk)
                body = dict(p)
                body["chain"] = rest
                try:
                    status, _, out = http_request(
                        "POST",
                        rest[0]["url"] + "/admin/ec/partial/stream/open",
                        json.dumps(body).encode(),
                        headers={"Content-Type": "application/json"},
                        timeout=60,
                    )
                except (IOError, OSError) as e:
                    return Response(
                        {"error": "hop_unreachable",
                         "failed_hop_server": rest[0].get("server", ""),
                         "detail": str(e)[:200]}, 502)
                try:
                    downstream = json.loads(out) if out else {}
                except ValueError:
                    downstream = {}
                if status != 200:
                    downstream.setdefault("error", f"open -> {status}")
                    downstream.setdefault(
                        "failed_hop_server", rest[0].get("server", ""))
                    return Response(downstream, 502)
                state["queue"] = queue.Queue(maxsize=state["window"])
                t = threading.Thread(
                    target=self._stream_forwarder, args=(state,),
                    daemon=True, name="sw-ec-stream",
                )
                state["thread"] = t
                t.start()
            elif state["write"]:
                with self._partial_lock:
                    rb = self._partial_rebuilds.get(vid)
                    if rb is None or rb["targets"] != targets:
                        return Response(
                            {"error": "start_failed",
                             "detail": "no matching rebuild state",
                             "failed_hop_server": me}, 409)
            with self._stream_lock:
                old = self._partial_streams.pop(sid, None)
                self._partial_streams[sid] = state
                swept = self._sweep_streams_locked()
            if old is not None:
                self._teardown_stream(old)
            for st in swept:
                self._teardown_stream(st)
            return Response({"ok": True, "session": sid})

        @svc.route("POST", r"/admin/ec/partial/stream/chunk")
        def ec_partial_stream_chunk(req: Request) -> Response:
            me = f"{self._host}:{self.data_port}"
            q = req.query
            sid = q.get("session", "")
            with self._stream_lock:
                state = self._partial_streams.get(sid)
            if state is None:
                return Response(
                    {"error": "unknown stream session",
                     "failed_hop_server": me}, 404)
            vid = state["volume"]
            seq = int(q["seq"])
            offset = int(q["offset"])
            size = int(q["size"])
            if size <= 0 or offset < 0:
                return Response({"error": "bad offset/size",
                                 "failed_hop_server": me,
                                 "chunk": seq}, 400)
            _FP_PARTIAL.hit(key=me, volume=vid)
            state["touched"] = time.time()
            if state["error"] is not None:
                return Response(dict(state["error"]), 502)
            targets = state["targets"]
            mchunks, _ = ec_decoder.stream_metrics()
            mbytes, _, _, _ = ec_decoder.repair_metrics()
            body = req.body
            partial = None
            if body:
                if len(body) != len(targets) * size:
                    return Response(
                        {"error": "partial size mismatch",
                         "failed_hop_server": me, "chunk": seq}, 409)
                want = req.headers.get("X-Repair-Crc")
                if want is not None and int(want) != crc_mod.crc32c(body):
                    mchunks.labels("crc_failed").inc()
                    return Response(
                        {"error": "chunk_crc", "failed_hop_server": me,
                         "chunk": seq}, 409)
                state["received"] += len(body)
                mbytes.labels("pipelined").inc(len(body))
                partial = np.frombuffer(body, dtype=np.uint8) \
                    .reshape(len(targets), size).copy()
            try:
                contrib, local_read = self._scale_local_shards(
                    vid, state["coefs"], targets, offset, size, me)
            except _PartialError as e:
                return Response({**e.payload, "chunk": seq}, e.status)
            state["read"] += local_read
            if contrib is not None:
                partial = ec_decoder.xor_partials(partial, contrib) \
                    if partial is not None else contrib
            if partial is None:
                partial = np.zeros((len(targets), size), dtype=np.uint8)
            if state["queue"] is not None:
                payload = np.ascontiguousarray(partial).tobytes()
                try:
                    state["queue"].put((seq, offset, size, payload),
                                       timeout=state["stall_timeout"])
                except queue.Full:
                    mchunks.labels("stalled").inc()
                    state["error"] = {
                        "error": "stream_stall",
                        "failed_hop_server":
                            state["downstream"][0].get("server", ""),
                        "chunk": seq,
                    }
                    return Response(dict(state["error"]), 503)
                return Response({"ok": True, "chunk": seq})
            # chain terminal: land the chunk at the committed frontier
            with self._partial_lock:
                rb = self._partial_rebuilds.get(vid)
                if rb is None or rb["targets"] != targets:
                    return Response(
                        {"error": "start_failed",
                         "detail": "no matching rebuild state",
                         "failed_hop_server": me, "chunk": seq}, 409)
                committed = rb.get("committed", 0)
                if offset + size <= committed:
                    # duplicate delivery: the upstream forwarder's retry
                    # policy re-sends a chunk whose ACK was lost on the
                    # wire. The write already landed — ACK it again
                    # instead of failing the session (a 409 here gets
                    # the healthy REBUILDER excluded by the ladder and
                    # its whole committed frontier aborted).
                    return Response({"ok": True, "chunk": seq,
                                     "committed": committed,
                                     "duplicate": True})
                if offset != committed:
                    return Response(
                        {"error": f"chunk out of order (offset {offset},"
                                  f" committed {committed})",
                         "failed_hop_server": me, "chunk": seq}, 409)
                for i, t in enumerate(targets):
                    rb["writers"].pwrite(t, partial[i], offset)
                rb["committed"] = offset + size
            mchunks.labels("written").inc()
            return Response({"ok": True, "chunk": seq,
                             "committed": offset + size})

        @svc.route("POST", r"/admin/ec/partial/stream/close")
        def ec_partial_stream_close(req: Request) -> Response:
            """Flush-and-report: drain this hop's forward queue, cascade
            the close downstream, and return per-hop received/read byte
            lists (chain order) plus the terminal's committed frontier.
            Always 200 — the payload carries `ok` and, on failure, the
            attributed error so the orchestrator's ladder can resume
            from `committed` instead of byte 0."""
            me = f"{self._host}:{self.data_port}"
            sid = req.query.get("session", "")
            with self._stream_lock:
                state = self._partial_streams.pop(sid, None)
            if state is None:
                return Response(
                    {"error": "unknown stream session",
                     "failed_hop_server": me}, 404)
            if state["queue"] is not None:
                self._teardown_stream(state)  # drains in order, then joins
            out: dict = {
                "ok": True,
                "received": [state["received"]],
                "read": [state["read"]],
                "committed": None,
            }
            if state["downstream"]:
                nxt = state["downstream"][0]
                try:
                    status, _, body = http_request(
                        "POST",
                        nxt["url"]
                        + f"/admin/ec/partial/stream/close?session={sid}",
                        b"", timeout=120,
                    )
                    down = json.loads(body) if body else {}
                except (IOError, OSError, ValueError) as e:
                    down = {"error": "hop_unreachable",
                            "failed_hop_server": nxt.get("server", ""),
                            "detail": str(e)[:200]}
                out["received"] += down.get("received", [])
                out["read"] += down.get("read", [])
                out["committed"] = down.get("committed")
                if (down.get("error") or not down.get("ok", True)) \
                        and state["error"] is None:
                    state["error"] = {
                        k: down[k]
                        for k in ("error", "failed_hop_server", "chunk",
                                  "detail")
                        if k in down
                    }
            else:
                with self._partial_lock:
                    rb = self._partial_rebuilds.get(state["volume"])
                    out["committed"] = (
                        None if rb is None else rb.get("committed", 0))
            if state["error"] is not None:
                out.update(state["error"])
                out["ok"] = False
            return Response(out)

        # --- volume copy / move plane (volume_grpc_copy.go) ---
        @svc.route("GET", r"/admin/volume/files")
        def volume_files(req: Request) -> Response:
            """List a volume's files + sizes so a receiver can pull them."""
            import os

            vid = int(req.query["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            out = {}
            for ext in (".dat", ".idx", ".vif"):
                p = v.base_name + ext
                if os.path.exists(p):
                    out[ext] = os.path.getsize(p)
            return Response(
                {"collection": v.collection, "files": out,
                 "version": v.version(),
                 "last_append_at_ns": v.last_append_at_ns}
            )

        @svc.route("GET", r"/admin/volume/raw")
        def volume_raw(req: Request) -> Response:
            """Raw byte range of one volume/EC file — the copy stream
            (`VolumeCopy`/`CopyFile` stream in volume_server.proto)."""
            import os

            if self.fastlane:  # copy streams must see the engine's appends
                self.fastlane.drain()
            vid = int(req.query["volume"])
            ext = req.query["ext"]
            collection = req.query.get("collection", "")
            offset = int(req.query.get("offset", 0))
            size = int(req.query.get("size", -1))
            if not _SAFE_EXT_RE.fullmatch(ext):
                return Response({"error": f"bad ext {ext}"}, 400)
            v = self.store.get_volume(vid)
            if v is not None:
                path = v.base_name + ext
            else:
                path = None
                for loc in self.store.locations:
                    cand = volume_file_name(loc.directory, collection, vid) + ext
                    if os.path.exists(cand):
                        path = cand
                        break
            if path is None or not os.path.exists(path):
                return Response({"error": f"no {ext} for volume {vid}"}, 404)
            total = os.path.getsize(path)
            if size < 0:
                size = total - offset
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(size)
            return Response(
                data, content_type="application/octet-stream",
                headers={"X-Total-Size": str(total)},
            )

        @svc.route("POST", r"/admin/volume/copy")
        def volume_copy(req: Request) -> Response:
            """Pull a volume's .dat/.idx from another volume server and mount
            it locally (`volume_grpc_copy.go VolumeCopy` — receiver-driven).
            A live online-EC volume arrives as .dat/.idx/.vif only — the
            source's streamed parity and journal stay (and die) with it —
            so the pulled .vif's unsealed ec_online policy RE-ARMS the
            striper here: re-encode parity from byte 0 of the durable
            .dat, the same path as /admin/ec/online/rebuild. That is what
            makes live online volumes movable by balance/evacuate instead
            of pinned forever."""
            p = req.json()
            vid = int(p["volume"])
            source = p["source"].rstrip("/")
            if self.store.has_volume(vid):
                return Response({"error": f"volume {vid} already here"}, 409)
            meta = get_json(f"{source}/admin/volume/files?volume={vid}", timeout=30)
            collection = meta.get("collection", "")
            loc = self.store._pick_location()
            base = volume_file_name(loc.directory, collection, vid)
            for ext in meta["files"]:
                self._pull_file(source, vid, collection, ext, base + ext)
            v = self.store.mount_volume(vid, collection)
            rearmed_rows = None
            try:
                from seaweedfs_tpu.storage.store import _attach_online_ec

                _attach_online_ec(v)  # no-op unless the .vif demands it
                if v.online_ec is not None:
                    rearmed_rows = v.online_ec.rearm()
                    if self.fastlane and vid in self.fastlane._volumes:
                        self.fastlane.ec_online_advance(
                            vid, v.online_ec.watermark)
            except Exception:
                # parity re-arm failed: the volume still serves off the
                # .dat and heartbeats without ec_online, so the layout
                # re-demands its real replica count and repair owns it
                if v.online_ec is not None:
                    v.online_ec.close()
                    v.online_ec = None
            # hand the received volume to the engine like ec_to_volume
            # does — without this a balanced/evacuated volume silently
            # lost its native data plane on the new holder until restart
            self._fl_register(vid)
            self.heartbeat_once()
            out = {"ok": True, "volume": vid, "size": v.size(),
                   "last_append_at_ns": v.last_append_at_ns}
            if rearmed_rows is not None:
                out["ec_online_rearmed_rows"] = rearmed_rows
            return Response(out)

        @svc.route("POST", r"/admin/volume/mount")
        def volume_mount(req: Request) -> Response:
            p = req.json()
            v = self.store.mount_volume(int(p["volume"]), p.get("collection", ""))
            self._fl_register(int(p["volume"]))  # native plane resumes
            self.heartbeat_once()
            return Response({"ok": True, "size": v.size()})

        @svc.route("POST", r"/admin/volume/unmount")
        def volume_unmount(req: Request) -> Response:
            self.store.unmount_volume(int(req.json()["volume"]))
            self.heartbeat_once()
            return Response({"ok": True})

        @svc.route("POST", r"/admin/ec/copy")
        def ec_copy(req: Request) -> Response:
            """Pull EC shard files (+ .ecx/.vif) from a source server
            (`VolumeEcShardsCopy`)."""
            import os

            p = req.json()
            vid = int(p["volume"])
            collection = p.get("collection", "")
            shards = [int(s) for s in p.get("shards", [])]
            source = p["source"].rstrip("/")
            from seaweedfs_tpu.storage.erasure_coding.ec_volume import (
                ec_shard_file_name,
            )

            loc = self.store._pick_location()
            base = ec_shard_file_name(collection, loc.directory, vid)
            exts = [geometry.to_ext(s) for s in shards]
            if p.get("copy_ecx", True) and not os.path.exists(base + ".ecx"):
                exts += [".ecx"]
            if p.get("copy_ecj", False):
                exts.append(".ecj")
            if p.get("copy_vif", True) and not os.path.exists(base + ".vif"):
                exts.append(".vif")
            copied = []
            pulled = 0
            for ext in exts:
                try:
                    pulled += self._pull_file(
                        source, vid, collection, ext, base + ext)
                    copied.append(ext)
                except IOError:
                    if ext == ".ecj":  # deletion journal may not exist
                        continue
                    if ext == ".vif":  # synthesize a default when absent
                        ec_encoder.save_volume_info(base + ".vif")
                        continue
                    raise
            if p.get("repair") and pulled:
                # whole-shard pulls feeding a classic rebuild: the traffic
                # the pipelined mode exists to cut — counted at the
                # receiving rebuilder, same convention as the partial hops
                ec_decoder.repair_metrics()[0].labels("classic").inc(pulled)
            return Response({"ok": True, "copied": copied, "bytes": pulled})

        @svc.route("POST", r"/admin/ec/delete_shards")
        def ec_delete_shards(req: Request) -> Response:
            """Remove local shard files after they moved elsewhere
            (`VolumeEcShardsDelete`)."""
            import os

            p = req.json()
            vid = int(p["volume"])
            collection = p.get("collection", "")
            shards = [int(s) for s in p.get("shards", [])]
            from seaweedfs_tpu.storage.erasure_coding.ec_volume import (
                ec_shard_file_name,
            )

            removed = []
            was_mounted = self.store.get_ec_volume(vid) is not None
            for loc in self.store.locations:
                base = ec_shard_file_name(collection, loc.directory, vid)
                for s in shards:
                    path = base + geometry.to_ext(s)
                    if os.path.exists(path):
                        os.remove(path)
                        removed.append(s)
                if p.get("delete_index", False):
                    for ext in (".ecx", ".ecj", ".vif"):
                        if os.path.exists(base + ext):
                            os.remove(base + ext)
            if was_mounted:
                # atomic swap: the old instance (whose open fds still
                # serve the just-unlinked shards) covers concurrent reads
                # until the refreshed one is in place, and the refresh
                # re-attaches the remote shard/partial fetchers (the old
                # unmount+mount dance silently dropped them — every later
                # degraded read on this node 500'd local-only)
                ev = self.store.remount_ec_volume(vid, collection)
                if ev is not None:
                    self._attach_shard_fetcher(ev)
            self.heartbeat_once()
            return Response({"ok": True, "removed": removed})

        @svc.route("GET", r"/admin/volume/needle_blob")
        def needle_blob(req: Request) -> Response:
            """Raw on-disk needle record (`ReadNeedleBlob`)."""
            vid = int(req.query["volume"])
            offset = int(req.query["offset"])
            size = int(req.query["size"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            return Response(
                v.read_needle_blob(offset, size),
                content_type="application/octet-stream",
            )

        @svc.route("POST", r"/admin/volume/write_needle_blob")
        def write_needle_blob(req: Request) -> Response:
            """Append a needle copied raw from a replica (`WriteNeedleBlob` —
            volume.check.disk repair path). Body = the on-disk record."""
            vid = int(req.query["volume"])
            size = int(req.query["size"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            n = Needle.from_bytes(req.body, size, v.version())
            v.write_needle(n)
            return Response({"ok": True, "id": n.id})

        @svc.route("GET", r"/admin/volume/needles")
        def volume_needles(req: Request) -> Response:
            """Live needle ids+sizes from the index — replica diffing for
            volume.check.disk (`volume_grpc_copy.go ReadNeedleMeta`-ish)."""
            vid = int(req.query["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            needles = [
                {"id": key, "offset": off, "size": sz}
                for key, off, sz in v.nm.ascending_visit()
            ]
            return Response({"volume": vid, "needles": needles})

        @svc.route("GET", r"/admin/fsck")
        def fsck(req: Request) -> Response:
            """Walk the index and CRC-verify every live needle
            (`volume_checking.go` + shell volume.fsck)."""
            vid = int(req.query["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            checked, errors = 0, []
            for key, off, sz in v.nm.ascending_visit():
                try:
                    v.read_needle(key)
                    checked += 1
                except Exception as e:
                    errors.append({"id": key, "error": str(e)})
            return Response(
                {"volume": vid, "checked": checked, "errors": errors,
                 "ok": not errors}
            )

        # --- integrity scrub plane (maintenance/scrub.py) -----------------
        @svc.route("GET", r"/admin/scrub/status")
        def scrub_status(req: Request) -> Response:
            if self.scrubber is None:
                return Response({"error": "scrubber not started"}, 503)
            out = self.scrubber.status()
            out["interval"] = self.scrub_interval
            return Response(out)

        @svc.route("POST", r"/admin/scrub/run")
        def scrub_run(req: Request) -> Response:
            """One synchronous, throttled scrub pass (whole store, or one
            volume) — the volume.scrub verb's and the chaos suite's
            entry. Detection only: repairs route through the master's
            scrub task (or volume.scrub -apply)."""
            if self.scrubber is None:
                return Response({"error": "scrubber not started"}, 503)
            try:
                p = req.json()
            except ValueError:
                p = {}
            vid = int(p["volume"]) if p.get("volume") is not None else None
            if self.fastlane:  # scrub must see the engine's appends
                self.fastlane.drain()
            found = self.scrubber.scrub_pass(volume_id=vid)
            if found:
                self.heartbeat_once()  # the master learns immediately
            return Response({
                "ok": True,
                "findings": [f.to_dict() for f in found],
                "stats": dict(self.scrubber.stats),
            })

        @svc.route("POST", r"/admin/scrub/resolve")
        def scrub_resolve(req: Request) -> Response:
            """Drop findings a just-applied repair addressed, so the
            heartbeat stops re-advertising healed damage (and the
            master's scrub detector stops re-queueing it). The next
            scheduled pass re-verifies — resolve is an optimization,
            re-detection is the ground truth."""
            if self.scrubber is None:
                return Response({"error": "scrubber not started"}, 503)
            p = req.json()
            dropped = self.scrubber.resolve(
                kind=p.get("kind"),
                volume=int(p["volume"]) if p.get("volume") is not None
                else None,
                needle=int(p["needle"]) if p.get("needle") is not None
                else None,
            )
            if dropped:
                self.heartbeat_once()
            return Response({"ok": True, "resolved": dropped})

        @svc.route("GET", r"/admin/scrub/needle")
        def scrub_needle(req: Request) -> Response:
            """One needle's record, read through the full verifying path
            (CRC + degraded-read ladder) and re-serialized canonically —
            the verified-good source side of a corrupt-needle repair."""
            vid = int(req.query["volume"])
            needle_id = int(req.query["needle"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            try:
                n = v.read_needle(needle_id)
            except NotFound:
                return Response({"error": "needle not found"}, 404)
            except Exception as e:
                # this holder can't prove the needle either: not a source
                return Response({"error": f"unverifiable: {e}"}, 409)
            return Response(
                n.to_bytes(v.version()),
                content_type="application/octet-stream",
            )

        @svc.route("POST", r"/admin/scrub/repair_needle")
        def scrub_repair_needle(req: Request) -> Response:
            """Heal one corrupt needle in place: re-append a verified
            copy (from `source`'s /admin/scrub/needle, or reconstructed
            locally through the degraded-read ladder when this volume
            has EC redundancy). The needle map then points at the clean
            record; the corrupt bytes become vacuumable garbage."""
            p = req.json()
            vid = int(p["volume"])
            needle_id = int(p["needle"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            source = (p.get("source") or "").rstrip("/")
            try:
                if source:
                    status, _, blob = http_request(
                        "GET",
                        f"{source}/admin/scrub/needle?volume={vid}"
                        f"&needle={needle_id}",
                        timeout=60,
                    )
                    if status != 200:
                        return Response(
                            {"error": f"source -> {status}"}, 502)
                    n = Needle.from_bytes(blob, version=v.version())
                else:
                    # local redundancy: read_needle's degraded ladder
                    # reconstructs from online/sealed EC parity
                    n = v.read_needle(needle_id)
            except Exception as e:
                return Response(
                    {"error": f"no verified copy: {e}"}, 409)
            if n.id != needle_id:
                return Response({"error": "source returned wrong needle"},
                                409)
            v.write_needle(n)
            if self.scrubber is not None:
                self.scrubber.resolve(kind="corrupt_needle", volume=vid,
                                      needle=needle_id)
            self.heartbeat_once()  # digest/finding state changed
            return Response({"ok": True, "needle": f"{needle_id:x}",
                             "source": source or "local-reconstruction"})

        @svc.route("POST", r"/admin/scrub/sync")
        def scrub_sync(req: Request) -> Response:
            """Anti-entropy re-sync of THIS holder's replica from a
            digest-majority source: pull the source's live needle list,
            append verified copies of needles we miss, tombstone needles
            the majority deleted. Needle-level — no whole-volume copy."""
            p = req.json()
            vid = int(p["volume"])
            source = p["source"].rstrip("/")
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            if self.fastlane:
                self.fastlane.drain()
            listing = get_json(
                f"{source}/admin/volume/needles?volume={vid}", timeout=300)
            theirs = {int(n["id"]): n for n in listing.get("needles", [])}
            mine = {key for key, _off, _sz in v.nm.ascending_visit()}
            if not theirs and mine:
                # the detector never SELECTS an empty-digest holder as
                # the sync source (empty replicas are always the
                # divergent targets) — so an empty source here means a
                # stale task or an operator mistake, and a bare sync
                # against it would tombstone the whole replica. Refuse:
                # that heal is fix_replication/human territory.
                return Response(
                    {"error": "source reports no live needles; refusing"
                              " to mass-delete this replica"}, 409)
            copied, deleted, failed = 0, 0, 0
            for nid, meta in theirs.items():
                if nid in mine:
                    continue
                status, _, blob = http_request(
                    "GET",
                    f"{source}/admin/volume/needle_blob?volume={vid}"
                    f"&offset={meta['offset']}&size={meta['size']}",
                    timeout=60,
                )
                if status != 200:
                    failed += 1
                    continue
                try:  # from_bytes CRC-verifies: never sync damage in
                    n = Needle.from_bytes(
                        blob, size=meta["size"], version=v.version())
                    v.write_needle(n)
                    copied += 1
                except Exception:
                    failed += 1
            for nid in mine - set(theirs):
                # the majority tombstoned it; a diverged replica that
                # missed the delete must not resurrect it on failover
                v.delete_needle(Needle(id=nid))
                deleted += 1
            if self.scrubber is not None:
                self.scrubber.resolve(kind="replica_divergence",
                                      volume=vid)
            self.heartbeat_once()  # fresh digest -> divergence clears
            return Response({"ok": True, "copied": copied,
                             "deleted": deleted, "failed": failed})

        @svc.route("GET", r"/admin/tail")
        def tail(req: Request) -> Response:
            """Needles appended after since_ns (`volume_backup.go:66`)."""
            if self.fastlane:  # tail must see the engine's appends
                self.fastlane.drain()
            vid = int(req.query["volume"])
            since_ns = int(req.query.get("since_ns", 0))
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            start = (
                v.binary_search_by_append_at_ns(since_ns) if since_ns else None
            )
            if start is None:
                from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE

                start = SUPER_BLOCK_SIZE
            import os

            data = v._dat.read_at(v.size() - start, start)
            return Response(data, content_type="application/octet-stream")

    def _register_query_route(self, svc) -> None:
        """S3-Select-ish content filtering (`volume_grpc_query.go:12`)."""

        @svc.route("POST", r"/query")
        def query(req: Request) -> Response:
            from seaweedfs_tpu.query import query_csv, query_json_lines

            p = req.json()
            fid = p.get("fid", "")
            try:
                vid_s, _, rest = fid.partition(",")
                vid = int(vid_s)
                key, cookie = parse_key_hash_with_delta(rest)
            except (ValueError, AttributeError):
                return Response({"error": f"bad fid {fid!r}"}, 400)
            # /query returns needle CONTENT: it is a read and must demand
            # the same token the GET path does, or secured reads leak
            if not self._file_jwt_ok(req, self.security.read_key, fid):
                return Response({"error": "unauthorized"}, 401)
            try:
                n = self._store_read(vid, key, cookie)
            except (NotFound, VolumeError) as e:
                return Response({"error": str(e)}, 404)
            data = n.data
            if n.is_compressed():
                from seaweedfs_tpu.util.compression import decompress_data

                data = decompress_data(data)
            kind = p.get("type", "json")
            select = p.get("select")
            where = p.get("where")
            limit = int(p.get("limit", 0))
            try:
                if kind == "csv":
                    rows = query_csv(
                        data, select, where,
                        has_header=bool(p.get("header", True)),
                        delimiter=p.get("delimiter", ","),
                        limit=limit,
                    )
                else:
                    rows = query_json_lines(data, select, where, limit=limit)
            except ValueError as e:
                return Response({"error": str(e)}, 400)
            return Response({"rows": rows, "count": len(rows)})

    def _pull_file(
        self, source: str, vid: int, collection: str, ext: str, dest: str,
        chunk: int = 16 * 1024 * 1024,
    ) -> int:
        """Ranged GETs of /admin/volume/raw until EOF -> dest file.
        Downloads into a temp sibling and renames, so a failed pull never
        clobbers an existing good file. Each ranged GET is idempotent and
        rides the unified RetryPolicy (a transient 5xx/socket error must
        not sink a multi-GB evacuate/rebuild copy at 99%). Returns the
        bytes pulled (classic-repair bytes-on-wire accounting)."""
        import os

        tmp = dest + ".pull"
        try:
            offset = 0
            with open(tmp, "wb") as f:
                while True:
                    url = (
                        f"{source}/admin/volume/raw?volume={vid}&ext={ext}"
                        f"&collection={urllib.parse.quote(collection)}"
                        f"&offset={offset}&size={chunk}"
                    )

                    def pull_range():
                        status, hdrs, data = http_request(
                            "GET", url, timeout=120)
                        if status >= 500:  # transient: worth a retry
                            raise IOError(
                                f"pull {ext} from {source}: {status}")
                        return status, hdrs, data

                    status, headers, body = READ_POLICY.call(pull_range)
                    if status != 200:
                        raise IOError(f"pull {ext} from {source}: {status}")
                    f.write(body)
                    offset += len(body)
                    total = int(headers.get("X-Total-Size", offset))
                    if offset >= total or not body:
                        break
            os.replace(tmp, dest)
            return offset
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    # --- handlers -------------------------------------------------------------
    def _parse_fid(self, req: Request) -> tuple[int, int, int]:
        vid = int(req.match.group(1))
        key, cookie = parse_key_hash_with_delta(req.match.group(2))
        return vid, key, cookie

    def _store_read(self, vid: int, key: int, cookie: int | None):
        """store.read with one drain-and-retry on miss: a needle the
        fastlane engine just wrote may not be in the Python map yet."""
        try:
            return self.store.read(vid, key, cookie=cookie)
        except NotFound:
            if not self.fastlane:
                raise
            # retry unconditionally after the drain: the background drain
            # loop may have applied the missing event between our miss and
            # our drain() returning 0
            self.fastlane.drain()
            return self.store.read(vid, key, cookie=cookie)

    def _do_read(self, req: Request, head: bool) -> Response:
        try:
            vid, key, cookie = self._parse_fid(req)
        except ValueError as e:
            return Response({"error": str(e)}, 400)
        if not self._check_read_jwt(req):
            return Response({"error": "unauthorized"}, 401)
        # cross-core delete fence: this handler only sees reads the engine
        # proxied (query params, multi-range, secure reads), and a native
        # DELETE acked up to one drain tick earlier may not be in the
        # Python needle map yet — a stale hit would serve a deleted needle.
        # Drain before the lookup so read-your-deletes holds on EVERY path.
        if self.fastlane is not None and vid in self.fastlane._volumes:
            self.fastlane.drain()
        try:
            n = self._store_read(vid, key, cookie)
        except NotFound:
            return Response(b"", 404)
        except VolumeError as e:
            return Response({"error": str(e)}, 404)
        headers = {"ETag": f'"{n.etag()}"', "Accept-Ranges": "bytes"}
        mime = n.mime.decode() if n.has_mime() and n.mime else "application/octet-stream"
        if n.has_name() and n.name:
            headers["Content-Disposition"] = (
                f'inline; filename="{urllib.parse.quote(n.name.decode("utf-8", "replace"))}"'
            )
        if n.is_compressed():
            headers["Content-Encoding"] = "gzip"
        data = n.data
        # on-read resize/crop hook (`volume_server_handlers_read.go:310-370`)
        if not n.is_compressed() and (
            "width" in req.query or "height" in req.query
        ):
            from seaweedfs_tpu.images import RESIZABLE_MIME, resized

            guessed = mime
            if guessed == "application/octet-stream" and n.has_name() and n.name:
                ext = n.name.decode("utf-8", "replace").rsplit(".", 1)[-1].lower()
                guessed = {"jpg": "image/jpeg", "jpeg": "image/jpeg",
                           "png": "image/png", "gif": "image/gif",
                           "webp": "image/webp"}.get(ext, guessed)
            if guessed in RESIZABLE_MIME:
                def _int(qk):
                    try:
                        return int(req.query.get(qk, "") or 0) or None
                    except ValueError:
                        return None

                data = resized(data, guessed, _int("width"), _int("height"),
                               req.query.get("mode", ""))
                mime = guessed
        # range support
        rng = req.headers.get("Range")
        status = 200
        if rng and rng.startswith("bytes=") and "," not in rng:
            # RFC 7233: an unintelligible Range is ignored (200 full body),
            # never a 500 — and the dash is mandatory. Same semantics as
            # the engine's native range path (fastlane.cpp handle_read).
            try:
                spec = rng[6:]
                if "-" not in spec:
                    raise ValueError(rng)
                start_s, _, end_s = spec.partition("-")
                # strict digits only (int() would accept '+', '_', spaces
                # and unicode digits the native path rejects)
                if (start_s and not start_s.isascii()) or \
                        (end_s and not end_s.isascii()) or \
                        (start_s and not start_s.isdigit()) or \
                        (end_s and not end_s.isdigit()):
                    raise ValueError(rng)
                start = (int(start_s) if start_s
                         else max(0, len(data) - int(end_s)))
                end = int(end_s) if end_s and start_s else len(data) - 1
            except ValueError:
                start, end = 0, -1  # ignore the malformed header
            end = min(end, len(data) - 1)
            if 0 <= start <= end:
                headers["Content-Range"] = f"bytes {start}-{end}/{len(data)}"
                data = data[start : end + 1]
                status = 206
        if head:
            headers["Content-Length-Hint"] = str(len(data))
            return Response(b"", status, headers, content_type=mime)
        return Response(data, status, headers, content_type=mime)

    def _file_jwt_ok(self, req: Request, key: str, fid: str) -> bool:
        """One fid-bound token check for reads AND writes
        (`volume_server_handlers.go:33-75` maybeCheckJwtAuthorization),
        shared so the claim-matching rule cannot drift between the two —
        or from the engine's native jwt_fid_ok (fastlane.cpp), which strips
        both the multi-count `_N` suffix and any `.ext` the same way."""
        if not key:
            return True
        base = fid.split("_")[0].split(".")[0]
        token = token_from_request(req.headers, req.query)
        return verify_file_jwt(key, token, base)

    def _check_read_jwt(self, req: Request) -> bool:
        """Demand a read token when jwt.signing.read is configured —
        `volume_server_handlers.go:33-46` (GET/HEAD). The engine verifies
        the same tokens natively (fastlane.cpp jwt_fid_ok) so secured reads
        stay on the native plane; this is the proxy/fallback path."""
        fid = f"{req.match.group(1)},{req.match.group(2)}"
        return self._file_jwt_ok(req, self.security.read_key, fid)

    def _check_write_jwt(self, req: Request) -> bool:
        # multi-count assignments append _N to the fid; the master signed
        # the base fid (weed/operation assign_file_id)
        fid = f"{req.match.group(1)},{req.match.group(2)}"
        return self._file_jwt_ok(req, self.security.write_key, fid)

    def _do_write(self, req: Request) -> Response:
        if self.fastlane:  # overwrite checks need the engine's appends applied
            self.fastlane.drain()
        try:
            vid, key, cookie = self._parse_fid(req)
        except ValueError as e:
            return Response({"error": str(e)}, 400)
        if not self._check_write_jwt(req):
            return Response({"error": "unauthorized"}, 401)
        is_replicate = req.query.get("type") == "replicate"
        body = req.body
        part = req.multipart_file()
        if part is not None:
            filename, mime, data = part
        else:
            data = body
            filename = req.headers.get("X-File-Name", "")
            mime = req.headers.get("Content-Type", "")
            if mime in ("application/json", "application/x-www-form-urlencoded"):
                mime = ""
        # EXIF orientation fix on upload (`needle.go:101-106`: .jpg only,
        # and only when the client isn't asking for raw bytes back)
        is_jpg = (
            mime == "image/jpeg"
            or filename.lower().endswith((".jpg", ".jpeg"))
        )
        if is_jpg and not is_replicate:
            from seaweedfs_tpu.images import fix_jpg_orientation

            data = fix_jpg_orientation(data)
        n = Needle(cookie=cookie, id=key, data=data)
        if filename:
            n.name = filename.encode()
            n.set_has_name()
        if mime and len(mime) < 256 and mime != "application/octet-stream":
            n.mime = mime.encode()
            n.set_has_mime()
        ttl_s = req.query.get("ttl", "")
        if ttl_s:
            from seaweedfs_tpu.storage.types import TTL

            n.ttl = TTL.parse(ttl_s)
            n.set_has_ttl()
        import time as _time

        n.last_modified = int(_time.time())
        n.set_has_last_modified()
        try:
            offset, size = self.store.write(vid, n, check_cookie=not is_replicate)
        except VolumeError as e:
            return Response({"error": str(e)}, 500)
        if not is_replicate:
            v = self.store.get_volume(vid)
            if v is not None and v.online_ec is not None \
                    and v.online_ec.active:
                # parity-only durability: the ack rides on local .dat
                # durability + the streamed parity emit — no 2x replica
                # fan-out (write amplification 1.4x instead of 2.0x)
                v.online_ec.pump()
                if v.size() >= self.volume_size_limit:
                    self.heartbeat_once()
                return Response(
                    {"name": filename, "size": len(data), "eTag": n.etag()},
                    201,
                )
            rp = v.super_block.replica_placement if v else None
            if rp and rp.copy_count() > 1:
                try:
                    extra = {"ttl": ttl_s} if ttl_s else {}
                    self._replicate(
                        "POST", vid, req.match.group(2), body,
                        {
                            "Content-Type": req.headers.get("Content-Type", ""),
                            "X-File-Name": req.headers.get("X-File-Name", ""),
                            # replicas verify the same master-signed token
                            "Authorization": req.headers.get("Authorization", ""),
                        },
                        extra_query=extra,
                    )
                except VolumeError as e:
                    return Response({"error": str(e)}, 500)
            if v and v.size() >= self.volume_size_limit:
                self.heartbeat_once()  # tell master it's full
        return Response(
            {"name": filename, "size": len(data), "eTag": n.etag()}, 201
        )

    def _do_delete(self, req: Request) -> Response:
        if self.fastlane:
            self.fastlane.drain()
        try:
            vid, key, cookie = self._parse_fid(req)
        except ValueError as e:
            return Response({"error": str(e)}, 400)
        if not self._check_write_jwt(req):
            return Response({"error": "unauthorized"}, 401)
        is_replicate = req.query.get("type") == "replicate"
        n = Needle(cookie=cookie, id=key)
        try:
            freed = self.store.delete(vid, n)
        except VolumeError as e:
            return Response({"error": str(e)}, 500)
        if not is_replicate:
            v = self.store.get_volume(vid)
            if v is not None and v.online_ec is not None \
                    and v.online_ec.active:
                v.online_ec.pump()  # the tombstone append rides the stripe
            else:
                rp = v.super_block.replica_placement if v else None
                if rp and rp.copy_count() > 1:
                    try:
                        self._replicate(
                            "DELETE", vid, req.match.group(2), b"",
                            {"Authorization": req.headers.get(
                                "Authorization", "")},
                        )
                    except VolumeError as e:
                        return Response({"error": str(e)}, 500)
        return Response({"size": freed}, 202)
