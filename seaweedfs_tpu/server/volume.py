"""Volume server: HTTP data plane + admin plane + heartbeat loop.

Reference: `weed/server/volume_server_handlers_read.go:45` /
`_write.go:18` (GET/POST/DELETE /<vid>,<fid>), `store_replicate.go:26`
(synchronous replica fan-out), `volume_grpc_erasure_coding.go` (EC verbs —
JSON admin endpoints here), `volume_grpc_client_to_master.go:50` (heartbeat).
"""

from __future__ import annotations

import json
import threading
import urllib.parse

from seaweedfs_tpu.storage import crc as crc_mod
from seaweedfs_tpu.storage.erasure_coding import encoder as ec_encoder
from seaweedfs_tpu.storage.erasure_coding import geometry
from seaweedfs_tpu.storage.file_id import parse_key_hash_with_delta
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import NotFound, VolumeError, volume_file_name

from .httpd import HTTPService, Request, Response, get_json, http_request, post_json

FID_RE = r"/(\d+),([0-9a-fA-F_]+)(?:\.[^/]*)?"


class VolumeServer:
    def __init__(
        self,
        directories: list[str],
        master_url: str,
        host: str = "127.0.0.1",
        port: int = 8080,
        public_url: str = "",
        data_center: str = "",
        rack: str = "",
        pulse_seconds: int = 5,
        max_volume_count: int = 100,
    ) -> None:
        self.master_url = master_url.rstrip("/")
        self.service = HTTPService(host, port)
        self.store: Store | None = None
        self._dirs = directories
        self._host = host
        self._public_url = public_url
        self.data_center = data_center
        self.rack = rack
        self.pulse_seconds = pulse_seconds
        self.max_volume_count = max_volume_count
        self.volume_size_limit = 30 * 1024 * 1024 * 1024
        self._stop = threading.Event()
        self._routes()

    def start(self) -> None:
        self.service.start()
        self.store = Store(
            self._dirs,
            ip=self._host,
            port=self.service.port,
            public_url=self._public_url,
        )
        for loc in self.store.locations:
            loc.max_volume_count = self.max_volume_count
        self.heartbeat_once()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        self.service.stop()
        if self.store:
            self.store.close()

    @property
    def url(self) -> str:
        return self.service.url

    # --- heartbeat --------------------------------------------------------------
    def heartbeat_once(self) -> None:
        hb = self.store.collect_heartbeat()
        hb["data_center"] = self.data_center
        hb["rack"] = self.rack
        hb["max_volume_count"] = self.max_volume_count
        try:
            resp = post_json(f"{self.master_url}/heartbeat", hb, timeout=10)
            self.volume_size_limit = int(
                resp.get("volume_size_limit", self.volume_size_limit)
            )
        except Exception:
            pass

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.pulse_seconds):
            self.heartbeat_once()

    # --- replication --------------------------------------------------------------
    def _replicate(
        self,
        method: str,
        vid: int,
        fid: str,
        body: bytes,
        headers: dict,
        extra_query: dict | None = None,
    ) -> None:
        """Fan out to the other replica locations (`store_replicate.go:26`).
        All-or-nothing: any replica failure surfaces as an error so the client
        can retry with a fresh assignment. The original request's ttl/headers
        are forwarded so replicas store identical needles."""
        try:
            info = get_json(f"{self.master_url}/dir/lookup?volumeId={vid}", timeout=5)
        except Exception as e:
            raise VolumeError(f"replicate lookup failed: {e}")
        me = f"{self._host}:{self.service.port}"
        qs = "type=replicate"
        for k, v in (extra_query or {}).items():
            qs += f"&{k}={urllib.parse.quote(str(v))}"
        for loc in info.get("locations", []):
            target = loc["url"]
            if target == me:
                continue
            status, _, out = http_request(
                method,
                f"http://{target}/{vid},{fid}?{qs}",
                body=body,
                headers={k: v for k, v in headers.items() if v},
            )
            if status >= 400:
                raise VolumeError(f"replica write to {target} failed: {out[:200]!r}")

    # --- routes -------------------------------------------------------------------
    def _routes(self) -> None:
        svc = self.service

        @svc.route("GET", FID_RE)
        def read(req: Request) -> Response:
            return self._do_read(req, head=False)

        @svc.route("HEAD", FID_RE)
        def head(req: Request) -> Response:
            return self._do_read(req, head=True)

        @svc.route("POST", FID_RE)
        def write(req: Request) -> Response:
            return self._do_write(req)

        @svc.route("PUT", FID_RE)
        def put(req: Request) -> Response:
            return self._do_write(req)

        @svc.route("DELETE", FID_RE)
        def delete(req: Request) -> Response:
            return self._do_delete(req)

        @svc.route("GET", r"/status")
        def status(req: Request) -> Response:
            hb = self.store.collect_heartbeat()
            return Response({"Version": "seaweedfs-tpu", **hb})

        @svc.route("POST", r"/admin/allocate_volume")
        def allocate(req: Request) -> Response:
            p = req.json()
            self.store.add_volume(
                int(p["volume"]),
                p.get("collection", ""),
                p.get("replication", "000"),
                p.get("ttl", ""),
            )
            return Response({"ok": True})

        @svc.route("POST", r"/admin/delete_volume")
        def delete_volume(req: Request) -> Response:
            self.store.delete_volume(int(req.json()["volume"]))
            return Response({"ok": True})

        @svc.route("POST", r"/admin/vacuum")
        def vacuum(req: Request) -> Response:
            vid = int(req.json()["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            garbage = v.garbage_level()
            v.compact()
            v.commit_compact()
            self.heartbeat_once()
            return Response({"ok": True, "garbage_was": garbage})

        @svc.route("POST", r"/admin/volume/readonly")
        def readonly(req: Request) -> Response:
            p = req.json()
            self.store.mark_readonly(int(p["volume"]), bool(p.get("readonly", True)))
            return Response({"ok": True})

        # --- EC verbs (volume_grpc_erasure_coding.go) ---
        @svc.route("POST", r"/admin/ec/generate")
        def ec_generate(req: Request) -> Response:
            p = req.json()
            vid = int(p["volume"])
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            v.readonly = True
            base = v.base_name
            ec_encoder.write_ec_files(base)
            ec_encoder.write_sorted_file_from_idx(base)
            ec_encoder.save_volume_info(base + ".vif", version=v.version())
            return Response({"ok": True, "shards": list(range(14))})

        @svc.route("POST", r"/admin/ec/mount")
        def ec_mount(req: Request) -> Response:
            p = req.json()
            ev = self.store.mount_ec_volume(int(p["volume"]), p.get("collection", ""))
            self.heartbeat_once()
            return Response({"ok": True, "shards": ev.shard_ids()})

        @svc.route("POST", r"/admin/ec/unmount")
        def ec_unmount(req: Request) -> Response:
            self.store.unmount_ec_volume(int(req.json()["volume"]))
            self.heartbeat_once()
            return Response({"ok": True})

        @svc.route("POST", r"/admin/ec/rebuild")
        def ec_rebuild(req: Request) -> Response:
            p = req.json()
            vid = int(p["volume"])
            collection = p.get("collection", "")
            for loc in self.store.locations:
                from seaweedfs_tpu.storage.erasure_coding.ec_volume import (
                    ec_shard_file_name,
                )

                base = ec_shard_file_name(collection, loc.directory, vid)
                import os

                if any(
                    os.path.exists(base + geometry.to_ext(i)) for i in range(14)
                ):
                    rebuilt = ec_encoder.rebuild_ec_files(base)
                    return Response({"ok": True, "rebuilt": rebuilt})
            return Response({"error": f"no shards for volume {vid}"}, 404)

        @svc.route("POST", r"/admin/ec/delete_volume")
        def ec_delete(req: Request) -> Response:
            """Delete the original volume files after EC spread
            (`command_ec_encode.go` deletes source replicas)."""
            vid = int(req.json()["volume"])
            self.store.delete_volume(vid)
            return Response({"ok": True})

        @svc.route("GET", r"/admin/ec/shard")
        def ec_shard_read(req: Request) -> Response:
            """Raw shard byte range — remote EC reads (`store_ec.go:281`)."""
            vid = int(req.query["volume"])
            shard = int(req.query["shard"])
            offset = int(req.query.get("offset", 0))
            size = int(req.query.get("size", -1))
            ev = self.store.get_ec_volume(vid)
            if ev is None:
                return Response({"error": "ec volume not mounted"}, 404)
            import os

            fd = ev.shards.get(shard)
            if fd is None:
                return Response({"error": f"shard {shard} not local"}, 404)
            if size < 0:
                size = ev.shard_size - offset
            data = os.pread(fd, size, offset)
            return Response(data, content_type="application/octet-stream")

        @svc.route("GET", r"/admin/tail")
        def tail(req: Request) -> Response:
            """Needles appended after since_ns (`volume_backup.go:66`)."""
            vid = int(req.query["volume"])
            since_ns = int(req.query.get("since_ns", 0))
            v = self.store.get_volume(vid)
            if v is None:
                return Response({"error": f"volume {vid} not found"}, 404)
            start = (
                v.binary_search_by_append_at_ns(since_ns) if since_ns else None
            )
            if start is None:
                from seaweedfs_tpu.storage.super_block import SUPER_BLOCK_SIZE

                start = SUPER_BLOCK_SIZE
            import os

            data = os.pread(v._fd, v.size() - start, start)
            return Response(data, content_type="application/octet-stream")

    # --- handlers -------------------------------------------------------------
    def _parse_fid(self, req: Request) -> tuple[int, int, int]:
        vid = int(req.match.group(1))
        key, cookie = parse_key_hash_with_delta(req.match.group(2))
        return vid, key, cookie

    def _do_read(self, req: Request, head: bool) -> Response:
        try:
            vid, key, cookie = self._parse_fid(req)
        except ValueError as e:
            return Response({"error": str(e)}, 400)
        try:
            n = self.store.read(vid, key, cookie=cookie)
        except NotFound:
            return Response(b"", 404)
        except VolumeError as e:
            return Response({"error": str(e)}, 404)
        headers = {"ETag": f'"{n.etag()}"', "Accept-Ranges": "bytes"}
        mime = n.mime.decode() if n.has_mime() and n.mime else "application/octet-stream"
        if n.has_name() and n.name:
            headers["Content-Disposition"] = (
                f'inline; filename="{urllib.parse.quote(n.name.decode("utf-8", "replace"))}"'
            )
        if n.is_compressed():
            headers["Content-Encoding"] = "gzip"
        data = n.data
        # range support
        rng = req.headers.get("Range")
        status = 200
        if rng and rng.startswith("bytes=") and "," not in rng:
            spec = rng[6:]
            start_s, _, end_s = spec.partition("-")
            start = int(start_s) if start_s else max(0, len(data) - int(end_s))
            end = int(end_s) if end_s and start_s else len(data) - 1
            end = min(end, len(data) - 1)
            if start <= end:
                headers["Content-Range"] = f"bytes {start}-{end}/{len(data)}"
                data = data[start : end + 1]
                status = 206
        if head:
            headers["Content-Length-Hint"] = str(len(data))
            return Response(b"", status, headers, content_type=mime)
        return Response(data, status, headers, content_type=mime)

    def _do_write(self, req: Request) -> Response:
        try:
            vid, key, cookie = self._parse_fid(req)
        except ValueError as e:
            return Response({"error": str(e)}, 400)
        is_replicate = req.query.get("type") == "replicate"
        body = req.body
        part = req.multipart_file()
        if part is not None:
            filename, mime, data = part
        else:
            data = body
            filename = req.headers.get("X-File-Name", "")
            mime = req.headers.get("Content-Type", "")
            if mime in ("application/json", "application/x-www-form-urlencoded"):
                mime = ""
        n = Needle(cookie=cookie, id=key, data=data)
        if filename:
            n.name = filename.encode()
            n.set_has_name()
        if mime and len(mime) < 256 and mime != "application/octet-stream":
            n.mime = mime.encode()
            n.set_has_mime()
        ttl_s = req.query.get("ttl", "")
        if ttl_s:
            from seaweedfs_tpu.storage.types import TTL

            n.ttl = TTL.parse(ttl_s)
            n.set_has_ttl()
        import time as _time

        n.last_modified = int(_time.time())
        n.set_has_last_modified()
        try:
            offset, size = self.store.write(vid, n, check_cookie=not is_replicate)
        except VolumeError as e:
            return Response({"error": str(e)}, 500)
        if not is_replicate:
            v = self.store.get_volume(vid)
            rp = v.super_block.replica_placement if v else None
            if rp and rp.copy_count() > 1:
                try:
                    extra = {"ttl": ttl_s} if ttl_s else {}
                    self._replicate(
                        "POST", vid, req.match.group(2), body,
                        {
                            "Content-Type": req.headers.get("Content-Type", ""),
                            "X-File-Name": req.headers.get("X-File-Name", ""),
                        },
                        extra_query=extra,
                    )
                except VolumeError as e:
                    return Response({"error": str(e)}, 500)
            if v and v.size() >= self.volume_size_limit:
                self.heartbeat_once()  # tell master it's full
        return Response(
            {"name": filename, "size": len(data), "eTag": n.etag()}, 201
        )

    def _do_delete(self, req: Request) -> Response:
        try:
            vid, key, cookie = self._parse_fid(req)
        except ValueError as e:
            return Response({"error": str(e)}, 400)
        is_replicate = req.query.get("type") == "replicate"
        n = Needle(cookie=cookie, id=key)
        try:
            freed = self.store.delete(vid, n)
        except VolumeError as e:
            return Response({"error": str(e)}, 500)
        if not is_replicate:
            v = self.store.get_volume(vid)
            rp = v.super_block.replica_placement if v else None
            if rp and rp.copy_count() > 1:
                try:
                    self._replicate("DELETE", vid, req.match.group(2), b"", {})
                except VolumeError as e:
                    return Response({"error": str(e)}, 500)
        return Response({"size": freed}, 202)
