"""seaweedfs_tpu — a TPU-native distributed object store / file system.

A ground-up rebuild of the capabilities of SeaweedFS (reference: kvaps/seaweedfs)
designed TPU-first:

  - the data-plane hot paths (Reed-Solomon(10,4) erasure coding, CRC32C / MD5
    content hashing, CDC dedup fingerprinting) run as JAX/XLA/Pallas kernels on
    TPU, batched onto the MXU/VPU, with C++ native CPU fallbacks (never pure
    Python) loaded via ctypes;
  - multi-chip scaling uses `jax.sharding.Mesh` + `shard_map` over volume
    batches (embarrassingly parallel over ICI; DCN for host batches);
  - the control plane (master / volume server / filer) is asyncio + HTTP/JSON,
    mirroring the reference's own HTTP surface (/dir/assign, /dir/lookup,
    /<vid>,<fid>), with on-disk formats bit-compatible with the reference
    (needle v1/v2/v3, .idx, superblock, .ec00–.ec13, .ecx, .ecj, .vif) so the
    reference's golden fixtures validate this implementation directly.

Layout:
  storage/   volume engine: needle format, volumes, needle maps, erasure coding
  ops/       TPU kernels: GF(2^8) Reed-Solomon, CRC32C, MD5, CDC (JAX/Pallas)
  native/    C++ CPU kernels (Reed-Solomon, CRC32C, MD5) behind ctypes
  parallel/  device mesh + shard_map multi-chip execution
  topology/  master-side cluster state: DC/rack/node tree, volume layout, growth
  server/    master / volume / filer HTTP servers
  filer/     namespace: entries, chunking, visible intervals, stores
  s3/        S3 gateway subset
  shell/     admin shell commands (ec.*, volume.*, fs.*)
  command/   CLI entrypoints (weed-tpu ...)
  utils/     config, http client, misc
"""

__version__ = "0.1.0"
