"""Image resize/crop on read + EXIF orientation fix on upload.

Behavioral port of `weed/images/resizing.go` (GET `?width=&height=&mode=`:
"" = fit preserving aspect, "fit" = letterbox pad, "fill" = cover+crop) and
`weed/images/orientation.go` (JPEG uploads are rewritten upright when EXIF
says the camera was rotated), hooked exactly where the reference hooks them
(`volume_server_handlers_read.go:310-370`, `needle.go:101-106`).

Uses PIL; every function degrades to returning the original bytes on any
decode error, like the reference.
"""

from __future__ import annotations

import io

RESIZABLE_MIME = {"image/jpeg", "image/png", "image/gif", "image/webp"}

_FORMAT_BY_MIME = {
    "image/jpeg": "JPEG",
    "image/png": "PNG",
    "image/gif": "GIF",
    "image/webp": "WEBP",
}

# EXIF 274 = Orientation; PIL transpose ops per value
_ORIENT_OPS = {
    2: ["FLIP_LEFT_RIGHT"],
    3: ["ROTATE_180"],
    4: ["FLIP_TOP_BOTTOM"],
    5: ["FLIP_LEFT_RIGHT", "ROTATE_270"],
    6: ["ROTATE_270"],
    7: ["FLIP_LEFT_RIGHT", "ROTATE_90"],
    8: ["ROTATE_90"],
}


def resized(data: bytes, mime: str, width: int | None, height: int | None,
            mode: str = "") -> bytes:
    """`resizing.go Resized`: scale to width/height; one dimension given →
    preserve aspect; mode "fit" letterboxes, "fill" covers and center-crops."""
    if mime not in RESIZABLE_MIME or (not width and not height):
        return data
    try:
        from PIL import Image

        img = Image.open(io.BytesIO(data))
        ow, oh = img.size
        w, h = width or 0, height or 0
        if w <= 0 and h <= 0:
            return data
        if w <= 0:
            w = max(1, ow * h // oh)
        if h <= 0:
            h = max(1, oh * w // ow)
        fmt = _FORMAT_BY_MIME.get(mime, img.format or "PNG")
        if mode == "fill":
            # cover: scale so both dims >= target, center-crop
            scale = max(w / ow, h / oh)
            nw, nh = max(1, round(ow * scale)), max(1, round(oh * scale))
            img = img.resize((nw, nh), Image.LANCZOS)
            left, top = (nw - w) // 2, (nh - h) // 2
            img = img.crop((left, top, left + w, top + h))
        elif mode == "fit":
            # letterbox inside w×h
            scale = min(w / ow, h / oh)
            nw, nh = max(1, round(ow * scale)), max(1, round(oh * scale))
            img = img.resize((nw, nh), Image.LANCZOS)
            canvas = Image.new(
                "RGBA" if fmt == "PNG" else "RGB", (w, h),
                (255, 255, 255, 0) if fmt == "PNG" else (255, 255, 255),
            )
            canvas.paste(img, ((w - nw) // 2, (h - nh) // 2))
            img = canvas
        else:
            # plain proportional scale (both given: use them as-is — the
            # reference resizes to the exact wxh when both are set)
            if width and height:
                img = img.resize((w, h), Image.LANCZOS)
            else:
                scale = w / ow if width else h / oh
                img = img.resize(
                    (max(1, round(ow * scale)), max(1, round(oh * scale))),
                    Image.LANCZOS,
                )
        if fmt == "JPEG" and img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        buf = io.BytesIO()
        img.save(buf, fmt)
        return buf.getvalue()
    except Exception:
        return data


def fix_jpg_orientation(data: bytes) -> bytes:
    """`orientation.go FixJpgOrientation`: bake the EXIF rotation into the
    pixels so downstream consumers need no EXIF support."""
    try:
        from PIL import Image

        img = Image.open(io.BytesIO(data))
        if (img.format or "").upper() != "JPEG":
            return data
        exif = img.getexif()
        orientation = exif.get(274, 1)
        if orientation in (0, 1):
            return data
        for opname in _ORIENT_OPS.get(orientation, []):
            img = img.transpose(getattr(Image.Transpose, opname))
        exif[274] = 1
        buf = io.BytesIO()
        img.save(buf, "JPEG", quality=95, exif=exif.tobytes())
        return buf.getvalue()
    except Exception:
        return data
