"""Raft consensus for master HA.

Behavioral port of the reference's master replication layer
(`weed/server/raft_server.go`, `raft_hashicorp.go`,
`master_grpc_server_raft.go`): masters elect a leader; the leader owns
volume-id allocation and the file-id sequence; followers redirect clients
to the leader; on failover the replicated state machine (max volume id +
sequence ceiling) carries over so ids are never reused.

This is a compact, standard Raft (election + log replication + persistence
+ commit/apply + snapshot/compaction), transported over the masters'
existing HTTP plane (`POST /raft/request_vote`, `/raft/append_entries`,
`/raft/install_snapshot`). Once the log exceeds `compact_threshold` applied
entries, the state machine is snapshotted via `snapshot_fn` and the log
prefix truncated; followers that fall behind the snapshot receive it via
InstallSnapshot and restore through `restore_fn` — so persistence cost per
write and memory stay bounded regardless of uptime.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable


class NotLeader(Exception):
    def __init__(self, leader: str | None) -> None:
        super().__init__(f"not leader; leader={leader}")
        self.leader = leader


def _default_rpc(peer: str, method: str, payload: dict,
                 timeout: float = 1.0) -> dict:
    import json as _json

    from seaweedfs_tpu.server.httpd import http_request

    status, _, body = http_request(
        "POST", f"{peer}/raft/{method}", body=_json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, timeout=timeout,
    )
    if status != 200:
        raise IOError(f"raft rpc {method} -> {status}")
    return _json.loads(body)


class RaftNode:
    def __init__(
        self,
        node_id: str,
        peers: list[str],
        apply_fn: Callable[[dict], object],
        state_dir: str | None = None,
        heartbeat_interval: float = 0.08,
        election_timeout: tuple[float, float] = (0.3, 0.6),
        rpc: Callable[..., dict] | None = None,
        snapshot_fn: Callable[[], dict] | None = None,
        restore_fn: Callable[[dict], None] | None = None,
        compact_threshold: int = 256,
        on_demote: Callable[[], None] | None = None,
    ) -> None:
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.apply_fn = apply_fn
        self.state_dir = state_dir
        # fired synchronously when this node stops being leader: the master
        # clears its native assign profiles here so a demoted leader never
        # keeps minting fids from stale topology (ADVICE r4)
        self.on_demote = on_demote
        self.heartbeat_interval = heartbeat_interval
        self.election_timeout = election_timeout
        self.rpc = rpc or _default_rpc
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.compact_threshold = compact_threshold

        self.mu = threading.RLock()
        self.role = "follower"
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []  # entries {term, index, command}; 1-indexed
        # log compaction state: entries <= snap_index live only in the
        # snapshot; self.log[0] (if any) has index snap_index + 1
        self.snap_index = 0
        self.snap_term = 0
        self.snap_state: dict | None = None
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: str | None = None
        self.removed = False  # true after a replicated self-removal
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self._apply_results: dict[int, object] = {}
        self._commit_cv = threading.Condition(self.mu)
        self._last_heartbeat = time.monotonic()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._load()

    # --- persistence ---------------------------------------------------------
    def _state_path(self) -> str | None:
        return os.path.join(self.state_dir, "raft_state.json") \
            if self.state_dir else None

    def _load(self) -> None:
        p = self._state_path()
        if p and os.path.exists(p):
            with open(p) as f:
                st = json.load(f)
            self.current_term = st.get("term", 0)
            self.voted_for = st.get("voted_for")
            self.log = st.get("log", [])
            self.commit_index = st.get("commit_index", 0)
            self.snap_index = st.get("snap_index", 0)
            self.snap_term = st.get("snap_term", 0)
            self.snap_state = st.get("snap_state")
            if "peers" in st:  # membership changes survive restarts
                self.peers = [p for p in st["peers"] if p != self.id]
            self.removed = bool(st.get("removed", False))
            if self.snap_state is not None and self.restore_fn is not None:
                self.restore_fn(self.snap_state)
            self.last_applied = self.snap_index

    def _persist(self) -> None:
        p = self._state_path()
        if not p:
            return
        os.makedirs(self.state_dir, exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "term": self.current_term,
                "voted_for": self.voted_for,
                "log": self.log,
                "commit_index": self.commit_index,
                "snap_index": self.snap_index,
                "snap_term": self.snap_term,
                "snap_state": self.snap_state,
                "peers": self.peers,
                "removed": self.removed,
            }, f)
        os.replace(tmp, p)

    # --- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._stop.clear()
        t = threading.Thread(target=self._ticker, daemon=True)
        t.start()
        self._threads.append(t)
        # replay committed-but-unapplied state after restart
        with self.mu:
            self._apply_committed()

    def stop(self) -> None:
        self._stop.set()

    # --- helpers (callers hold mu) --------------------------------------------
    def _last_log(self) -> tuple[int, int]:
        if not self.log:
            return self.snap_index, self.snap_term
        e = self.log[-1]
        return e["index"], e["term"]

    def _entry(self, index: int) -> dict | None:
        pos = index - self.snap_index - 1
        if 0 <= pos < len(self.log):
            return self.log[pos]
        return None

    def _term_at(self, index: int) -> int:
        if index == self.snap_index:
            return self.snap_term
        e = self._entry(index)
        return e["term"] if e else 0

    def _maybe_compact(self) -> None:
        """Snapshot the applied state machine and truncate the log prefix
        once it outgrows compact_threshold (the checkpoint the r1 docstring
        promised; advisor finding #2)."""
        if self.snapshot_fn is None:
            return
        if self.last_applied - self.snap_index < self.compact_threshold:
            return
        cut = self.last_applied
        cut_term = self._term_at(cut)
        state = self.snapshot_fn()
        del self.log[: cut - self.snap_index]
        self.snap_index = cut
        self.snap_term = cut_term
        self.snap_state = state
        # prune stale results; keep a threshold-wide margin so an in-flight
        # propose() waiter racing this compaction can still claim its result
        stale = cut - self.compact_threshold
        for idx in [i for i in self._apply_results if i <= stale]:
            self._apply_results.pop(idx, None)
        self._persist()

    def _become_follower(self, term: int, leader: str | None = None) -> None:
        was_leader = self.role == "leader"
        self.role = "follower"
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
        if leader:
            self.leader_id = leader
        self._persist()
        if was_leader and self.on_demote is not None:
            try:
                self.on_demote()
            except Exception:
                pass  # demotion hooks must never break the raft transition

    def _apply_conf(self, cmd: dict) -> dict:
        """Replicated membership change (`cluster.raft.add/remove`,
        `weed/shell/command_cluster_raft_add.go`): applied on every node
        through the log, persisted so restarts (even after compaction)
        keep the current member set. Removing THIS node demotes it to an
        isolated follower."""
        peer = (cmd.get("peer") or "").rstrip("/")
        if cmd.get("op") == "add":
            if peer and peer != self.id and peer not in self.peers:
                self.peers.append(peer)
                last_index = self.snap_index + len(self.log)
                self.next_index[peer] = last_index + 1
                self.match_index[peer] = 0
        elif cmd.get("op") == "remove":
            if peer == self.id:
                # this node left the cluster: it must never elect itself
                # leader of a singleton again (split brain with the
                # remaining members) — `removed` pins it as a follower
                self.peers = []
                self.removed = True
                self._become_follower(self.current_term)
            elif peer in self.peers:
                self.peers.remove(peer)
                # keep replicating to the victim for a grace window (see
                # _broadcast_heartbeats) so it applies its own removal
                if not hasattr(self, "_parting"):
                    self._parting: dict[str, float] = {}
                self._parting[peer] = time.monotonic() + 3.0
        self._persist()
        return {"ok": True, "peers": list(self.peers)}

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            e = self._entry(self.last_applied)
            if e is not None:
                cmd = e["command"]
                # capture leadership BEFORE applying: a self-removal conf
                # entry demotes inside _apply_conf, and its proposer still
                # deserves the result instead of a spurious NotLeader
                was_leader = self.role == "leader"
                try:
                    if isinstance(cmd, dict) and cmd.get("type") == "_raft_conf":
                        result = self._apply_conf(cmd)
                    else:
                        result = self.apply_fn(cmd)
                except Exception as exc:  # state machine must not kill raft
                    result = exc
                # only a leader has propose() waiters that will claim the
                # result; followers storing them forever is a leak
                if was_leader:
                    self._apply_results[self.last_applied] = result
        self._maybe_compact()
        self._commit_cv.notify_all()

    # --- election ------------------------------------------------------------
    def _ticker(self) -> None:
        while not self._stop.is_set():
            timeout = random.uniform(*self.election_timeout)
            time.sleep(self.heartbeat_interval / 2)
            with self.mu:
                role = self.role
                since = time.monotonic() - self._last_heartbeat
            if role == "leader":
                self._broadcast_heartbeats()
                time.sleep(self.heartbeat_interval / 2)
            elif since > timeout:
                self._run_election()

    def _run_election(self) -> None:
        with self.mu:
            if self.removed:
                return  # a removed node never elects itself (split brain)
            self.role = "candidate"
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.id
            self._last_heartbeat = time.monotonic()
            self._persist()
            last_index, last_term = self._last_log()
            peers = list(self.peers)
        votes = [1]  # self
        done = threading.Event()

        def ask(peer: str) -> None:
            try:
                out = self.rpc(peer, "request_vote", {
                    "term": term, "candidate_id": self.id,
                    "last_log_index": last_index, "last_log_term": last_term,
                })
            except Exception:
                return
            with self.mu:
                if out.get("term", 0) > self.current_term:
                    self._become_follower(out["term"])
                    done.set()
                    return
                if out.get("vote_granted") and self.role == "candidate" \
                        and self.current_term == term:
                    votes[0] += 1
                    if votes[0] * 2 > len(peers) + 1:
                        self._become_leader_locked()
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True)
                   for p in peers]
        for t in threads:
            t.start()
        if not peers:
            with self.mu:
                self._become_leader_locked()
            return
        done.wait(self.election_timeout[0])

    def _become_leader_locked(self) -> None:
        if self.role != "candidate":
            return
        self.role = "leader"
        self.leader_id = self.id
        last_index, _ = self._last_log()
        self.next_index = {p: last_index + 1 for p in self.peers}
        self.match_index = {p: 0 for p in self.peers}
        # announce immediately — followers are near their election timeout
        threading.Thread(
            target=self._broadcast_heartbeats, daemon=True
        ).start()

    # --- replication ----------------------------------------------------------
    def _broadcast_heartbeats(self) -> None:
        targets = list(self.peers)
        # parting peers (just removed) still receive heartbeats briefly so
        # their commit index reaches the removal entry and they learn they
        # were removed (otherwise the victim never applies it)
        parting = getattr(self, "_parting", None)
        if parting:
            now = time.monotonic()
            for p in list(parting):
                if parting[p] < now:
                    parting.pop(p, None)
                    self.next_index.pop(p, None)
                    self.match_index.pop(p, None)
                elif p not in targets:
                    targets.append(p)
        for peer in targets:
            threading.Thread(
                target=self._replicate_to, args=(peer,), daemon=True
            ).start()

    def _replicate_to(self, peer: str) -> None:
        with self.mu:
            if self.role != "leader":
                return
            term = self.current_term
            ni = self.next_index.get(peer, self.snap_index + 1)
            if ni <= self.snap_index and self.snap_index > 0:
                # follower is behind the compacted prefix: ship the snapshot
                payload = {
                    "term": term, "leader_id": self.id,
                    "last_included_index": self.snap_index,
                    "last_included_term": self.snap_term,
                    "state": self.snap_state,
                }
                snap_index = self.snap_index
            else:
                payload = None
        if payload is not None:
            try:
                out = self.rpc(peer, "install_snapshot", payload)
            except Exception:
                return
            with self.mu:
                if out.get("term", 0) > self.current_term:
                    self._become_follower(out["term"])
                    return
                if self.role != "leader" or self.current_term != term:
                    return
                if out.get("success"):
                    self.match_index[peer] = max(
                        self.match_index.get(peer, 0), snap_index
                    )
                    self.next_index[peer] = snap_index + 1
            return
        with self.mu:
            if self.role != "leader" or self.current_term != term:
                return
            ni = max(self.next_index.get(peer, self.snap_index + 1), 1)
            if ni <= self.snap_index:
                return  # compacted meanwhile; next tick ships the snapshot
            prev_index = ni - 1
            prev_term = self._term_at(prev_index)
            entries = self.log[ni - self.snap_index - 1:]
            commit = self.commit_index
        try:
            out = self.rpc(peer, "append_entries", {
                "term": term, "leader_id": self.id,
                "prev_log_index": prev_index, "prev_log_term": prev_term,
                "entries": entries, "leader_commit": commit,
            })
        except Exception:
            return
        with self.mu:
            if out.get("term", 0) > self.current_term:
                self._become_follower(out["term"])
                return
            if self.role != "leader" or self.current_term != term:
                return
            if out.get("success"):
                match = prev_index + len(entries)
                self.match_index[peer] = max(
                    self.match_index.get(peer, 0), match
                )
                self.next_index[peer] = self.match_index[peer] + 1
                self._advance_commit()
            else:
                # back off; once next_index falls to the snapshot boundary
                # the next round ships InstallSnapshot instead
                self.next_index[peer] = max(
                    self.snap_index if self.snap_index > 0 else 1, ni - 1
                )

    def _advance_commit(self) -> None:
        last_index, _ = self._last_log()
        for n in range(last_index, self.commit_index, -1):
            e = self._entry(n)
            if e is None or e["term"] != self.current_term:
                continue
            count = 1 + sum(
                1 for p in self.peers if self.match_index.get(p, 0) >= n
            )
            if count * 2 > len(self.peers) + 1:
                self.commit_index = n
                self._persist()
                self._apply_committed()
                break

    # --- rpc handlers ---------------------------------------------------------
    def handle_request_vote(self, p: dict) -> dict:
        with self.mu:
            # non-members cannot be elected: a removed node that missed
            # its own removal keeps timing out, and without this gate its
            # inflated terms would repeatedly depose the real leader
            cand = p.get("candidate_id")
            if cand is not None and cand != self.id and cand not in self.peers:
                return {"term": self.current_term, "granted": False}
            # leader-lease check (hashicorp/raft CheckQuorum semantics): a
            # node that heard from a live leader recently refuses to join a
            # disruptive election — prevents term-inflation leadership flap
            if (
                p["term"] > self.current_term
                and self.role == "follower"
                and self.leader_id is not None
                and time.monotonic() - self._last_heartbeat
                < self.election_timeout[0]
            ):
                return {"term": self.current_term, "vote_granted": False}
            if p["term"] > self.current_term:
                self._become_follower(p["term"])
            granted = False
            if p["term"] == self.current_term and \
                    self.voted_for in (None, p["candidate_id"]):
                my_index, my_term = self._last_log()
                up_to_date = (
                    p["last_log_term"] > my_term
                    or (p["last_log_term"] == my_term
                        and p["last_log_index"] >= my_index)
                )
                if up_to_date:
                    granted = True
                    self.voted_for = p["candidate_id"]
                    self._last_heartbeat = time.monotonic()
                    self._persist()
            return {"term": self.current_term, "vote_granted": granted}

    def handle_append_entries(self, p: dict) -> dict:
        with self.mu:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self._last_heartbeat = time.monotonic()
            if p["term"] > self.current_term or self.role != "follower":
                self._become_follower(p["term"], p.get("leader_id"))
            self.leader_id = p.get("leader_id")
            prev_index = p["prev_log_index"]
            entries = p["entries"]
            if prev_index < self.snap_index:
                # our snapshot already covers part of this batch; everything
                # at or below snap_index is committed state, skip it
                entries = [e for e in entries if e["index"] > self.snap_index]
                prev_index = self.snap_index
            elif prev_index > 0 and self._term_at(prev_index) != p["prev_log_term"]:
                return {"term": self.current_term, "success": False}
            # append, truncating conflicts
            for entry in entries:
                existing = self._entry(entry["index"])
                if existing is not None and existing["term"] != entry["term"]:
                    del self.log[entry["index"] - self.snap_index - 1:]
                    existing = None
                if existing is None:
                    self.log.append(entry)
            if entries:
                self._persist()
            if p["leader_commit"] > self.commit_index:
                last_index, _ = self._last_log()
                self.commit_index = min(p["leader_commit"], last_index)
                self._apply_committed()
            return {"term": self.current_term, "success": True}

    def handle_install_snapshot(self, p: dict) -> dict:
        """Install a leader snapshot on a follower whose log is behind the
        leader's compacted prefix (raft InstallSnapshot RPC)."""
        with self.mu:
            if p["term"] < self.current_term:
                return {"term": self.current_term, "success": False}
            self._last_heartbeat = time.monotonic()
            if p["term"] > self.current_term or self.role != "follower":
                self._become_follower(p["term"], p.get("leader_id"))
            self.leader_id = p.get("leader_id")
            incl = p["last_included_index"]
            if incl <= self.last_applied:
                # already at or past this point; never rewind the state machine
                return {"term": self.current_term, "success": True}
            # retain any log suffix consistent with the snapshot, else discard
            if self._term_at(incl) == p["last_included_term"]:
                self.log = [e for e in self.log if e["index"] > incl]
            else:
                self.log = []
            self.snap_index = incl
            self.snap_term = p["last_included_term"]
            self.snap_state = p.get("state")
            if self.snap_state is not None and self.restore_fn is not None:
                self.restore_fn(self.snap_state)
            self.last_applied = incl
            self.commit_index = max(self.commit_index, incl)
            self._persist()
            self._apply_committed()
            return {"term": self.current_term, "success": True}

    # --- client API -----------------------------------------------------------
    def is_leader(self) -> bool:
        with self.mu:
            return self.role == "leader"

    def term(self) -> int:
        with self.mu:
            return self.current_term

    def leader(self) -> str | None:
        with self.mu:
            return self.leader_id if self.role != "leader" else self.id

    def add_peer(self, peer_url: str, timeout: float = 5.0):
        """Leader-side membership add, replicated through the log
        (`cluster.raft.add`)."""
        return self.propose({"type": "_raft_conf", "op": "add",
                             "peer": peer_url.rstrip("/")}, timeout)

    def remove_peer(self, peer_url: str, timeout: float = 5.0):
        return self.propose({"type": "_raft_conf", "op": "remove",
                             "peer": peer_url.rstrip("/")}, timeout)

    def propose(self, command: dict, timeout: float = 5.0):
        """Append via the leader; blocks until committed+applied; returns the
        apply_fn result. Raises NotLeader elsewhere."""
        with self.mu:
            if self.role != "leader":
                raise NotLeader(self.leader_id)
            index = self._last_log()[0] + 1
            self.log.append({
                "term": self.current_term, "index": index, "command": command,
            })
            self._persist()
            if not self.peers:  # single node: commit immediately
                self.commit_index = index
                self._persist()
                self._apply_committed()
        self._broadcast_heartbeats()
        deadline = time.monotonic() + timeout
        missing = object()
        with self.mu:
            while self.last_applied < index:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise TimeoutError(f"propose not committed in {timeout}s")
                # a demotion only aborts the wait if the entry can no
                # longer produce a result here — a self-removal conf entry
                # demotes while STILL applying (its result lands in
                # _apply_results because leadership is captured pre-apply)
                if self.role != "leader" and index not in self._apply_results:
                    raise NotLeader(self.leader_id)
                self._commit_cv.wait(min(remain, 0.05))
            result = self._apply_results.pop(index, missing)
        if result is missing:
            # stepped down between append and apply: the entry may have
            # committed under the new leader, but its result was discarded
            # (followers don't retain results) — surface the demotion rather
            # than returning a bogus None
            raise NotLeader(self.leader())
        if isinstance(result, Exception):
            raise result
        return result

    def status(self) -> dict:
        with self.mu:
            return {
                "id": self.id,
                "role": self.role,
                "term": self.current_term,
                "leader": self.leader_id if self.role != "leader" else self.id,
                "commit_index": self.commit_index,
                "log_length": len(self.log),
                "peers": self.peers,
            }
