"""Cross-cutting utilities (reference `weed/util`, `weed/glog`)."""
