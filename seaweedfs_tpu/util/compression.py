"""Chunk compression: gzip + zstd with compressability heuristics.

Behavioral port of `weed/util/compression.go`: uploads compress chunk data
when the mime/extension says it is worth it (`IsCompressableFileType`
compression.go:60-90) and the compressed form actually shrinks; reads
auto-detect by magic bytes (`IsGzippedData`, `IsZstdData`) and decompress.
zstd rides the `zstandard` package (the reference vendors klauspost/compress).
"""

from __future__ import annotations

import gzip

try:
    import zstandard as _zstd

    _ZSTD_C = _zstd.ZstdCompressor(level=3)
    _ZSTD_D = _zstd.ZstdDecompressor()
except Exception:  # pragma: no cover - zstd is baked into the image
    _zstd = None

GZIP_MAGIC = b"\x1f\x8b"
ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"

# mirror of compression.go:60-90's switch tables; .pdf counts as
# compressable both by extension and by mime, matching the reference's
# IsCompressableFileType (compression.go:121)
_UNCOMPRESSABLE_EXT = {
    ".zip", ".rar", ".gz", ".bz2", ".xz", ".zst", ".br",  # already compressed
}
_TEXT_EXT = {
    ".csv", ".txt", ".json", ".xml", ".html", ".htm", ".css", ".js", ".log",
    ".md", ".yaml", ".yml", ".toml", ".svg", ".conf", ".ini", ".py", ".go",
    ".java", ".c", ".cpp", ".h", ".rs", ".ts", ".sql", ".sh", ".pdf",
}
_UNCOMPRESSABLE_MIME_PREFIX = ("video/", "audio/", "image/")
_UNCOMPRESSABLE_MIME = {
    "application/zip", "application/gzip", "application/x-gzip",
    "application/zstd", "application/x-rar-compressed",
    "application/x-7z-compressed", "application/x-xz",
}
_COMPRESSABLE_MIME = {
    "application/json", "application/xml", "application/javascript",
    "application/x-javascript", "application/toml", "application/pdf",
}


def is_gzipped_data(data: bytes) -> bool:
    return data[:2] == GZIP_MAGIC


def is_zstd_data(data: bytes) -> bool:
    return data[:4] == ZSTD_MAGIC


def is_compressed(data: bytes) -> bool:
    return is_gzipped_data(data) or is_zstd_data(data)


def is_compressable_file_type(ext: str, mime: str) -> bool:
    """Heuristic from `compression.go:60-90`: compress text-ish content,
    skip media and archive formats."""
    ext = ext.lower()
    mime = mime.split(";")[0].strip().lower()
    if ext in _UNCOMPRESSABLE_EXT:
        return False
    if mime in _UNCOMPRESSABLE_MIME:
        return False
    if mime.startswith(_UNCOMPRESSABLE_MIME_PREFIX):
        return False
    if ext in _TEXT_EXT:
        return True
    if mime.startswith("text/"):
        return True
    return mime in _COMPRESSABLE_MIME


def gzip_data(data: bytes) -> bytes:
    return gzip.compress(data, compresslevel=3)


def zstd_data(data: bytes) -> bytes:
    if _zstd is None:  # pragma: no cover
        return gzip_data(data)
    return _ZSTD_C.compress(data)


def maybe_compress_data(data: bytes, mime: str = "", ext: str = "",
                        method: str = "gzip") -> tuple[bytes, bool]:
    """Compress when the type heuristic says yes AND it actually shrinks
    (`MaybeGzipData` semantics). Returns (payload, is_compressed)."""
    if len(data) < 128:
        return data, False
    if not is_compressable_file_type(ext, mime):
        return data, False
    packed = zstd_data(data) if method == "zstd" else gzip_data(data)
    if len(packed) >= len(data) * 9 // 10:
        return data, False
    return packed, True


def decompress_data(data: bytes) -> bytes:
    """Auto-detect gzip/zstd by magic; pass through raw data unchanged
    (`DecompressData`)."""
    if is_gzipped_data(data):
        return gzip.decompress(data)
    if is_zstd_data(data):
        if _zstd is None:  # pragma: no cover
            raise ValueError("zstd data but zstandard unavailable")
        return _ZSTD_D.decompress(data)
    return data
