"""Leveled logging in the glog style (`weed/glog/glog.go`).

`v(2).info(...)` logs only when the process verbosity is >= 2; errors and
warnings always log. Optional file output with size-based rotation
(MaxSize/MaxFileCount, `weed/weed.go:51-52`).
"""

from __future__ import annotations

import os
import sys
import threading
import time

_lock = threading.Lock()
_verbosity = int(os.environ.get("SEAWEEDFS_TPU_V", "0"))
_out = sys.stderr
_log_file: str | None = None
_max_size = 100 * 1024 * 1024
_max_files = 5


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def set_output_file(path: str, max_size: int = _max_size, max_files: int = 5) -> None:
    global _log_file, _max_size, _max_files
    _log_file = path
    _max_size = max_size
    _max_files = max_files


def _rotate() -> None:
    if _log_file is None:
        return
    try:
        if os.path.getsize(_log_file) < _max_size:
            return
    except OSError:
        return
    for i in range(_max_files - 1, 0, -1):
        src = f"{_log_file}.{i}" if i > 1 else _log_file
        dst = f"{_log_file}.{i + 1}" if i > 1 else f"{_log_file}.1"
        if os.path.exists(src):
            os.replace(src, dst)


def _emit(level: str, msg: str, args: tuple) -> None:
    if args:
        msg = msg % args
    line = (
        f"{level}{time.strftime('%m%d %H:%M:%S')} "
        f"{threading.get_ident() % 100000:05d} {msg}\n"
    )
    with _lock:
        if _log_file is not None:
            _rotate()
            with open(_log_file, "a") as f:
                f.write(line)
        else:
            _out.write(line)


def info(msg: str, *args) -> None:
    _emit("I", msg, args)


def warning(msg: str, *args) -> None:
    _emit("W", msg, args)


def error(msg: str, *args) -> None:
    _emit("E", msg, args)


class _V:
    def __init__(self, level: int) -> None:
        self.enabled = level <= _verbosity

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            _emit("I", msg, args)


def v(level: int) -> _V:
    return _V(level)
