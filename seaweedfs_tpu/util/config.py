"""TOML config discovery (`weed/util/config.go:40-60`).

`load_configuration("filer")` looks for filer.toml in ./, ~/.seaweedfs,
/usr/local/etc/seaweedfs, /etc/seaweedfs (viper search-path order) and
returns the parsed dict ({} when absent and not required).
"""

from __future__ import annotations

import os
try:
    import tomllib
except ModuleNotFoundError:  # py<3.11: same-format tomli fallback
    import tomli as tomllib

SEARCH_DIRS = [
    ".",
    os.path.expanduser("~/.seaweedfs"),
    "/usr/local/etc/seaweedfs",
    "/etc/seaweedfs",
]


def resolve_config_path(name: str) -> str | None:
    fname = name if name.endswith(".toml") else f"{name}.toml"
    for d in SEARCH_DIRS:
        cand = os.path.join(d, fname)
        if os.path.exists(cand):
            return cand
    return None


def load_configuration(name: str, required: bool = False) -> dict:
    path = resolve_config_path(name)
    if path is None:
        if required:
            raise FileNotFoundError(
                f"no {name}.toml found in {SEARCH_DIRS}"
            )
        return {}
    with open(path, "rb") as f:
        return tomllib.load(f)
