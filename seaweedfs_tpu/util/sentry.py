"""Sentry error reporting over the plain store-API protocol.

The reference links getsentry/sentry-go and initializes it from a DSN in
each long-running command (`go.mod: github.com/getsentry/sentry-go`).
Sentry's ingestion is just HTTP: POST a JSON event to
`{scheme}://{host}/api/{project}/store/` with an `X-Sentry-Auth` header
carrying the DSN's public key. That's implemented here directly —
`init_sentry(dsn)` hooks `sys.excepthook` and exposes
`capture_exception()` for servers' catch-all error paths.

Events are sent from a daemon thread so a slow/unreachable ingest host
never stalls a request path; failures are dropped silently (error
reporting must never become an error source).
"""

from __future__ import annotations

import json
import queue
import sys
import threading
import time
import traceback
import urllib.parse
import uuid

_state: dict = {"client": None}


class _SentryClient:
    def __init__(self, dsn: str, environment: str = "",
                 release: str = "") -> None:
        # DSN: {scheme}://{public_key}@{host}[:port]/{project_id}
        parsed = urllib.parse.urlparse(dsn)
        if not parsed.username or not parsed.path.strip("/"):
            raise ValueError(f"malformed sentry DSN")
        self.public_key = parsed.username
        self.project = parsed.path.strip("/")
        if not parsed.hostname:
            raise ValueError("sentry DSN has no host")
        netloc = parsed.hostname + (
            f":{parsed.port}" if parsed.port else ""
        )
        self.store_url = f"{parsed.scheme}://{netloc}/api/{self.project}/store/"
        self.environment = environment
        self.release = release
        self._q: queue.Queue = queue.Queue(maxsize=100)
        self._pending = 0           # queued + in-flight sends
        self._pending_mu = threading.Condition()
        threading.Thread(target=self._sender, daemon=True).start()

    def _auth_header(self) -> str:
        return (
            "Sentry sentry_version=7, sentry_client=seaweedfs-tpu/1.0, "
            f"sentry_key={self.public_key}"
        )

    def capture(self, exc: BaseException, extra: dict | None = None) -> None:
        frames = [
            {
                "filename": f.filename,
                "function": f.name,
                "lineno": f.lineno,
                "context_line": f.line,
            }
            for f in traceback.extract_tb(exc.__traceback__)
        ]
        event = {
            "event_id": uuid.uuid4().hex,
            "timestamp": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime()
            ),
            "platform": "python",
            "level": "error",
            "environment": self.environment or "production",
            "release": self.release,
            "exception": {
                "values": [
                    {
                        "type": type(exc).__name__,
                        "value": str(exc),
                        "stacktrace": {"frames": frames},
                    }
                ]
            },
            "extra": extra or {},
        }
        try:
            with self._pending_mu:
                self._q.put_nowait(event)
                self._pending += 1
        except queue.Full:
            pass  # shed load: reporting must not block or grow unbounded

    def _sender(self) -> None:  # pragma: no cover - daemon loop timing
        from seaweedfs_tpu.server.httpd import http_request

        while True:
            event = self._q.get()
            try:
                http_request(
                    "POST",
                    self.store_url,
                    json.dumps(event).encode(),
                    {
                        "Content-Type": "application/json",
                        "X-Sentry-Auth": self._auth_header(),
                    },
                    timeout=10,
                )
            except Exception:
                pass
            finally:
                with self._pending_mu:
                    self._pending -= 1
                    self._pending_mu.notify_all()

    def flush(self, timeout: float = 5.0) -> None:
        """Wait until queued AND in-flight events are sent (the excepthook
        depends on this covering the send itself, not just the queue)."""
        deadline = time.time() + timeout
        with self._pending_mu:
            while self._pending > 0:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._pending_mu.wait(remaining)


def init_sentry(dsn: str, environment: str = "", release: str = "") -> bool:
    """Install the reporter (reference: sentry.Init in each command's
    startup). Returns False when the DSN is empty/invalid."""
    if not dsn:
        return False
    try:
        client = _SentryClient(dsn, environment, release)
    except (ValueError, TypeError):
        return False
    _state["client"] = client
    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            client.capture(exc)
            client.flush(2.0)
        except Exception:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook
    return True


def capture_exception(exc: BaseException, **extra) -> None:
    """Report an exception if a client is configured; no-op otherwise —
    the hook servers call from their catch-all error paths."""
    client = _state.get("client")
    if client is not None:
        client.capture(exc, extra or None)
