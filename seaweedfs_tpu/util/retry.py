"""RetryPolicy: one exponential-backoff-with-jitter + deadline-budget
policy shared by every outbound HTTP hop.

Before this module each caller grew its own ad-hoc loop (wdclient tried
each holder once, the heartbeat rotated masters, replication fan-out
gave up on the first failure) and each picked its own — or no — timeout.
A degraded cluster turns those differences into behavior: the chaos
suite kills a holder under a read storm and the client-visible error
rate is exactly the retry policy. One policy, deterministic math
(`now=`/`sleep=`/`rng=` injectable), deadline as a hard budget so no
worker can hang forever regardless of how many attempts remain.

    policy = RetryPolicy(attempts=4, deadline=10.0)
    result = policy.call(do_request, retry_on=(IOError, OSError))

or drive the schedule by hand:

    start = now()
    for attempt in itertools.count():
        try: return fn()
        except IOError:
            delay = policy.delay(attempt)
            if not policy.should_retry(attempt + 1, start, now(), delay):
                raise
            sleep(delay)
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass

# the shared outbound-HTTP timeout default: generous enough for a slow
# admin verb, finite so no call can hang a worker forever (the audit
# rule: every outbound call either passes its own timeout or this one)
DEFAULT_TIMEOUT = 30.0


@dataclass(frozen=True)
class RetryPolicy:
    """attempts: total tries (1 = no retry). base/multiplier/max_delay:
    exponential backoff schedule. jitter: +/- fraction of each delay.
    deadline: wall-clock budget across ALL attempts including their
    backoff sleeps — the hard bound."""

    attempts: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.5
    deadline: float = DEFAULT_TIMEOUT

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        """Backoff before retry number `attempt` (0-based: the delay
        after the first failure is delay(0))."""
        d = min(self.max_delay, self.base_delay * (self.multiplier ** attempt))
        if self.jitter > 0:
            r = (rng or random).random()
            d *= 1.0 + self.jitter * (2.0 * r - 1.0)
        return max(0.0, d)

    def remaining(self, start: float, now: float) -> float:
        """Deadline budget left; clamped at 0."""
        return max(0.0, self.deadline - (now - start))

    def should_retry(self, tried: int, start: float, now: float,
                     next_delay: float = 0.0) -> bool:
        """True when another attempt fits: tries left AND the budget
        still covers the backoff (an attempt that would start past the
        deadline is a hang with extra steps)."""
        if tried >= self.attempts:
            return False
        return self.remaining(start, now) > next_delay

    def call(self, fn, retry_on=(IOError, OSError), now=time.monotonic,
             sleep=time.sleep, rng: random.Random | None = None):
        """Run fn() under this policy. fn gets no args (close over what
        you need); only `retry_on` exceptions retry, everything else —
        and the final failure — propagates."""
        start = now()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on:
                d = self.delay(attempt, rng)
                attempt += 1
                if not self.should_retry(attempt, start, now(), d):
                    raise
                sleep(d)


# module-wide defaults: data-plane reads retry fast and give up inside a
# request budget; control-plane/admin calls get more patience
READ_POLICY = RetryPolicy(attempts=4, base_delay=0.05, max_delay=1.0,
                          deadline=15.0)
ADMIN_POLICY = RetryPolicy(attempts=3, base_delay=0.2, max_delay=5.0,
                           deadline=60.0)
