"""AES-256-GCM chunk encryption (`weed/util/cipher.go`).

The reference encrypts each chunk with a fresh random key when the filer
runs with `-encryptVolumeData`; the per-chunk key lives only in filer
metadata (FileChunk.cipher_key), so volume servers store ciphertext they
cannot read. Same layout here: 12-byte nonce || ciphertext || 16-byte tag,
key is 32 random bytes. Hardware AES stays on CPU — not a TPU target
(SURVEY.md §2.2 item 5).
"""

from __future__ import annotations

import os

try:  # gated: ciphered filers need it, plain filers must import fine
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - environment-dependent
    AESGCM = None

KEY_SIZE = 32
NONCE_SIZE = 12


def available() -> bool:
    return AESGCM is not None


def _require() -> None:
    if AESGCM is None:
        raise RuntimeError(
            "chunk encryption needs the 'cryptography' package, which is"
            " not installed; run the filer without -encryptVolumeData"
        )


def gen_cipher_key() -> bytes:
    return os.urandom(KEY_SIZE)


def encrypt(data: bytes, key: bytes | None = None) -> tuple[bytes, bytes]:
    """Returns (nonce||ciphertext||tag, key). Fresh key per chunk when none
    given (`Encrypt` cipher.go)."""
    _require()
    if key is None:
        key = gen_cipher_key()
    nonce = os.urandom(NONCE_SIZE)
    ct = AESGCM(key).encrypt(nonce, data, None)
    return nonce + ct, key


def decrypt(payload: bytes, key: bytes) -> bytes:
    _require()
    if len(payload) < NONCE_SIZE:
        raise ValueError("cipher payload too short")
    nonce, ct = payload[:NONCE_SIZE], payload[NONCE_SIZE:]
    return AESGCM(key).decrypt(nonce, ct, None)
