"""AES-256-GCM chunk encryption (`weed/util/cipher.go`).

The reference encrypts each chunk with a fresh random key when the filer
runs with `-encryptVolumeData`; the per-chunk key lives only in filer
metadata (FileChunk.cipher_key), so volume servers store ciphertext they
cannot read. Same layout here: 12-byte nonce || ciphertext || 16-byte tag,
key is 32 random bytes. Hardware AES stays on CPU — not a TPU target
(SURVEY.md §2.2 item 5).
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

KEY_SIZE = 32
NONCE_SIZE = 12


def gen_cipher_key() -> bytes:
    return os.urandom(KEY_SIZE)


def encrypt(data: bytes, key: bytes | None = None) -> tuple[bytes, bytes]:
    """Returns (nonce||ciphertext||tag, key). Fresh key per chunk when none
    given (`Encrypt` cipher.go)."""
    if key is None:
        key = gen_cipher_key()
    nonce = os.urandom(NONCE_SIZE)
    ct = AESGCM(key).encrypt(nonce, data, None)
    return nonce + ct, key


def decrypt(payload: bytes, key: bytes) -> bytes:
    if len(payload) < NONCE_SIZE:
        raise ValueError("cipher payload too short")
    nonce, ct = payload[:NONCE_SIZE], payload[NONCE_SIZE:]
    return AESGCM(key).decrypt(nonce, ct, None)
