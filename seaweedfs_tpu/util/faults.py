"""Named fault-point registry: inject errors, latency, torn writes,
silent bit flips, full disks and network partitions at the cluster's
hot seams.

Every repair path this repo grew (PR 5's detect->plan->heal, PR 8's
online EC) was only ever tested by *polite* loss — admin APIs deleting
shards. Real outages happen mid-request: a holder dies under a read
storm, a parity write tears, a heartbeat partitions. This module is the
cluster-wide switchboard for injecting exactly those faults
(arXiv:1709.05365 measures degraded-mode behavior as the dominant tail
in online-coded arrays; you cannot measure what you cannot inject).

Design constraints, in order:

  1. **Disarmed is free.** A fault point on the needle-read path runs on
     every data-plane request; the disarmed check is one attribute load
     and a None test — no dict lookup, no allocation, no closure. The
     tier-1 suite asserts this with a hot-loop guard.
  2. **Points are declared, not discovered.** `ALL_POINTS` is the
     closed set of seam names; `register()` rejects anything else, so a
     typo'd seam cannot silently never fire, and
     tools/check_metric_names.py can lint that every declared point is
     exercised by the chaos suite.
  3. **Per-process arming.** In production each node is its own process
     (`-faults` flag, `POST /debug/faults`); in-process test clusters
     share one registry, so a spec may carry `key=` to scope a fault to
     one server's seam invocations (the seam passes its identity).

Seam API:

    _FP = faults.register("volume.read.dat")   # module import time
    ...
    _FP.hit()                # raise/sleep per the armed spec, or no-op
    data = _FP.mangle(data)  # torn-write seams: maybe truncate
    spec = _FP.draw()        # custom seams: count the injection, act
                             # themselves (e.g. tear a parity file)

Injections count into SeaweedFS_faults_injected_total{point,mode}.
"""

from __future__ import annotations

import errno
import random
import threading
import time
from dataclasses import dataclass

# The closed set of fault-point names (dotted lowercase, linted by
# tools/check_metric_names.py; each must be exercised by tests/test_chaos.py).
ALL_POINTS = (
    "volume.read.dat",        # needle read from the .dat
    "volume.read.idx",        # needle-map lookup on the read path
    "volume.write.dat",       # needle append to the .dat
    "volume.ec.shard.read",   # sealed EC shard pread
    "volume.ec.parity.write", # online-EC parity emit (torn = tear the file)
    "volume.heartbeat.send",  # volume server -> master heartbeat POST
    "master.assign",          # /dir/assign handler
    "master.lookup",          # /dir/lookup handler
    "filer.chunk.read",       # filer -> volume chunk relay (wdclient.fetch)
    "volume.replicate.fanout",# synchronous replica fan-out
    "volume.fastlane.drain",  # engine event drain (ABI hook when present)
    "repair.partial_fetch",   # pipelined-rebuild partial-sum hop (/admin/ec/
                              # partial): error = a chain hop dies mid-rebuild
)

# `corrupt` is the silent-damage mode the scrub subsystem exists to
# catch: deterministic in-place bit flips on the payload at the
# .dat/shard read-write byte seams (mangle()), invisible to the writer —
# only a CRC/parity check can notice. rate/count/key/volume scoping
# applies like every other mode.
MODES = ("error", "latency", "torn", "disk_full", "partition", "corrupt")


class FaultInjected(IOError):
    """An `error`-mode fault fired. Derives from IOError so seams that
    already treat IO failures as recoverable treat injections the same
    way — the whole point is exercising the real failure handling."""


class FaultPartition(ConnectionError):
    """A `partition`-mode fault fired: the peer is unreachable."""


@dataclass
class FaultSpec:
    """One armed fault. `count` < 0 means unlimited; a positive count
    decrements per firing and auto-disarms at zero. `rate` in (0, 1]
    fires probabilistically. `key` scopes the fault to seam invocations
    passing the same discriminator (in-process multi-server tests)."""

    mode: str
    rate: float = 1.0
    ms: float = 0.0       # latency mode: injected delay
    frac: float = 0.5     # torn mode: fraction of the payload DROPPED
    count: int = -1       # firings remaining; <0 = unlimited
    key: str = ""         # scope discriminator ("" = every invocation)
    after: int = 0        # skip the first N would-fire draws (onset
                          # delay: "die on the 4th chunk, not the 1st")

    def to_dict(self) -> dict:
        return {"mode": self.mode, "rate": self.rate, "ms": self.ms,
                "frac": self.frac, "count": self.count, "key": self.key,
                "after": self.after}


_metric = None


def _injected_counter():
    global _metric
    if _metric is None:
        from seaweedfs_tpu.stats import default_registry

        _metric = default_registry().counter(
            "SeaweedFS_faults_injected_total",
            "fault injections fired, by point and mode",
            ("point", "mode"),
        )
    return _metric


class FaultPoint:
    """One named seam. `spec` is None when disarmed — the hot-path check
    is a single attribute load (__slots__, no dict walk)."""

    __slots__ = ("name", "spec", "fired")

    def __init__(self, name: str) -> None:
        self.name = name
        self.spec: FaultSpec | None = None
        self.fired = 0

    # --- hot path -----------------------------------------------------------
    def draw(self, key: str | None = None,
             volume: int | None = None) -> FaultSpec | None:
        """Decide whether the armed fault fires for this invocation and
        count it; returns the spec (caller acts) or None. Seams with
        custom damage (torn parity) use this directly. `volume` is a
        pure correlation key for the flight-recorder journal — seams
        that know which volume they are damaging pass it so
        `cluster.why <volume>` can show the injection in the timeline."""
        spec = self.spec
        if spec is None:
            return None
        return self._draw_slow(spec, key, volume)

    def _draw_slow(self, spec: FaultSpec, key: str | None,
                   volume: int | None = None) -> FaultSpec | None:
        if spec.key and key is not None and key != spec.key:
            return None
        if spec.rate < 1.0 and random.random() >= spec.rate:
            return None
        with _lock:
            if self.spec is not spec:  # disarmed/re-armed under us
                return None
            if spec.after > 0:  # onset delay: let the first N draws pass
                spec.after -= 1
                return None
            if spec.count == 0:
                return None
            if spec.count > 0:
                spec.count -= 1
                if spec.count == 0:
                    self.spec = None
            self.fired += 1
        _injected_counter().labels(self.name, spec.mode).inc()
        # flight-recorder journal (cold path: only a FIRING fault pays) —
        # emitted inside the request span when one is active, so
        # cluster.why joins the injection to the read it degraded
        from seaweedfs_tpu.stats import events as _events

        _events.emit("fault_injected", point=self.name, mode=spec.mode,
                     key=key or "", volume=volume)
        return spec

    def hit(self, key: str | None = None, volume: int | None = None) -> None:
        """The standard seam check: no-op disarmed; armed, acts per mode
        (error/partition/disk_full raise, latency sleeps; torn and
        corrupt are no-ops here — use mangle() at the byte seam, so a
        seam calling both never double-counts one firing)."""
        spec = self.spec
        if spec is None or spec.mode in ("torn", "corrupt"):
            return
        spec = self.draw(key, volume)
        if spec is not None:
            act(self.name, spec)

    def mangle(self, data: bytes, key: str | None = None,
               volume: int | None = None) -> bytes:
        """Byte seams: `torn` truncates the payload by `frac`; `corrupt`
        flips every bit of ONE byte at position frac*len — deterministic
        silent damage a CRC must catch (the writer never notices). Every
        other mode is handled by hit()."""
        spec = self.spec
        if spec is None or spec.mode not in ("torn", "corrupt"):
            return data
        spec = self.draw(key, volume)
        if spec is None:
            return data
        if spec.mode == "corrupt":
            if not data:
                return data
            pos = min(len(data) - 1, int(len(data) * spec.frac))
            out = bytearray(data)
            out[pos] ^= 0xFF
            return bytes(out)
        keep = max(0, int(len(data) * (1.0 - spec.frac)))
        return data[:keep]


def act(name: str, spec: FaultSpec) -> None:
    """Perform a drawn spec's generic behavior (raise/sleep)."""
    mode = spec.mode
    if mode == "latency":
        time.sleep(spec.ms / 1000.0)
    elif mode == "error":
        raise FaultInjected(f"injected fault at {name}")
    elif mode == "disk_full":
        raise OSError(errno.ENOSPC, f"injected disk-full at {name}")
    elif mode == "partition":
        raise FaultPartition(f"injected partition at {name}")
    # torn: byte-level, handled at the seam via mangle()/draw()


_lock = threading.Lock()
_points: dict[str, FaultPoint] = {}

# Runtime-arming gate for the HTTP surface: every other debug route is
# read-only, but POST /debug/faults can tear writes — so it 403s unless
# the operator opted the PROCESS in (the -faults flag, even bare, or
# SEAWEEDFS_TPU_FAULTS=1). In-process callers (tests, the flag parser)
# use arm() directly and are unaffected.
_enabled = False


def enable() -> None:
    global _enabled
    _enabled = True


def runtime_arming_enabled() -> bool:
    import os

    return _enabled or os.environ.get("SEAWEEDFS_TPU_FAULTS") == "1"


def register(name: str) -> FaultPoint:
    """Module-import-time seam registration. Idempotent; the name must
    be declared in ALL_POINTS (a seam nobody can lint is a seam nobody
    tests)."""
    if name not in ALL_POINTS:
        raise ValueError(f"undeclared fault point {name!r}"
                         f" (add it to faults.ALL_POINTS)")
    with _lock:
        p = _points.get(name)
        if p is None:
            p = _points[name] = FaultPoint(name)
        return p


def point(name: str) -> FaultPoint:
    """Lookup-or-register — the arming side's handle."""
    return register(name)


def registered_points() -> list[str]:
    with _lock:
        return sorted(_points)


def arm(name: str, mode: str, rate: float = 1.0, ms: float = 0.0,
        frac: float = 0.5, count: int = -1, key: str = "",
        after: int = 0) -> FaultSpec:
    """Arm one point. Validates the mode and numeric ranges; replaces
    any existing spec on the point."""
    if mode not in MODES:
        raise ValueError(f"unknown fault mode {mode!r} (one of {MODES})")
    rate = float(rate)
    ms = float(ms)
    frac = float(frac)
    count = int(count)
    after = int(after)
    if not (0.0 < rate <= 1.0):
        raise ValueError(f"rate {rate} not in (0, 1]")
    if ms < 0 or not (0.0 < frac <= 1.0) or ms != ms:
        raise ValueError(f"bad latency/frac ({ms}, {frac})")
    if after < 0:
        raise ValueError(f"after {after} < 0")
    spec = FaultSpec(mode=mode, rate=rate, ms=ms, frac=frac, count=count,
                     key=key, after=after)
    p = point(name)
    with _lock:
        p.spec = spec
    return spec


def disarm(name: str) -> bool:
    """Disarm one point; True if it was armed."""
    p = point(name)
    with _lock:
        was = p.spec is not None
        p.spec = None
    return was


def disarm_all() -> int:
    """Back to the zero-injection steady state; returns how many points
    were armed."""
    n = 0
    with _lock:
        for p in _points.values():
            if p.spec is not None:
                p.spec = None
                n += 1
    return n


def armed() -> dict[str, FaultSpec]:
    with _lock:
        return {n: p.spec for n, p in _points.items() if p.spec is not None}


def snapshot() -> list[dict]:
    """Full state for /debug/faults and cluster.faults -list."""
    with _lock:
        return [
            {"point": n, "fired": p.fired,
             "armed": p.spec.to_dict() if p.spec is not None else None}
            for n, p in sorted(_points.items())
        ]


def arm_from_spec(text: str) -> list[str]:
    """Parse the `-faults` flag grammar and arm each entry:

        point=mode[:k=v[,k=v...]][;point=mode...]

    e.g. `-faults "volume.read.dat=error:rate=0.5;master.assign=latency:ms=20"`.
    Returns the armed point names; raises ValueError on any bad entry
    (a half-armed process would lie about what it injects)."""
    out: list[str] = []
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        name, _, rest = entry.partition("=")
        mode, _, opts_s = rest.partition(":")
        name, mode = name.strip(), mode.strip()
        if not mode:
            raise ValueError(f"fault spec {entry!r}: missing =mode")
        opts: dict = {}
        for kv in opts_s.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            if k not in ("rate", "ms", "frac", "count", "key", "after"):
                raise ValueError(f"fault spec {entry!r}: unknown option {k!r}")
            opts[k] = v if k == "key" else float(v)
        for k in ("count", "after"):
            if k in opts:
                opts[k] = int(opts[k])
        arm(name, mode, **opts)
        out.append(name)
    return out
