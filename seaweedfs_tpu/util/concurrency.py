"""Concurrency/IO helpers: bounded executors, buffer pools, retry.

Mirrors `weed/util`'s LimitedConcurrentExecutor, bytes pools, and
`retry.go`'s Retry/RetryForever backoff loop.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, TypeVar

T = TypeVar("T")


class LimitedConcurrentExecutor:
    """At most `limit` tasks in flight; submit blocks when full
    (`weed/util/limited_executor.go`)."""

    def __init__(self, limit: int) -> None:
        self._pool = ThreadPoolExecutor(max_workers=limit)
        self._sem = threading.Semaphore(limit)

    def execute(self, fn: Callable[..., T], *args, **kwargs) -> Future:
        self._sem.acquire()

        def run():
            try:
                return fn(*args, **kwargs)
            finally:
                self._sem.release()

        return self._pool.submit(run)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)


class BytesBufferPool:
    """Reusable fixed-size buffers for the upload fan-out (the reference
    bounds in-flight chunk buffers at 4, `filer_server_handlers_write_upload.go:52`)."""

    def __init__(self, size: int, count: int) -> None:
        self.size = size
        self._free: list[bytearray] = [bytearray(size) for _ in range(count)]
        self._cond = threading.Condition()

    def acquire(self) -> bytearray:
        with self._cond:
            while not self._free:
                self._cond.wait()
            return self._free.pop()

    def release(self, buf: bytearray) -> None:
        with self._cond:
            self._free.append(buf)
            self._cond.notify()


def retry(name: str, fn: Callable[[], T], *, attempts: int = 3,
          base_delay: float = 0.05, max_delay: float = 2.0,
          retriable: Callable[[Exception], bool] | None = None) -> T:
    """`util.Retry`: exponential backoff, re-raise the last error."""
    delay = base_delay
    last: Exception | None = None
    for _ in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - mirror Retry's catch-all
            if retriable is not None and not retriable(e):
                raise
            last = e
            time.sleep(delay)
            delay = min(delay * 2, max_delay)
    assert last is not None
    raise last
