"""In-memory append log with time-based flush — the filer's metadata event
pipe (reference: `weed/util/log_buffer/log_buffer.go:30`).

Entries are (ts_ns, payload bytes). The buffer keeps a bounded in-memory
window; when it exceeds `flush_bytes` or `flush_interval` a flush function
persists the batch (the filer writes dated segment files under
`/topics/.system/log/...`, `weed/filer/filer_notify.go:62`). Readers pull
from the in-memory window when their start timestamp is inside it and fall
back to the flushed segments otherwise (ReadFromBuffer semantics).
"""

from __future__ import annotations

import threading
import time
from typing import Callable


class LogBuffer:
    def __init__(
        self,
        flush_fn: Callable[[int, int, list[tuple[int, bytes]]], None] | None = None,
        flush_bytes: int = 4 * 1024 * 1024,
        flush_interval: float = 2.0,
        keep: int = 10_000,
    ) -> None:
        self._entries: list[tuple[int, bytes]] = []  # sorted by ts_ns
        self._bytes = 0
        self._lock = threading.Condition()
        # serializes flushers; flush_fn runs OUTSIDE _lock — it may re-enter
        # locks held by appenders (the filer writes segments through its own
        # store), so nesting it under _lock would be an AB-BA deadlock
        self._flush_mutex = threading.Lock()
        # appenders must not flush synchronously either: an appender may hold
        # the filer's entry lock, and flush_fn (segment write → _insert_quiet)
        # takes that same lock — appender(filer lock → _flush_mutex) vs
        # flusher(_flush_mutex → filer lock) deadlocks. Byte-threshold flushes
        # instead wake the flusher thread early via this event.
        self._flush_wake = threading.Event()
        self._flush_fn = flush_fn
        self._flush_bytes = flush_bytes
        self._flush_interval = flush_interval
        self._keep = keep
        self._flushed_until_ns = 0  # everything <= this ts has been flushed
        self._dropped_until_ns = 0  # everything <= this ts left the window
        self._last_ts = 0
        self._closed = False
        self._flusher: threading.Thread | None = None
        if flush_fn is not None and flush_interval > 0:
            self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
            self._flusher.start()

    # --- write ------------------------------------------------------------------
    def append(self, payload: bytes, ts_ns: int | None = None) -> int:
        return self.append_with(lambda ts: payload, ts_ns)

    def append_with(
        self, payload_fn: Callable[[int], bytes], ts_ns: int | None = None
    ) -> int:
        """Append with the payload built from the FINAL timestamp — callers
        that embed ts in the payload stay consistent with the monotonic bump."""
        with self._lock:
            ts = ts_ns or time.time_ns()
            if ts <= self._last_ts:
                ts = self._last_ts + 1  # strictly monotonic, ties broken by +1ns
            self._last_ts = ts
            payload = payload_fn(ts)
            self._entries.append((ts, payload))
            self._bytes += len(payload)
            self._lock.notify_all()
            need_flush = (
                self._flush_fn is not None and self._bytes >= self._flush_bytes
            )
        if need_flush:
            if self._flusher is not None:
                self._flush_wake.set()
            else:
                self.flush()
        return ts

    def flush(self) -> None:
        if self._flush_fn is None:
            return
        with self._flush_mutex:
            with self._lock:
                batch = [
                    (ts, p) for ts, p in self._entries
                    if ts > self._flushed_until_ns
                ]
            if not batch:
                return
            self._flush_fn(batch[0][0], batch[-1][0], batch)
            with self._lock:
                self._flushed_until_ns = batch[-1][0]
                # trim the in-memory window but keep a tail for fast readers
                if len(self._entries) > self._keep:
                    dropped = self._entries[: -self._keep]
                    self._bytes -= sum(len(p) for _, p in dropped)
                    self._entries = self._entries[-self._keep :]
                    self._dropped_until_ns = dropped[-1][0]

    def _flush_loop(self) -> None:
        while not self._closed:
            self._flush_wake.wait(self._flush_interval)
            self._flush_wake.clear()
            try:
                self.flush()
            except Exception:
                pass

    def close(self) -> None:
        self._closed = True
        if self._flush_fn is not None:
            self.flush()

    # --- read -------------------------------------------------------------------
    @property
    def earliest_ts_ns(self) -> int:
        with self._lock:
            return self._entries[0][0] if self._entries else 0

    @property
    def latest_ts_ns(self) -> int:
        with self._lock:
            return self._last_ts

    def read_since(
        self, ts_ns: int, limit: int = 1 << 31
    ) -> tuple[list[tuple[int, bytes]], bool]:
        """Entries with ts > ts_ns. Returns (batch, resumable): resumable is
        False when ts_ns predates the in-memory window AND data was flushed —
        the caller must read the flushed segments first."""
        with self._lock:
            return self._read_since_locked(ts_ns, limit)

    def wait_since(
        self, ts_ns: int, timeout: float, limit: int = 1 << 31
    ) -> tuple[list[tuple[int, bytes]], bool]:
        """Long-poll read: block until an entry newer than ts_ns arrives or
        timeout elapses."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                batch, ok = self._read_since_locked(ts_ns, limit)
                if batch or not ok:
                    return batch, ok
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], True
                self._lock.wait(remaining)

    def _read_since_locked(self, ts_ns, limit):
        # resumable iff no entry in (ts_ns, now] has been trimmed from memory
        if ts_ns < self._dropped_until_ns:
            return [], False
        return [(t, p) for t, p in self._entries if t > ts_ns][:limit], True
