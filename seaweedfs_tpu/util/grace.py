"""Graceful shutdown hooks + profiling (`weed/util/grace/`).

`on_interrupt` registers cleanup callbacks fired on SIGINT/SIGTERM (and at
interpreter exit); `setup_profiling` mirrors `pprof.go:11` — start a CPU
profile (cProfile) and dump stats + a heap snapshot (tracemalloc) on exit.
"""

from __future__ import annotations

import atexit
import cProfile
import signal
import threading
from typing import Callable

_hooks: list[Callable[[], None]] = []
_lock = threading.Lock()
_installed = False


def _run_hooks(*_args) -> None:
    with _lock:
        hooks, _hooks[:] = _hooks[:], []
    for h in reversed(hooks):
        try:
            h()
        except Exception:
            pass


def _install() -> None:
    global _installed
    if _installed:
        return
    _installed = True
    atexit.register(_run_hooks)
    if threading.current_thread() is threading.main_thread():
        for sig in (signal.SIGINT, signal.SIGTERM):
            prev = signal.getsignal(sig)

            def handler(signum, frame, prev=prev):
                _run_hooks()
                if callable(prev):
                    prev(signum, frame)
                else:
                    raise SystemExit(128 + signum)

            signal.signal(sig, handler)


def on_interrupt(fn: Callable[[], None]) -> None:
    _install()
    with _lock:
        _hooks.append(fn)


def setup_profiling(cpu_profile: str | None = None,
                    mem_profile: str | None = None) -> None:
    """`grace.SetupProfiling`: cpu → cProfile dump at exit; mem →
    tracemalloc snapshot at exit."""
    if cpu_profile:
        prof = cProfile.Profile()
        prof.enable()

        def dump_cpu():
            prof.disable()
            prof.dump_stats(cpu_profile)

        on_interrupt(dump_cpu)
    if mem_profile:
        import tracemalloc

        tracemalloc.start()

        def dump_mem():
            snap = tracemalloc.take_snapshot()
            with open(mem_profile, "w") as f:
                for stat in snap.statistics("lineno")[:100]:
                    f.write(str(stat) + "\n")

        on_interrupt(dump_mem)
